"""Concurrency rules — unlocked shared-state writes in thread-backed
classes and lock-order inversions.

The serving engine, frontend and broker all follow one pattern: a class
spawns ``threading.Thread(target=self._run)`` and the rest of its methods
are called from other threads. Attributes touched on **both** sides of
that boundary are shared state; writes to them must hold the class's
lock. The rule reconstructs the thread-reachable method set from the AST
(entry = any ``Thread(target=self.X)``, closure over ``self.Y()`` calls)
and flags cross-boundary writes that are not under a ``with self.*lock``
— thread-confined attributes (written and read only inside the thread's
own call tree) are deliberately not flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from analytics_zoo_tpu.analysis.core import (
    FileContext, Finding, Rule, ancestors, register,
)

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

#: attribute-name fragments that identify a lock-ish context manager
_LOCKISH = ("lock", "cv", "cond", "mutex", "sem")


def _is_lockish_ctx(expr: ast.AST) -> bool:
    """``with self._lock:`` / ``with state.cv:`` — the guard we accept."""
    cur = expr
    while isinstance(cur, ast.Call):
        cur = cur.func
    if isinstance(cur, ast.Attribute):
        return any(m in cur.attr.lower() for m in _LOCKISH)
    if isinstance(cur, ast.Name):
        return any(m in cur.id.lower() for m in _LOCKISH)
    return False


def _under_lock(node: ast.AST) -> bool:
    for a in ancestors(node):
        if isinstance(a, ast.With) and any(
                _is_lockish_ctx(item.context_expr) for item in a.items):
            return True
    return False


def _lock_name(expr: ast.AST) -> str:
    try:
        return ast.unparse(expr)
    except Exception:
        return "<lock>"


def _self_attr(node: ast.AST):
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _ClassInfo:
    """Per-class maps a file rule needs: method bodies, self-call edges,
    thread-target entry methods, per-method self-attribute reads/writes."""

    def __init__(self, cls: ast.ClassDef, ctx: FileContext):
        self.cls = cls
        self.methods: Dict[str, ast.AST] = {
            n.name: n for n in cls.body if isinstance(n, _FUNCS)}
        self.entries: Set[str] = set()
        self.calls: Dict[str, Set[str]] = {}
        self.writes: Dict[str, List[ast.Attribute]] = {}
        self.reads: Dict[str, Set[str]] = {}
        for name, fn in self.methods.items():
            calls: Set[str] = set()
            writes: List[ast.Attribute] = []
            reads: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = _self_attr(node.func)
                    if callee:
                        calls.add(callee)
                    if self._thread_target(ctx, node):
                        tgt = self._target_method(node)
                        if tgt:
                            self.entries.add(tgt)
                attr = _self_attr(node)
                if attr is not None:
                    # AugAssign targets also carry Store ctx in py3.8+
                    if isinstance(node.ctx, ast.Store):
                        writes.append(node)
                    else:
                        reads.add(attr)
            self.calls[name] = calls
            self.writes[name] = writes
            self.reads[name] = reads

    @staticmethod
    def _thread_target(ctx: FileContext, call: ast.Call) -> bool:
        name = ctx.imports.resolve(call.func)
        return bool(name) and name.split(".")[-1] == "Thread"

    @staticmethod
    def _target_method(call: ast.Call):
        for kw in call.keywords:
            if kw.arg == "target":
                return _self_attr(kw.value)
        return None

    def reachable(self) -> Set[str]:
        """Methods the spawned thread can execute: closure of the entry
        set over ``self.X()`` edges."""
        seen: Set[str] = set()
        stack = [e for e in self.entries if e in self.methods]
        while stack:
            m = stack.pop()
            if m in seen:
                continue
            seen.add(m)
            stack.extend(c for c in self.calls.get(m, ())
                         if c in self.methods and c not in seen)
        return seen


@register
class EngineUnlockedWrite(Rule):
    """Unlocked write to an attribute shared across a thread boundary.

    In a class that spawns ``Thread(target=self.X)``, an attribute both
    (a) written inside the thread's reachable call tree and (b) touched
    by outside methods — or vice versa — is shared state. Every such
    write must sit under ``with self.<lock>:``. ``__init__`` is exempt
    (runs before the thread exists)."""

    id = "engine-unlocked-write"
    description = "cross-thread attribute write without a lock"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        for cls in ctx.walk():
            if not isinstance(cls, ast.ClassDef):
                continue
            info = _ClassInfo(cls, ctx)
            if not info.entries:
                continue
            reach = info.reachable()
            outside = [m for m in info.methods
                       if m not in reach and m != "__init__"]
            touched_outside: Set[str] = set()
            for m in outside:
                touched_outside |= info.reads[m]
                touched_outside |= {_self_attr(w) for w in info.writes[m]}
            touched_inside: Set[str] = set()
            for m in reach:
                touched_inside |= info.reads[m]
                touched_inside |= {_self_attr(w) for w in info.writes[m]}
            shared = touched_outside & touched_inside
            for side, methods in (("thread", reach), ("caller", outside)):
                for m in methods:
                    for w in info.writes[m]:
                        attr = _self_attr(w)
                        if attr in shared and not _under_lock(w):
                            yield Finding(
                                self.id, ctx.path, w.lineno, w.col_offset,
                                f"self.{attr} is written in "
                                f"{cls.name}.{m} ({side} side) and "
                                "touched across the thread boundary "
                                "without holding a lock — wrap the write "
                                "in `with self._lock:` (or confine the "
                                "attribute to one thread)")


@register
class LockOrder(Rule):
    """Inconsistent nested lock acquisition order within one file.

    ``with A: with B:`` in one place and ``with B: with A:`` in another
    is the classic deadlock; the rule records every nested (outer, inner)
    lock-attribute pair and flags the inversion where the second order
    appears."""

    id = "lock-order"
    description = "nested locks acquired in both orders"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        pairs: Dict[Tuple[str, str], ast.With] = {}
        for node in ctx.walk():
            if not isinstance(node, ast.With):
                continue
            inner = [i.context_expr for i in node.items
                     if _is_lockish_ctx(i.context_expr)]
            if not inner:
                continue
            outer = []
            for a in ancestors(node):
                if isinstance(a, ast.With):
                    outer.extend(i.context_expr for i in a.items
                                 if _is_lockish_ctx(i.context_expr))
            for o in outer:
                for i in inner:
                    pairs.setdefault(
                        (_lock_name(o), _lock_name(i)), node)
        for (o, i), node in pairs.items():
            if o != i and (i, o) in pairs:
                rev = pairs[(i, o)]
                if (node.lineno, o) > (rev.lineno, i):
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        f"locks `{i}` → `{o}` here but `{o}` → `{i}` at "
                        f"line {rev.lineno} — pick one global order to "
                        "avoid an ABBA deadlock")
