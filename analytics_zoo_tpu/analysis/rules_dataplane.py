"""Data-plane rules — row-at-a-time pandas in the shard transform layer.

The Flare argument (PAPERS.md 1703.08219): an interpreted per-row data
plane dominates end-to-end recsys time, so the Friesian transforms were
rewritten as fixed-width numpy kernels (friesian/feature/table.py). This
rule keeps them that way: a ``Series.map(lambda ...)`` or
``DataFrame.apply(..., axis=1)`` in ``analytics_zoo_tpu/data/`` or a
``friesian/`` package re-introduces a Python call per row. The legacy
``ZOO_DATA_VECTORIZE=0`` bodies are baselined (dev/zoolint-baseline.json);
the sanctioned row-wise seam is ``transform_python_udf``, whose UDF arrives
as a parameter, not a lambda.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from analytics_zoo_tpu.analysis.core import (
    FileContext, Finding, Rule, ancestors, register,
)

#: path segments that mark the data plane (matches both the shipped
#: ``analytics_zoo_tpu/data``/``friesian`` trees and test fixtures)
_DATA_PLANE_SEGMENTS = frozenset({"data", "friesian"})

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _in_data_plane(path: str) -> bool:
    return bool(_DATA_PLANE_SEGMENTS & set(path.split("/")[:-1]))


def _nested_def_names(node: ast.AST) -> set:
    """Names of functions defined inside the enclosing functions of
    ``node`` — a ``.map(pad_one)`` where ``pad_one`` is a nested def is a
    per-row Python kernel just like a lambda."""
    names = set()
    for a in ancestors(node):
        if isinstance(a, _FUNCS):
            for n in ast.walk(a):
                if isinstance(n, _FUNCS) and n is not a:
                    names.add(n.name)
    return names


def _axis_is_1(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "axis" and isinstance(kw.value, ast.Constant) \
                and kw.value.value in (1, "columns"):
            return True
    return False


@register
class RowwiseMapInDataPlane(Rule):
    id = "rowwise-map-in-data-plane"
    description = ("Series.map(lambda)/nested-def or DataFrame.apply(axis=1) "
                   "in the data plane — a Python call per row; write a "
                   "vectorized numpy/pandas kernel instead")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if not _in_data_plane(ctx.path):
            return
        for node in ctx.walk():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr == "map":
                hit = None
                for arg in node.args:
                    if isinstance(arg, ast.Lambda):
                        hit = "a lambda"
                    elif isinstance(arg, ast.Name) \
                            and arg.id in _nested_def_names(node):
                        hit = f"nested def `{arg.id}`"
                if hit:
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        f".map({hit}) in the data plane runs a Python call "
                        "per row — replace with a vectorized kernel "
                        "(preallocated ndarray fill / searchsorted take), "
                        "or route real UDFs through transform_python_udf")
            elif attr == "apply" and _axis_is_1(node):
                yield Finding(
                    self.id, ctx.path, node.lineno, node.col_offset,
                    ".apply(axis=1) in the data plane materializes a Series "
                    "per row — use column-wise numpy ops instead")
