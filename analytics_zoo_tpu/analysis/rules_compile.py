"""Compile-ahead rules — XLA compilation reachable from serve/drain loops.

ISSUE 5 moved every hot-path compile onto a background warmup thread
(common/compile_ahead.py): the serve loop swaps to an already-built rung,
it never builds one. This rule keeps it that way: an in-band
``jitted.lower(...)`` / ``lowered.compile()`` inside the loop of a
dispatch/drain/serve/produce-named function stalls the serve thread for
the full XLA compile exactly when backlog is highest — the regression the
compile-ahead layer exists to prevent.

The warmup path itself is baselined by design: code inside any
``*warm*``-named function (``warm_up``, ``warm_async``, ``_warm_rung``)
is the sanctioned home for AOT builds, and plain-function compiles with
no enclosing hot loop (``ExecutableCache._compile``) are not findings.
"""

from __future__ import annotations

import ast
from typing import Iterable

from analytics_zoo_tpu.analysis.core import (
    FileContext, Finding, Rule, ancestors, register,
)
from analytics_zoo_tpu.analysis.rules_hotpath import (
    HOT_FN_TOKENS, _enclosing, _fn_tokens, _LOOPS, _nearest_function,
)

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

#: attribute tails that perform (or trigger) an XLA build on the spot
_COMPILE_ATTRS = frozenset({"lower", "compile"})

#: fully-resolved callables that merely LOOK like compiles (regex)
_NOT_XLA = frozenset({"re.compile", "regex.compile"})


def _in_warmup_code(node: ast.AST) -> bool:
    """True inside any ``*warm*``-named function — the sanctioned AOT
    build path (warm_up / warm_async / _warm_rung / worker closures whose
    enclosing function is warm-named)."""
    for a in ancestors(node):
        if isinstance(a, _FUNCS) and "warm" in a.name.lower():
            return True
    return False


@register
class JitCompileInServeLoop(Rule):
    """``.lower(...)`` / ``.compile(...)`` inside a serve/drain loop.

    In a hot-path package, an XLA lowering or compile call lexically
    inside a loop of a hot-named function (dispatch/drain/serve/produce/
    predict/fit/...) pays a multi-second compile on the latency-critical
    thread. Route the build through ``compile_ahead.ExecutableCache``
    (``warm``/``warm_async``) instead — warmup-named functions are
    baselined, ``re.compile`` is ignored, and a bare ``.lower()`` with no
    arguments reads as ``str.lower`` (never flagged)."""

    id = "jit-compile-in-serve-loop"
    description = "XLA lower/compile inside a serve/drain loop"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.is_hot_path:
            return
        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute) or \
                    func.attr not in _COMPILE_ATTRS:
                continue
            # str.lower() — zero-arg .lower is string casing, not a
            # jit lowering (which always takes avals/args)
            if func.attr == "lower" and not node.args and \
                    not node.keywords:
                continue
            name = ctx.imports.resolve(func)
            if name in _NOT_XLA:
                continue
            fn = _nearest_function(node)
            if fn is None or not (_fn_tokens(fn.name) & HOT_FN_TOKENS):
                continue
            loops = [lp for lp in _enclosing(node, _LOOPS)
                     if _nearest_function(lp) is fn]
            if not loops:
                continue
            if _in_warmup_code(node):
                continue
            yield Finding(
                self.id, ctx.path, node.lineno, node.col_offset,
                f".{func.attr}(...) inside the `{fn.name}` loop compiles "
                "XLA on the serve thread — AOT-build the rung through "
                "compile_ahead.ExecutableCache.warm_async and swap to it "
                "when ready")
