"""``python -m analytics_zoo_tpu.analysis`` — the zoolint CLI.

Exit codes: 0 clean (modulo baseline + inline suppressions), 1 findings,
2 usage/internal error. ``dev/run-tests.sh zoolint`` (and the ``all`` /
``smoke`` lanes) require exit 0 on the shipped tree and non-zero on
tests/fixtures/zoolint's seeded violations.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from analytics_zoo_tpu.analysis import baseline as baseline_lib
from analytics_zoo_tpu.analysis import report
from analytics_zoo_tpu.analysis.core import (
    all_rules, analyze_paths, find_repo_root, iter_python_files, relpath,
)


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m analytics_zoo_tpu.analysis",
        description="zoolint: AST-based JAX-aware static analysis "
                    "(hot-path syncs, recompile hazards, concurrency, "
                    "catalog drift)")
    p.add_argument("paths", nargs="*", default=["analytics_zoo_tpu"],
                   help="files/directories to scan "
                        "(default: analytics_zoo_tpu)")
    p.add_argument("--format", choices=("human", "json"), default="human")
    p.add_argument("--rules", metavar="ID[,ID...]",
                   help="run only these rule ids")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--baseline", metavar="PATH",
                   help="baseline file (default: <repo>/dev/"
                        "zoolint-baseline.json when it exists)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings to the baseline "
                        "(preserving surviving justifications) and exit 0")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _parser().parse_args(argv)
    rules = all_rules()
    if args.list_rules:
        for rid in sorted(rules):
            r = rules[rid]
            print(f"{rid:24s} [{r.scope:7s}] {r.description}")
        return 0
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - set(rules)
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = {rid: r for rid, r in rules.items() if rid in wanted}
    for p in args.paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2
    root = find_repo_root(args.paths[0])
    findings = analyze_paths(args.paths, rules=rules, root=root)

    baseline_path = args.baseline
    if baseline_path is None and root is not None:
        cand = os.path.join(root, baseline_lib.DEFAULT_BASELINE)
        if os.path.isfile(cand) or args.write_baseline:
            baseline_path = cand
    if args.write_baseline:
        if baseline_path is None:
            print("--write-baseline needs --baseline or a repo root",
                  file=sys.stderr)
            return 2
        n = baseline_lib.save(baseline_path, findings, root)
        print(f"baseline written: {baseline_path} ({n} entries)")
        return 0

    stale: List[dict] = []
    if baseline_path is not None and not args.no_baseline:
        try:
            entries = baseline_lib.load(baseline_path)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        # a partial scan (subset of paths or --rules) must not report
        # out-of-scope baseline entries as stale — judge staleness only
        # for entries this run could have re-found
        scanned = {relpath(p, root) for p in iter_python_files(args.paths)}
        in_scope = {fp: e for fp, e in entries.items()
                    if e["path"] in scanned and e["rule"] in rules}
        findings, stale = baseline_lib.apply(findings, in_scope, root)

    if args.format == "json":
        print(report.json_report(findings, stale, root))
    else:
        print(report.human_report(findings, stale))
    return 1 if findings else 0
