"""``python -m analytics_zoo_tpu.analysis`` — the zoolint CLI.

Exit codes: 0 clean (modulo baseline + inline suppressions), 1 findings,
2 usage error, 3 internal crash (so CI can tell "the tree has findings"
from "the linter itself broke"). ``dev/run-tests.sh zoolint`` (and the
``all`` / ``smoke`` lanes) require exit 0 on the shipped tree and
non-zero on tests/fixtures/zoolint's seeded violations.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
import traceback
from typing import List, Optional

from analytics_zoo_tpu.analysis import baseline as baseline_lib
from analytics_zoo_tpu.analysis import report
from analytics_zoo_tpu.analysis.core import (
    CFG_STATS, all_rules, analyze_paths, build_model_for_paths,
    find_repo_root, iter_python_files, relpath,
)


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m analytics_zoo_tpu.analysis",
        description="zoolint: AST-based JAX-aware static analysis "
                    "(hot-path syncs, recompile hazards, whole-program "
                    "concurrency, catalog drift)")
    p.add_argument("paths", nargs="*", default=["analytics_zoo_tpu"],
                   help="files/directories to scan "
                        "(default: analytics_zoo_tpu)")
    p.add_argument("--format", choices=("human", "json", "github"),
                   default="human",
                   help="human (default), json (stable schema), or "
                        "github (workflow-annotation lines)")
    p.add_argument("--rules", metavar="ID[,ID...]",
                   help="run only these rule ids")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--jobs", type=int, default=0, metavar="N",
                   help="parse files with N threads (0 = auto)")
    p.add_argument("--baseline", metavar="PATH",
                   help="baseline file (default: <repo>/dev/"
                        "zoolint-baseline.json when it exists)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings to the baseline "
                        "(preserving surviving justifications) and exit 0")
    p.add_argument("--prune-baseline", nargs="?", const="report",
                   choices=("report", "fix"), metavar="fix",
                   help="list baseline entries whose fingerprint matched "
                        "no finding in this scan; --prune-baseline=fix "
                        "also deletes them from the file (exit 0 either "
                        "way)")
    p.add_argument("--timing", action="store_true",
                   help="print scan wall time and CFG cache statistics "
                        "to stderr")
    p.add_argument("--migrate-baseline", action="store_true",
                   help="one-shot rewrite of a version-1 baseline to the "
                        "line-drift-stable version-2 fingerprints")
    p.add_argument("--ownership-report", metavar="PATH",
                   help="write the whole-program thread-ownership map "
                        "(markdown at PATH, JSON next to it) and exit 0")
    return p


def _jobs(args) -> int:
    if args.jobs > 0:
        return args.jobs
    return min(8, os.cpu_count() or 1)


def main(argv: Optional[List[str]] = None) -> int:
    args = _parser().parse_args(argv)
    try:
        return _run(args)
    except SystemExit:
        raise
    except BaseException:
        traceback.print_exc()
        print("zoolint: internal error (exit 3) — this is a linter bug, "
              "not a finding", file=sys.stderr)
        return 3


def _run(args) -> int:
    rules = all_rules()
    if args.list_rules:
        for rid in sorted(rules):
            r = rules[rid]
            print(f"{rid:28s} [{r.scope:7s}] {r.description}")
        return 0
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - set(rules)
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = {rid: r for rid, r in rules.items() if rid in wanted}
    for p in args.paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2
    root = find_repo_root(args.paths[0])

    if args.ownership_report:
        model = build_model_for_paths(args.paths, root=root,
                                      jobs=_jobs(args))
        from analytics_zoo_tpu.analysis import ownership
        md, js = ownership.write_report(model, args.ownership_report)
        print(f"ownership report written: {md} + {js} "
              f"({len(model.roots)} roots)")
        return 0

    CFG_STATS["built"] = CFG_STATS["hits"] = 0
    t0 = time.perf_counter()
    findings = analyze_paths(args.paths, rules=rules, root=root,
                             jobs=_jobs(args))
    if args.timing:
        n_files = sum(1 for _ in iter_python_files(args.paths))
        print(f"zoolint: scanned {n_files} files in "
              f"{time.perf_counter() - t0:.2f}s (CFGs "
              f"built={CFG_STATS['built']} "
              f"cache-hits={CFG_STATS['hits']})", file=sys.stderr)

    baseline_path = args.baseline
    if baseline_path is None and root is not None:
        cand = os.path.join(root, baseline_lib.DEFAULT_BASELINE)
        if os.path.isfile(cand) or args.write_baseline:
            baseline_path = cand
    if args.migrate_baseline:
        if baseline_path is None:
            print("--migrate-baseline needs --baseline or a repo root",
                  file=sys.stderr)
            return 2
        migrated = baseline_lib.migrate(baseline_path, findings, root)
        if migrated is None:
            print(f"baseline already version "
                  f"{baseline_lib.BASELINE_VERSION}: {baseline_path}")
        else:
            n, dropped = migrated
            print(f"baseline migrated: {baseline_path} ({n} entries)")
            for e in dropped:
                print(f"  dropped stale entry {e['fingerprint']} "
                      f"({e['rule']} at {e['path']})")
        return 0
    if args.write_baseline:
        if baseline_path is None:
            print("--write-baseline needs --baseline or a repo root",
                  file=sys.stderr)
            return 2
        n = baseline_lib.save(baseline_path, findings, root)
        print(f"baseline written: {baseline_path} ({n} entries)")
        return 0
    if args.prune_baseline:
        if baseline_path is None or not os.path.isfile(baseline_path):
            print("--prune-baseline: no baseline file to prune")
            return 0
        try:
            entries = baseline_lib.load(baseline_path)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        # like apply() below, only entries this run could have re-found
        # are judged — a partial scan must never prune what it cannot see
        scanned = {relpath(p, root) for p in iter_python_files(args.paths)}
        in_scope = {fp: e for fp, e in entries.items()
                    if e["path"] in scanned and e["rule"] in rules}
        _, stale = baseline_lib.apply(findings, in_scope, root)
        if not stale:
            print(f"baseline {baseline_path}: 0 stale entries "
                  f"({len(in_scope)} in scope)")
            return 0
        for e in stale:
            print(f"stale baseline entry {e['fingerprint']} "
                  f"({e['rule']} at {e['path']}:{e['line']})")
        if args.prune_baseline == "fix":
            n = baseline_lib.prune(
                baseline_path, {e["fingerprint"] for e in stale})
            print(f"baseline pruned: {baseline_path} "
                  f"({n} entries removed)")
        else:
            print(f"{len(stale)} stale entr"
                  f"{'y' if len(stale) == 1 else 'ies'} — re-run with "
                  f"--prune-baseline=fix to delete them")
        return 0

    stale: List[dict] = []
    if baseline_path is not None and not args.no_baseline:
        try:
            entries = baseline_lib.load(baseline_path)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        # a partial scan (subset of paths or --rules) must not report
        # out-of-scope baseline entries as stale — judge staleness only
        # for entries this run could have re-found
        scanned = {relpath(p, root) for p in iter_python_files(args.paths)}
        in_scope = {fp: e for fp, e in entries.items()
                    if e["path"] in scanned and e["rule"] in rules}
        findings, stale = baseline_lib.apply(findings, in_scope, root)

    if args.format == "json":
        print(report.json_report(findings, stale, root))
    elif args.format == "github":
        print(report.github_report(findings, stale))
    else:
        print(report.human_report(findings, stale))
    return 1 if findings else 0
