"""zoolint reporters — human (one finding per line, grep/editor-friendly)
and JSON (stable schema for CI tooling; schema changes bump
``JSON_SCHEMA_VERSION`` and are asserted by tests/test_zoolint.py)."""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from analytics_zoo_tpu.analysis.baseline import fingerprints
from analytics_zoo_tpu.analysis.core import Finding

JSON_SCHEMA_VERSION = 1


def human_report(findings: List[Finding], stale: List[dict]) -> str:
    lines = [f.format() for f in findings]
    for e in stale:
        lines.append(
            f"warning: stale baseline entry {e['fingerprint']} "
            f"({e['rule']} at {e['path']}) no longer matches — delete it")
    if findings:
        by_rule: Dict[str, int] = {}
        for f in findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        summary = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items()))
        lines.append(f"zoolint: {len(findings)} finding(s) ({summary})")
    else:
        lines.append("zoolint: clean")
    return "\n".join(lines)


def github_report(findings: List[Finding], stale: List[dict]) -> str:
    """GitHub Actions workflow-annotation lines — `--format=github` in
    CI makes every finding a review annotation on the touched line."""
    def esc(msg: str) -> str:
        # the annotation grammar reserves %, \r, \n in the message part
        return (msg.replace("%", "%25").replace("\r", "%0D")
                   .replace("\n", "%0A"))

    lines = [f"::error file={f.path},line={f.line},col={f.col + 1},"
             f"title=zoolint {f.rule}::{esc(f.message)}"
             for f in findings]
    lines += [f"::warning file={e['path']},title=zoolint stale baseline::"
              f"baseline entry {e['fingerprint']} ({e['rule']}) no longer "
              f"matches - delete it" for e in stale]
    if not lines:
        lines.append("::notice title=zoolint::clean")
    return "\n".join(lines)


def json_report(findings: List[Finding], stale: List[dict],
                root: Optional[str]) -> str:
    fps = dict((id(f), fp) for f, fp in fingerprints(findings, root))
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    obj = {
        "version": JSON_SCHEMA_VERSION,
        "findings": [
            {"rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
             "message": f.message, "fingerprint": fps[id(f)]}
            for f in findings],
        "stale_baseline": [e["fingerprint"] for e in stale],
        "summary": {"total": len(findings), "by_rule": by_rule},
    }
    return json.dumps(obj, indent=2)
