"""zoolint baseline — committed, fingerprinted grandfather list.

A finding the team decides to live with (with a one-line justification)
goes in ``dev/zoolint-baseline.json`` instead of an inline suppression —
the source line stays clean and the debt is inventoried in one reviewable
place. Fingerprints hash the rule id, the repo-relative path and the
*normalized source-line text* (plus an occurrence index for duplicates) —
NOT the line number — so edits elsewhere in a file never invalidate the
baseline, while any edit to the offending line itself retires the entry
(the finding resurfaces and must be re-justified or fixed).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from analytics_zoo_tpu.analysis.core import Finding

BASELINE_VERSION = 1
#: default location, relative to the repo root
DEFAULT_BASELINE = os.path.join("dev", "zoolint-baseline.json")


def _line_text(root: Optional[str], finding: Finding) -> str:
    path = finding.path
    if root is not None and not os.path.isabs(path):
        path = os.path.join(root, path)
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            lines = fh.read().splitlines()
        return lines[finding.line - 1].strip()
    except (OSError, IndexError):
        return ""


def fingerprints(findings: Iterable[Finding],
                 root: Optional[str]) -> List[Tuple[Finding, str]]:
    """Stable fingerprint per finding. Identical (rule, path, line-text)
    triples get an occurrence counter so N copies of the same offending
    line need N baseline entries — deleting one resurfaces one."""
    counts: Dict[str, int] = {}
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        base = f"{f.rule}\x00{f.path}\x00{_line_text(root, f)}"
        n = counts.get(base, 0)
        counts[base] = n + 1
        digest = hashlib.sha256(
            f"{base}\x00{n}".encode("utf-8")).hexdigest()[:16]
        out.append((f, digest))
    return out


def load(path: str) -> Dict[str, dict]:
    """fingerprint -> entry dict. Missing file = empty baseline."""
    if not os.path.isfile(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r}")
    return {e["fingerprint"]: e for e in data.get("entries", ())}


def save(path: str, findings: Iterable[Finding], root: Optional[str],
         justifications: Optional[Dict[str, str]] = None) -> int:
    """Write a baseline covering ``findings``. Existing justifications at
    ``path`` are preserved for fingerprints that survive; new entries get
    a TODO marker that review is expected to replace."""
    prior = {}
    if os.path.isfile(path):
        try:
            prior = load(path)
        except ValueError:
            prior = {}
    entries = []
    for f, fp in fingerprints(findings, root):
        just = (justifications or {}).get(fp) \
            or prior.get(fp, {}).get("justification") \
            or "TODO: justify or fix"
        entries.append({"fingerprint": fp, "rule": f.rule, "path": f.path,
                        "line": f.line, "message": f.message,
                        "justification": just})
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": BASELINE_VERSION, "entries": entries},
                  fh, indent=2, sort_keys=False)
        fh.write("\n")
    return len(entries)


def apply(findings: List[Finding], baseline: Dict[str, dict],
          root: Optional[str]) -> Tuple[List[Finding], List[dict]]:
    """(surviving findings, stale baseline entries). A stale entry's
    offending line was fixed or edited — it should be deleted from the
    baseline file (reported as a warning, never a failure)."""
    matched = set()
    out = []
    for f, fp in fingerprints(findings, root):
        if fp in baseline:
            matched.add(fp)
        else:
            out.append(f)
    stale = [e for fp, e in baseline.items() if fp not in matched]
    return out, stale
