"""zoolint baseline — committed, fingerprinted grandfather list.

A finding the team decides to live with (with a one-line justification)
goes in ``dev/zoolint-baseline.json`` instead of an inline suppression —
the source line stays clean and the debt is inventoried in one reviewable
place. Version-2 fingerprints hash the rule id, the repo-relative path
and the *normalized statement text* (continuation lines joined, comments
stripped, whitespace collapsed, plus an occurrence index for duplicates)
— NOT the line number and NOT the raw wrapping — so edits elsewhere in a
file, and even re-wrapping the offending statement across lines, never
invalidate the baseline, while any semantic edit to the statement itself
retires the entry (the finding resurfaces and must be re-justified or
fixed). Version-1 files (single raw-line fingerprints) are upgraded in
place with ``--migrate-baseline``.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from analytics_zoo_tpu.analysis.core import Finding

BASELINE_VERSION = 2
#: default location, relative to the repo root
DEFAULT_BASELINE = os.path.join("dev", "zoolint-baseline.json")

def _read_lines(root: Optional[str], finding: Finding,
                cache: Dict[str, List[str]]) -> List[str]:
    path = finding.path
    if root is not None and not os.path.isabs(path):
        path = os.path.join(root, path)
    cached = cache.get(path)
    if cached is not None:
        return cached
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            lines = fh.read().splitlines()
    except OSError:
        lines = []
    cache[path] = lines
    return lines


def _line_text(root: Optional[str], finding: Finding,
               cache: Dict[str, List[str]]) -> str:
    """Version-1 fingerprint text: the raw stripped source line."""
    lines = _read_lines(root, finding, cache)
    try:
        return lines[finding.line - 1].strip()
    except IndexError:
        return ""


def _strip_comment(line: str) -> str:
    """Drop a trailing ``#`` comment, respecting string literals (a naive
    quote-state scan — good enough for fingerprint normalization; an
    f-string with a quoted ``#`` inside a format spec is vanishingly rare
    on a *flagged* line, and mis-stripping only widens the fingerprint)."""
    quote = ""
    i = 0
    while i < len(line):
        c = line[i]
        if quote:
            if c == "\\":
                i += 2
                continue
            if line.startswith(quote, i):
                i += len(quote)
                quote = ""
                continue
        elif c in "\"'":
            quote = line[i:i + 3] if line.startswith(c * 3, i) else c
            i += len(quote)
            continue
        elif c == "#":
            return line[:i]
        i += 1
    return line


def _stmt_text(root: Optional[str], finding: Finding,
               cache: Dict[str, List[str]]) -> str:
    """Version-2 fingerprint text: the whole logical statement starting
    at the finding's line — physical lines joined while brackets stay
    open or a backslash continuation is pending — with comments stripped
    and whitespace runs collapsed. Re-wrapping the statement over more or
    fewer lines produces the same text."""
    lines = _read_lines(root, finding, cache)
    i = finding.line - 1
    if i < 0 or i >= len(lines):
        return ""
    parts: List[str] = []
    depth = 0
    for j in range(i, min(i + 40, len(lines))):
        line = _strip_comment(lines[j])
        cont = line.rstrip().endswith("\\")
        if cont:
            line = line.rstrip()[:-1]
        parts.append(line.strip())
        # bracket depth outside string literals (same naive scan)
        quote = ""
        k = 0
        while k < len(line):
            c = line[k]
            if quote:
                if c == "\\":
                    k += 2
                    continue
                if line.startswith(quote, k):
                    k += len(quote)
                    quote = ""
                    continue
            elif c in "\"'":
                quote = line[k:k + 3] if line.startswith(c * 3, k) else c
                k += len(quote)
                continue
            elif c in "([{":
                depth += 1
            elif c in ")]}":
                depth = max(0, depth - 1)
            k += 1
        if depth == 0 and not cont:
            break
    return " ".join(" ".join(parts).split())


def fingerprints(findings: Iterable[Finding], root: Optional[str],
                 version: int = BASELINE_VERSION
                 ) -> List[Tuple[Finding, str]]:
    """Stable fingerprint per finding. Identical (rule, path, text)
    triples get an occurrence counter so N copies of the same offending
    statement need N baseline entries — deleting one resurfaces one."""
    text_fn = _line_text if version == 1 else _stmt_text
    # file cache scoped to this call: callers may edit sources between
    # fingerprint passes (the round-trip tests do)
    cache: Dict[str, List[str]] = {}
    counts: Dict[str, int] = {}
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        base = f"{f.rule}\x00{f.path}\x00{text_fn(root, f, cache)}"
        n = counts.get(base, 0)
        counts[base] = n + 1
        digest = hashlib.sha256(
            f"{base}\x00{n}".encode("utf-8")).hexdigest()[:16]
        out.append((f, digest))
    return out


def load(path: str) -> Dict[str, dict]:
    """fingerprint -> entry dict. Missing file = empty baseline."""
    if not os.path.isfile(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    version = data.get("version")
    if version == 1:
        raise ValueError(
            f"baseline {path} uses the version-1 (raw line) fingerprint "
            f"format — run `python -m analytics_zoo_tpu.analysis "
            f"--migrate-baseline` once to rewrite it")
    if version != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {version!r}")
    return {e["fingerprint"]: e for e in data.get("entries", ())}


def save(path: str, findings: Iterable[Finding], root: Optional[str],
         justifications: Optional[Dict[str, str]] = None) -> int:
    """Write a baseline covering ``findings``. Existing justifications at
    ``path`` are preserved for fingerprints that survive; new entries get
    a TODO marker that review is expected to replace."""
    prior = {}
    if os.path.isfile(path):
        try:
            prior = load(path)
        except ValueError:
            prior = {}
    entries = []
    for f, fp in fingerprints(findings, root):
        just = (justifications or {}).get(fp) \
            or prior.get(fp, {}).get("justification") \
            or "TODO: justify or fix"
        entries.append({"fingerprint": fp, "rule": f.rule, "path": f.path,
                        "line": f.line, "message": f.message,
                        "justification": just})
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": BASELINE_VERSION, "entries": entries},
                  fh, indent=2, sort_keys=False)
        fh.write("\n")
    return len(entries)


def migrate(path: str, findings: List[Finding],
            root: Optional[str]) -> Optional[Tuple[int, List[dict]]]:
    """One-shot version-1 → version-2 rewrite of the baseline at
    ``path``. Each current finding is fingerprinted under BOTH schemes;
    a v1 entry matched by its old fingerprint is rewritten with the new
    one (justification, message, and line refreshed). Returns
    ``(migrated_count, dropped_entries)`` — dropped entries matched no
    current finding (already stale) and are removed — or ``None`` when
    nothing was rewritten (missing file or already version 2)."""
    if not os.path.isfile(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    version = data.get("version")
    if version == BASELINE_VERSION:
        return None
    if version != 1:
        raise ValueError(
            f"baseline {path}: cannot migrate version {version!r}")
    old = {e["fingerprint"]: e for e in data.get("entries", ())}
    entries = []
    matched = set()
    pairs = zip(fingerprints(findings, root, version=1),
                fingerprints(findings, root, version=2))
    for (f, fp1), (_f, fp2) in pairs:
        e = old.get(fp1)
        if e is None:
            continue
        matched.add(fp1)
        entries.append({"fingerprint": fp2, "rule": f.rule, "path": f.path,
                        "line": f.line, "message": f.message,
                        "justification": e.get("justification",
                                               "TODO: justify or fix")})
    dropped = [e for fp, e in old.items() if fp not in matched]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": BASELINE_VERSION, "entries": entries},
                  fh, indent=2, sort_keys=False)
        fh.write("\n")
    return len(entries), dropped


def prune(path: str, stale_fps: Iterable[str]) -> int:
    """Rewrite the baseline at ``path`` without the given fingerprints,
    preserving entry order and justifications. Returns how many entries
    were removed. A missing file prunes nothing."""
    if not os.path.isfile(path):
        return 0
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    drop = set(stale_fps)
    entries = [e for e in data.get("entries", ())
               if e.get("fingerprint") not in drop]
    removed = len(data.get("entries", ())) - len(entries)
    if removed:
        data["entries"] = entries
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2, sort_keys=False)
            fh.write("\n")
    return removed


def apply(findings: List[Finding], baseline: Dict[str, dict],
          root: Optional[str]) -> Tuple[List[Finding], List[dict]]:
    """(surviving findings, stale baseline entries). A stale entry's
    offending statement was fixed or edited — it should be deleted from
    the baseline file (reported as a warning, never a failure)."""
    matched = set()
    out = []
    for f, fp in fingerprints(findings, root):
        if fp in baseline:
            matched.add(fp)
        else:
            out.append(f)
    stale = [e for fp, e in baseline.items() if fp not in matched]
    return out, stale
