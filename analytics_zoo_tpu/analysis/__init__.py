"""zoolint — AST-based, JAX-aware static analysis for this codebase's
real failure modes (ISSUE 4 tentpole). Rule catalog: docs/zoolint.md.

Four rule families:

- **hot-path sync** (`wallclock-hotpath`, `hotpath-host-sync`) — wall-
  clock timing and implicit host↔device syncs in the serve/dispatch/train
  inner loops under serving/, common/, learn/;
- **recompile hazard** (`jit-in-loop`, `jit-call-inline`,
  `jit-static-unhashable`) — jit constructions that silently recompile;
- **concurrency** (`engine-unlocked-write`, `lock-order`) — unlocked
  cross-thread attribute writes in Thread-spawning classes, ABBA lock
  inversions;
- **catalog drift** (`metric-undocumented`, `metric-undeclared`,
  `envvar-undocumented`) — code vs docs/observability.md agreement.

CLI: ``python -m analytics_zoo_tpu.analysis [paths...]``. Suppress a
finding in place with ``# zoolint: disable=RULE`` (or grandfather it in
``dev/zoolint-baseline.json`` with a justification).
"""

from analytics_zoo_tpu.analysis.core import (  # noqa: F401
    Finding, Rule, all_rules, analyze_paths, analyze_source,
    find_repo_root,
)
from analytics_zoo_tpu.analysis.rules_catalog import (  # noqa: F401
    catalog_drift,
)

__all__ = ["Finding", "Rule", "all_rules", "analyze_paths",
           "analyze_source", "catalog_drift", "find_repo_root"]
