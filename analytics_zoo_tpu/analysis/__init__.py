"""zoolint — AST-based, JAX-aware static analysis for this codebase's
real failure modes. Rule catalog: docs/zoolint.md; thread-ownership map:
docs/concurrency.md (regenerate with ``--ownership-report``).

Five rule families:

- **hot-path sync** (`wallclock-hotpath`, `hotpath-host-sync`) — wall-
  clock timing and implicit host↔device syncs in the serve/dispatch/train
  inner loops under serving/, common/, learn/;
- **recompile hazard** (`jit-in-loop`, `jit-call-inline`,
  `jit-static-unhashable`) — jit constructions that silently recompile;
- **concurrency, per-file** (`engine-unlocked-write`, `lock-order`) —
  unlocked cross-thread attribute writes in Thread-spawning classes,
  same-file ABBA lock inversions;
- **concurrency, whole-program** (`cross-thread-unlocked-state`,
  `lock-order-inversion`, `blocking-under-lock`, `thread-leak`) — a
  project-wide call graph with thread-root inference and runs-on
  propagation catches races, inversions, and leaks that span modules;
- **catalog drift** (`metric-undocumented`, `metric-undeclared`,
  `envvar-undocumented`) — code vs docs/observability.md agreement.

CLI: ``python -m analytics_zoo_tpu.analysis [paths...]``. Suppress a
finding in place with ``# zoolint: disable=RULE`` (or grandfather it in
``dev/zoolint-baseline.json`` with a justification).
"""

from analytics_zoo_tpu.analysis.core import (  # noqa: F401
    Finding, Rule, all_rules, analyze_paths, analyze_source,
    build_model_for_paths, build_project, find_repo_root,
)
from analytics_zoo_tpu.analysis.rules_catalog import (  # noqa: F401
    catalog_drift,
)

__all__ = ["Finding", "Rule", "all_rules", "analyze_paths",
           "analyze_source", "build_model_for_paths", "build_project",
           "catalog_drift", "find_repo_root"]
