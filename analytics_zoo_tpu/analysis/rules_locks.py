"""Interprocedural lock-discipline rules (zoolint v2).

``lock-order-inversion`` runs cycle detection over the *global*
lock-acquisition graph — edges come both from syntactic ``with`` nesting
and from held-lock propagation through the call graph, so an ABBA pair
split across ``serving/engine.py`` and ``common/fleet.py`` is caught.
Pure same-file syntactic nesting is left to the per-file ``lock-order``
rule (no double report).

``blocking-under-lock`` flags a blocking call (socket ops, ``join``,
``time.sleep``, ``block_until_ready``/``device_get``, future
``.result()``, event ``.wait()``, broker RPC) made while a *contended*
lock is held — one that at least two thread roots acquire — because the
block then stalls every thread queued on that lock, serve loop included.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from analytics_zoo_tpu.analysis.core import (
    Finding, ProjectContext, Rule, _is_lockish_expr, register,
)

_SOCKET_METHODS = frozenset({"recv", "recv_into", "accept", "sendall",
                             "connect"})


def _num_const(node) -> bool:
    return isinstance(node, ast.Constant) and \
        isinstance(node.value, (int, float)) and \
        not isinstance(node.value, bool)


def _blocking_desc(call: ast.Call, fn, model) -> Optional[str]:
    d = fn.ctx.imports.resolve(call.func)
    if d == "time.sleep":
        return "time.sleep"
    if d and (d.endswith(".block_until_ready") or d == "jax.device_get"):
        return d.rsplit(".", 1)[-1]
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    base = call.func.value
    if attr in _SOCKET_METHODS:
        return f"socket .{attr}()"
    if attr == "join":
        # thread/process join only: zero args or a numeric timeout —
        # str.join takes an iterable positional
        if d and d.startswith("os.path"):
            return None
        if isinstance(base, ast.Constant):
            return None
        timeout_kw = any(kw.arg == "timeout" for kw in call.keywords)
        if not call.args and not call.keywords:
            return ".join()"
        if timeout_kw or (len(call.args) == 1 and _num_const(call.args[0])):
            return ".join()"
        return None
    if attr == "wait" and not _is_lockish_expr(base):
        return ".wait()"
    if attr == "result" and not call.args:
        return ".result()"
    # any method on a BrokerClient-typed receiver is a socket round-trip
    recv_t = None
    if isinstance(base, ast.Name):
        recv_t = fn.local_types.get(base.id)
    elif isinstance(base, ast.Attribute) and \
            isinstance(base.value, ast.Name) and \
            base.value.id == "self" and fn.cls is not None:
        recv_t = model._attr_type(fn.cls, base.attr)
    if recv_t and recv_t.endswith(".BrokerClient"):
        return f"broker RPC .{attr}()"
    return None


def _lock_short(lock: str) -> str:
    parts = lock.split(".")
    return ".".join(parts[-2:]) if len(parts) >= 2 else lock


@register
class LockOrderInversion(Rule):
    id = "lock-order-inversion"
    scope = "project"
    description = ("two locks acquired in both orders across the global "
                   "(interprocedural, cross-file) acquisition graph")

    def check_project(self, pctx: ProjectContext) -> Iterable[Finding]:
        model = pctx.model()
        edges = model.lock_edges
        done = set()
        reported_locks = set()
        for (a, b) in sorted(edges):
            if (a, b) in done or (b, a) not in edges:
                continue
            done.add((a, b))
            done.add((b, a))
            pa, la, ia = edges[(a, b)]
            pb, lb, ib = edges[(b, a)]
            if pa == pb and not ia and not ib:
                # same-file syntactic nesting — the per-file lock-order
                # rule owns that report
                continue
            (path, line), other = max(((pa, la), (pb, lb))), \
                min(((pa, la), (pb, lb)))
            reported_locks.update((a, b))
            yield Finding(
                self.id, path, line, 0,
                f"locks '{_lock_short(a)}' and '{_lock_short(b)}' are "
                f"taken in both orders — here and via {other[0]}:"
                f"{other[1]} — an ABBA deadlock across the call graph; "
                f"pick one order and hold to it")
        # longer cycles (A->B->C->A) with no internal two-cycle
        for cyc in _cycles(edges):
            if len(cyc) < 3 or reported_locks.intersection(cyc):
                continue
            first = min(cyc)
            i = cyc.index(first)
            cyc = cyc[i:] + cyc[:i]
            nxt = cyc[1]
            path, line, _ = edges[(first, nxt)]
            chain = " -> ".join(_lock_short(x) for x in cyc + [cyc[0]])
            reported_locks.update(cyc)
            yield Finding(
                self.id, path, line, 0,
                f"lock-acquisition cycle {chain} — a deadlock once all "
                f"{len(cyc)} locks are contended; break one edge")


def _cycles(edges):
    """Simple cycles in the lock graph (Tarjan SCCs; each SCC of >=3
    nodes is reported as one cycle along existing edges)."""
    adj = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    index, low, on, stack = {}, {}, set(), []
    out, counter = [], [0]

    def strongconnect(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in sorted(adj.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) >= 3:
                out.append(_order_cycle(comp, adj))

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)
    return [c for c in out if c]


def _order_cycle(comp, adj):
    """Walk the SCC along real edges to present a concrete cycle."""
    comp_set = set(comp)
    start = min(comp)
    path, seen = [start], {start}
    cur = start
    while True:
        nxts = [w for w in sorted(adj.get(cur, ()))
                if w in comp_set and w not in seen]
        back = [w for w in adj.get(cur, ()) if w == start]
        if back and len(path) >= 3:
            return path
        if not nxts:
            return path if len(path) >= 3 and start in adj.get(cur, ()) \
                else []
        cur = nxts[0]
        path.append(cur)
        seen.add(cur)


@register
class BlockingUnderLock(Rule):
    id = "blocking-under-lock"
    scope = "project"
    description = ("blocking call (socket/join/sleep/block_until_ready/"
                   "broker RPC) while holding a lock contended by >=2 "
                   "thread roots")

    def check_project(self, pctx: ProjectContext) -> Iterable[Finding]:
        model = pctx.model()
        for funcq in sorted(model.calls_in):
            fn = model.functions.get(funcq)
            if fn is None:
                continue
            may = model.may_held.get(funcq, frozenset())
            for call in model.calls_in[funcq]:
                desc = _blocking_desc(call, fn, model)
                if desc is None:
                    continue
                held = model._held_at(call, fn) | may
                contended = [L for L in sorted(held)
                             if len(model.lock_roots.get(L, ())) >= 2]
                if not contended:
                    continue
                lock = contended[0]
                who = ", ".join(sorted(model.lock_roots.get(lock, ())))
                yield Finding(
                    self.id, fn.ctx.path, call.lineno, call.col_offset,
                    f"blocking call ({desc}) while holding "
                    f"'{_lock_short(lock)}', a lock also taken from "
                    f"({who}) — the block stalls every thread queued on "
                    f"it; move the blocking call outside the critical "
                    f"section")
