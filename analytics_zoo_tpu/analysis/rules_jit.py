"""Recompile-hazard rules — ``jax.jit`` misuse that causes silent
per-call or per-iteration recompilation.

On TPU a recompile costs seconds and stalls the whole dispatch window; the
``zoo_jit_cache_misses_total`` counter detects a storm at runtime, these
rules catch the three constructions that guarantee one before the code
ever reaches a chip: jit built inside a loop, jit built and invoked in one
expression (a fresh wrapper per call), and unhashable / list-typed
``static_argnums``/``static_argnames`` values.
"""

from __future__ import annotations

import ast
from typing import Iterable

from analytics_zoo_tpu.analysis.core import (
    FileContext, Finding, Rule, ancestors, register,
)

#: callee names that construct a jitted callable
_JIT_TAILS = frozenset({"jit", "instrument_jit", "pjit"})

_LOOPS = (ast.For, ast.While, ast.AsyncFor)
_STATIC_KWARGS = ("static_argnums", "static_argnames")


def _is_jit_constructor(ctx: FileContext, node: ast.Call) -> bool:
    name = ctx.imports.resolve(node.func)
    if not name:
        return False
    parts = name.split(".")
    # bare `jit` only counts when it resolves through an import (jax.jit,
    # telemetry.instrument_jit) — a local helper named `jit` does not
    return len(parts) > 1 and parts[-1] in _JIT_TAILS


@register
class JitInLoop(Rule):
    """``jax.jit(...)`` constructed inside a ``for``/``while`` body.

    Every iteration builds a fresh wrapper; tracing (and often XLA
    compilation) re-runs per iteration. Construct the jitted callable
    once outside the loop (or in ``__init__``) and call it inside."""

    id = "jit-in-loop"
    description = "jit constructed inside a loop"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ctx.walk():
            if isinstance(node, ast.Call) \
                    and _is_jit_constructor(ctx, node) \
                    and any(isinstance(a, _LOOPS) for a in ancestors(node)):
                yield Finding(
                    self.id, ctx.path, node.lineno, node.col_offset,
                    f"{ctx.imports.resolve(node.func)} constructed inside "
                    "a loop — build the jitted callable once outside and "
                    "reuse it")


@register
class JitCallInline(Rule):
    """``jax.jit(f)(x)`` — a jitted wrapper built and invoked in one
    expression, i.e. rebuilt on every call of the enclosing function.

    The per-call wrapper defeats jit's own cache keying and re-traces per
    call site; hoist the ``jax.jit(f)`` to module/``__init__`` scope."""

    id = "jit-call-inline"
    description = "jit built and invoked in one expression"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ctx.walk():
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Call) \
                    and _is_jit_constructor(ctx, node.func):
                yield Finding(
                    self.id, ctx.path, node.lineno, node.col_offset,
                    "jit wrapper built and invoked in one expression — a "
                    "fresh trace per call; hoist the jit() construction "
                    "out of the call path")


@register
class JitStaticUnhashable(Rule):
    """List/set/dict literals passed as ``static_argnums`` /
    ``static_argnames``.

    Static argument descriptors are part of jit's cache key; an
    unhashable container either raises at call time or (on older APIs)
    silently defeats caching. Use a tuple — and mark only arguments whose
    values are hashable and genuinely static."""

    id = "jit-static-unhashable"
    description = "unhashable static_argnums/static_argnames value"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ctx.walk():
            if not (isinstance(node, ast.Call)
                    and _is_jit_constructor(ctx, node)):
                continue
            for kw in node.keywords:
                if kw.arg in _STATIC_KWARGS and isinstance(
                        kw.value, (ast.List, ast.Set, ast.Dict)):
                    kind = type(kw.value).__name__.lower()
                    yield Finding(
                        self.id, ctx.path, kw.value.lineno,
                        kw.value.col_offset,
                        f"{kw.arg} given a {kind} literal — static arg "
                        "descriptors key the jit cache and must be "
                        "hashable; use a tuple")
