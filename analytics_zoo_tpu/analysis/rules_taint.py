"""JAX hot-path dataflow rules — device-value taint over per-function
CFGs plus the jit-region closure on the ProjectModel call graph.

* ``tainted-host-sync`` — values produced by jit-wrapped callables /
  ``device_put`` are device arrays; converting one to host
  (``float``/``int``/``bool``/``np.asarray``/``.item()``/``.tolist()``)
  or branching on it inside a serve/decode/fit loop is an implicit
  host↔device sync per iteration. This is the *dataflow* sibling of the
  lexical ``hotpath-host-sync`` rule: it follows the value, so it fires
  in helpers the lexical rule's hot-name heuristic misses, and it
  catches implicit truthiness (``if y:``) the lexical rule cannot see.
* ``shape-dependent-branch-in-jit`` — python ``if``/``while`` on traced
  values inside a jitted body (the function itself or anything the call
  graph says it reaches): branching on a traced scalar raises at trace
  time, and branching on ``.shape``/``len()`` of a traced array bakes a
  per-shape specialization — the recompile hazard class the runtime's
  compile counter only reports after the fact.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from analytics_zoo_tpu.analysis.core import (
    CFG, FileContext, Finding, HOT_PATH_SEGMENTS, ProjectContext, Rule,
    ancestors, dataflow, module_name, register,
)
from analytics_zoo_tpu.analysis.rules_hotpath import HOT_FN_TOKENS

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)
_LOOPS = (ast.For, ast.While, ast.AsyncFor)

#: callee tails that construct a jit-compiled callable (same set the
#: lexical rules_jit family recognizes)
_JIT_TAILS = frozenset({"jit", "pjit", "instrument_jit"})

#: packages whose files carry serve/decode/fit hot loops — the lexical
#: hot-path set plus inference/ (the decode loop lives there)
_TAINT_SEGMENTS = HOT_PATH_SEGMENTS | {"inference"}

#: host-conversion callables by resolved name
_CONVERTERS = frozenset({"float", "int", "bool"})
_NP_COPIES = frozenset({"numpy.asarray", "numpy.array"})


def _nearest_function(node: ast.AST) -> Optional[ast.AST]:
    for a in ancestors(node):
        if isinstance(a, _FUNCS):
            return a
    return None


def _in_loop_of(node: ast.AST, fn: ast.AST) -> bool:
    for a in ancestors(node):
        if a is fn:
            return False
        if isinstance(a, _LOOPS):
            return True
    return False


def _names_in(node: Optional[ast.AST]) -> Set[str]:
    if node is None:
        return set()
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _target_names(tgt: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(tgt)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)}


def _is_jit_constructor(ctx: FileContext, call: ast.Call) -> bool:
    name = ctx.imports.resolve(call.func)
    parts = name.split(".") if name else []
    return len(parts) > 1 and parts[-1] in _JIT_TAILS


def _fn_tokens(name: str) -> Set[str]:
    return {t for t in name.lower().split("_") if t}


class _TaintScan:
    """Per-function taint facts: which locals may hold device values at
    each CFG block entry."""

    def __init__(self, ctx: FileContext, fn: ast.AST,
                 jit_locals: Set[str], jit_fns: Set[str]):
        self.ctx = ctx
        self.fn = fn
        self.jit_locals = jit_locals    # locals bound to jit(f)
        self.jit_fns = jit_fns          # file-level @jit function names
        self.cfg: CFG = ctx.cfg(fn)
        self.facts = dataflow(
            self.cfg, self._transfer, init=frozenset(),
            bottom=frozenset(), join=lambda a, b: a | b)

    # ------------------------------------------------------- sources
    def source_call(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in self.jit_locals or f.id in self.jit_fns:
                return True
            # the conventional jitted-apply parameter (predict_fn,
            # step_fn, apply_fn...) — device out unless proven otherwise
            if f.id.endswith("_fn"):
                return True
            return False
        name = self.ctx.imports.resolve(f)
        return bool(name) and name.split(".")[-1] == "device_put"

    def expr_tainted(self, expr: Optional[ast.AST],
                     tainted: frozenset) -> bool:
        if expr is None:
            return False
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in tainted:
                return True
            if isinstance(n, ast.Call) and self.source_call(n):
                return True
        return False

    # ------------------------------------------------------ transfer
    def _transfer(self, block, fact):
        s = block.stmt
        if s is None:
            return fact
        if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = s.targets if isinstance(s, ast.Assign) else [s.target]
            names: Set[str] = set()
            for t in targets:
                names |= _target_names(t)
            value = getattr(s, "value", None)
            rhs = self.expr_tainted(value, fact) or (
                isinstance(s, ast.AugAssign) and
                any(n in fact for n in names))
            return fact | names if rhs else fact - names
        if block.label == "loop-head" and \
                isinstance(s, (ast.For, ast.AsyncFor)):
            names = _target_names(s.target)
            if self.expr_tainted(s.iter, fact):
                return fact | names
            return fact - names
        return fact

    def fact_at(self, node: ast.AST) -> frozenset:
        cur: Optional[ast.AST] = node
        while cur is not None:
            hits = self.cfg.blocks_of(cur)
            if hits:
                return self.facts.get(hits[0], frozenset())
            cur = getattr(cur, "_zl_parent", None)
        return frozenset()


@register
class TaintedHostSync(Rule):
    """A device value synced to host inside a hot loop, found by taint.

    Tracks values produced by jit-wrapped callables (``step =
    jax.jit(f)`` then ``y = step(x)``), ``*_fn`` apply parameters, and
    ``device_put`` through assignments, and flags host conversions
    (``float``/``int``/``bool``/``np.asarray``/``.item()``/``.tolist()``)
    and implicit truthiness (``if y:``) on them inside a loop. Syncs the
    lexical ``hotpath-host-sync`` rule already owns (hot-named function
    in a hot package) are skipped, so one defect reports once."""

    id = "tainted-host-sync"
    description = "device-tainted value forced to host inside a loop"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if not (_TAINT_SEGMENTS & set(ctx.path.split("/")[:-1])):
            return
        jit_fns = {n.name for n in ctx.walk() if isinstance(n, _FUNCS)
                   and any(self._jit_decorator(ctx, d)
                           for d in n.decorator_list)}
        for fn in (n for n in ctx.walk() if isinstance(n, _FUNCS)):
            jit_locals = {
                n.targets[0].id for n in ctx.walk(fn)
                if isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and isinstance(n.value, ast.Call)
                and _is_jit_constructor(ctx, n.value)}
            if not (jit_locals or jit_fns or self._has_fn_calls(ctx, fn)):
                continue
            scan = _TaintScan(ctx, fn, jit_locals, jit_fns)
            yield from self._sinks(ctx, fn, scan)

    @staticmethod
    def _jit_decorator(ctx: FileContext, dec: ast.AST) -> bool:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = ctx.imports.resolve(target)
        parts = name.split(".") if name else []
        if len(parts) > 1 and parts[-1] in _JIT_TAILS:
            return True
        if parts and parts[-1] == "partial" and isinstance(dec, ast.Call) \
                and dec.args:
            inner = ctx.imports.resolve(dec.args[0])
            ip = inner.split(".") if inner else []
            return len(ip) > 1 and ip[-1] in _JIT_TAILS
        return False

    @staticmethod
    def _has_fn_calls(ctx: FileContext, fn: ast.AST) -> bool:
        return any(isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                   and n.func.id.endswith("_fn") for n in ctx.walk(fn))

    def _sinks(self, ctx: FileContext, fn: ast.AST,
               scan: _TaintScan) -> Iterable[Finding]:
        lexical_owns = ctx.is_hot_path and \
            bool(_fn_tokens(fn.name) & HOT_FN_TOKENS)
        for node in ctx.walk(fn):
            if _nearest_function(node) is not fn:
                continue
            if isinstance(node, ast.Call):
                label, overlaps, method = self._sync_label(ctx, node)
                if label is None or not _in_loop_of(node, fn):
                    continue
                if lexical_owns and overlaps:
                    continue        # hotpath-host-sync reports this one
                fact = scan.fact_at(node)
                if self._call_tainted(node, scan, fact, method):
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        f"{label} on a device-tainted value inside the "
                        f"`{fn.name}` loop forces a host sync per "
                        "iteration — keep the value on device or fence "
                        "it outside the loop")
            elif isinstance(node, (ast.If, ast.While)) and \
                    _in_loop_of(node, fn):
                fact = scan.fact_at(node)
                if self._branch_tainted(node.test, scan, fact):
                    yield Finding(
                        self.id, ctx.path, node.lineno, node.col_offset,
                        "branching on a device-tainted value inside the "
                        f"`{fn.name}` loop is an implicit host sync per "
                        "iteration — compute the predicate on host or "
                        "use lax.cond/where")

    @staticmethod
    def _sync_label(ctx: FileContext,
                    node: ast.Call) -> Tuple[Optional[str], bool, bool]:
        """(human label, overlaps-with-lexical-rule, is-method-sink)"""
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in ("item", "tolist") \
                and not node.args:
            return f".{f.attr}()", f.attr == "item", True
        name = ctx.imports.resolve(f)
        if name in _NP_COPIES:
            return f"{name}()", True, False
        if name in _CONVERTERS and len(node.args) == 1 and \
                not isinstance(node.args[0], ast.Constant):
            return f"{name}()", name == "float", False
        return None, False, False

    @staticmethod
    def _call_tainted(node: ast.Call, scan: _TaintScan,
                      fact: frozenset, method: bool) -> bool:
        if method:                                  # .item()/.tolist()
            return scan.expr_tainted(node.func.value, fact)
        return any(scan.expr_tainted(a, fact) for a in node.args)

    @staticmethod
    def _branch_tainted(test: ast.AST, scan: _TaintScan,
                        fact: frozenset) -> bool:
        """Bare truthiness / comparison on a tainted value — not
        ``is``/``isinstance`` checks (static at trace time)."""
        if isinstance(test, ast.Name):
            return test.id in fact
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return TaintedHostSync._branch_tainted(test.operand, scan, fact)
        if isinstance(test, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
                return False
            return scan.expr_tainted(test, fact)
        if isinstance(test, ast.BoolOp):
            return any(TaintedHostSync._branch_tainted(v, scan, fact)
                       for v in test.values)
        return False


# ----------------------------------------- shape-dependent-branch-in-jit

class _JitEntry:
    __slots__ = ("qual", "static_names", "static_nums")

    def __init__(self, qual: str, static_names: Set[str],
                 static_nums: Set[int]):
        self.qual = qual
        self.static_names = static_names
        self.static_nums = static_nums


def _static_spec(call_kwargs) -> Tuple[Set[str], Set[int]]:
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in call_kwargs:
        if kw.arg == "static_argnames":
            v = kw.value
            vals = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            names |= {e.value for e in vals
                      if isinstance(e, ast.Constant)
                      and isinstance(e.value, str)}
        elif kw.arg == "static_argnums":
            v = kw.value
            vals = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            nums |= {e.value for e in vals
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int)}
    return names, nums


@register
class ShapeBranchInJit(Rule):
    """Python branching on traced values/shapes inside a jitted body.

    Jitted entries are functions decorated ``@jax.jit`` /
    ``@partial(jax.jit, ...)`` or passed to a jit constructor; the
    jit *region* is their call-graph closure on the ProjectModel (a
    helper called from a jitted body traces too). Inside the region,
    an ``if``/``while`` whose test reads a traced parameter (non-static
    params at entries; arguments fed from traced caller values in
    helpers) either raises TracerBoolConversionError at trace time
    (value test) or bakes one executable per shape (``.shape`` /
    ``len()`` test — the silent recompile hazard). ``is``/``is not``,
    ``isinstance`` and ``hasattr`` tests are static at trace time and
    exempt. Fix: ``lax.cond``/``lax.select`` for values; make the
    argument static or branch outside jit for shapes."""

    id = "shape-dependent-branch-in-jit"
    scope = "project"
    description = "python branch on a traced value/shape inside jit"

    def check_project(self, pctx: ProjectContext) -> Iterable[Finding]:
        model = pctx.model()
        entries = self._entries(pctx, model)
        if not entries:
            return
        region = model.reachable(entries)
        tainted = self._region_taint(model, entries, region)
        for qual in sorted(region):
            fn = model.functions.get(qual)
            if fn is None or fn.node is None or fn.is_test:
                continue
            yield from self._branches(fn, tainted.get(qual, frozenset()))

    # ------------------------------------------------------- entries
    def _entries(self, pctx: ProjectContext,
                 model) -> Dict[str, _JitEntry]:
        entries: Dict[str, _JitEntry] = {}
        for fn in model.functions.values():
            node = fn.node
            if node is None or not isinstance(node, _FUNCS):
                continue
            for dec in node.decorator_list:
                if TaintedHostSync._jit_decorator(fn.ctx, dec):
                    kwargs = dec.keywords if isinstance(dec, ast.Call) \
                        else []
                    names, nums = _static_spec(kwargs)
                    entries[fn.qual] = _JitEntry(fn.qual, names, nums)
        # functions passed to a jit constructor: step = jax.jit(f, ...)
        for ctx in pctx.files:
            mod = module_name(ctx.path)
            for call in (n for n in ctx.walk()
                         if isinstance(n, ast.Call)):
                if not _is_jit_constructor(ctx, call) or not call.args:
                    continue
                arg = call.args[0]
                if not isinstance(arg, (ast.Name, ast.Attribute)):
                    continue
                r = model.resolve_dotted(ctx.imports.resolve(arg), mod)
                if r is None or r[0] != "func" or r[1].node is None:
                    continue
                names, nums = _static_spec(call.keywords)
                prev = entries.get(r[1].qual)
                if prev is not None:
                    names |= prev.static_names
                    nums |= prev.static_nums
                entries[r[1].qual] = _JitEntry(r[1].qual, names, nums)
        return entries

    # -------------------------------------------------- region taint
    def _region_taint(self, model, entries: Dict[str, _JitEntry],
                      region: Set[str]) -> Dict[str, frozenset]:
        """Tainted (traced) local names per region function: non-static
        params at entries, call-site-fed params in helpers, closed over
        assignments — a bounded worklist over the call graph."""
        tainted: Dict[str, Set[str]] = {}
        for qual, ent in entries.items():
            fn = model.functions.get(qual)
            if fn is None or fn.node is None:
                continue
            params = self._param_names(fn.node)
            tainted[qual] = {
                p for i, p in enumerate(params)
                if p not in ("self", "cls")
                and p not in ent.static_names
                and i not in ent.static_nums}
        for _ in range(4):
            changed = False
            # intraprocedural closure over straight-line assignments
            for qual in list(tainted):
                fn = model.functions.get(qual)
                if fn is None or fn.node is None:
                    continue
                t = tainted[qual]
                for n in fn.ctx.walk(fn.node):
                    if isinstance(n, ast.Assign) and \
                            _names_in(n.value) & t:
                        for tg in n.targets:
                            new = _target_names(tg) - t
                            if new:
                                t |= new
                                changed = True
            # interprocedural: traced args taint helper params
            for caller, callee, node, _held in model.call_sites:
                if caller not in tainted or callee not in region or \
                        not isinstance(node, ast.Call):
                    continue
                cfn = model.functions.get(callee)
                if cfn is None or cfn.node is None:
                    continue
                params = self._param_names(cfn.node)
                offset = 1 if params[:1] in (["self"], ["cls"]) and \
                    isinstance(node.func, ast.Attribute) else 0
                tset = tainted[caller]
                dst = tainted.setdefault(callee, set())
                for i, a in enumerate(node.args):
                    if _names_in(a) & tset and i + offset < len(params):
                        if params[i + offset] not in dst:
                            dst.add(params[i + offset])
                            changed = True
                for kw in node.keywords:
                    if kw.arg and _names_in(kw.value) & tset and \
                            kw.arg in params and kw.arg not in dst:
                        dst.add(kw.arg)
                        changed = True
            if not changed:
                break
        return {q: frozenset(v) for q, v in tainted.items()}

    @staticmethod
    def _param_names(node: ast.AST) -> List[str]:
        a = node.args
        return [p.arg for p in
                list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]

    # ------------------------------------------------------ branches
    def _branches(self, fn, tainted: frozenset) -> Iterable[Finding]:
        if not tainted:
            return
        for node in fn.ctx.walk(fn.node):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            if _nearest_function(node) is not fn.node:
                continue
            kind = self._test_kind(node.test, tainted)
            if kind is None:
                continue
            if kind == "shape":
                msg = ("python branch on the shape of a traced value "
                       f"inside jitted `{fn.name}` — one executable is "
                       "compiled per shape; make the argument static "
                       "(static_argnums) or branch outside jit")
            else:
                msg = ("python branch on a traced value inside jitted "
                       f"`{fn.name}` — this raises at trace time (or "
                       "silently recompiles); use lax.cond / lax.select")
            yield Finding(self.id, fn.ctx.path, node.lineno,
                          node.col_offset, msg)

    @staticmethod
    def _test_kind(test: ast.AST, tainted: frozenset) -> Optional[str]:
        kind: Optional[str] = None
        for n in ast.walk(test):
            if isinstance(n, ast.Call):
                f = n.func
                nm = f.id if isinstance(f, ast.Name) else \
                    f.attr if isinstance(f, ast.Attribute) else ""
                if nm in ("isinstance", "hasattr", "getattr", "callable"):
                    return None
                if nm == "len" and n.args and \
                        _names_in(n.args[0]) & tainted:
                    kind = "shape"
            if isinstance(n, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
                # `x is None` on an optional param: static at trace time
                shadow = _names_in(n)
                tainted = tainted - shadow
            if isinstance(n, ast.Attribute) and \
                    n.attr in ("shape", "ndim", "size") and \
                    _names_in(n.value) & tainted:
                kind = "shape"
        if kind == "shape":
            return kind
        leaves = {x.id for x in ast.walk(test)
                  if isinstance(x, ast.Name) and x.id in tainted}
        if leaves:
            return "value"
        return None
