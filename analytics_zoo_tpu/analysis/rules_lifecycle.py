"""Resource-lifecycle rules — path-sensitive proofs over per-function
CFGs (analysis/core.py) that acquired resources settle on *every* path.

Four contracts, one engine:

* ``record-ack-leak`` — every entry dequeued from the broker
  (XREADGROUP/XCLAIM) or taken from an assembly bucket must reach
  exactly one settlement per loop iteration (an XACK append / ``xack``
  call, or a re-bin that keeps the record alive under its lease), and
  every list accumulating XACK commands must be flushed or escape on
  every path to function exit. This machine-checks the at-least-once
  delivery contract the serving engine's leases/redelivery design
  (PR 9/10) and the gen-kind push-back (PR 14) rest on.
* ``lock-release-path`` — a bare ``.acquire()`` must be matched by a
  ``.release()`` on every exit edge, exception edges included.
* ``span-pairing`` — paired enter/exit calls (``attach``/``detach``,
  ``add_hook``/``remove_hook``, ``arm``/``disarm``, ...) on the same
  receiver must balance on all paths when the function closes the pair
  at all; long-lived attaches (no matching exit anywhere in the
  function) are deliberately out of scope.
* ``kv-page-leak`` — KV pages taken from the shared decode pool
  (``.alloc_pages(...)`` bound to a local) must be freed or handed to a
  new owner on every path to every exit, the raise exit included. This
  machine-checks the paged-KV allocator contract the step-level decode
  scheduler (PR 16) rests on: a leaked page list shrinks the pool for
  every future admission, forever.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from analytics_zoo_tpu.analysis.core import (
    CFG, FileContext, Finding, Rule, ancestors, dataflow, register,
    _is_lockish_expr,
)

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)
_LOOPS = (ast.For, ast.While, ast.AsyncFor)

#: mutator tails that move a value into a collection (set ``.add`` is
#: deliberately absent: dedupe-ring bookkeeping is not a settlement)
_BIN_MUTATORS = frozenset({"append", "appendleft", "extend", "extendleft"})

#: broker read calls whose result is a collection of leased entries
_OBTAIN_TAILS = frozenset({"xreadgroup", "xclaim"})

#: command tuples that settle a record's lease
_ACK_COMMANDS = frozenset({"XACK"})


def _functions(ctx: FileContext) -> Iterable[ast.AST]:
    for node in ctx.walk():
        if isinstance(node, _FUNCS):
            yield node


def _nearest_function(node: ast.AST) -> Optional[ast.AST]:
    for a in ancestors(node):
        if isinstance(a, _FUNCS):
            return a
    return None


def _nearest_loop(node: ast.AST) -> Optional[ast.AST]:
    for a in ancestors(node):
        if isinstance(a, _LOOPS):
            return a
        if isinstance(a, _FUNCS):
            return None
    return None


def _names_in(node: Optional[ast.AST]) -> Set[str]:
    if node is None:
        return set()
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _stmt_blocks(cfg: CFG, ctx: FileContext, node: ast.AST) -> List[int]:
    """All CFG blocks carrying the statement that contains ``node`` —
    a ``finally`` statement owns one block per duplicated copy (normal,
    exceptional, and one per abrupt exit), and a settlement in any copy
    counts."""
    cur: Optional[ast.AST] = node
    while cur is not None:
        hits = cfg.blocks_of(cur)
        if hits:
            return list(hits)
        cur = getattr(cur, "_zl_parent", None)
    return []


def _stmt_block(cfg: CFG, ctx: FileContext, node: ast.AST) -> Optional[int]:
    """The first CFG block carrying the statement containing ``node``."""
    hits = _stmt_blocks(cfg, ctx, node)
    return hits[0] if hits else None


def _recv_text(expr: ast.AST) -> str:
    try:
        return ast.unparse(expr)
    except Exception:       # pragma: no cover - malformed receiver
        return ""


def _command_tuple(expr: ast.AST) -> Optional[str]:
    """The command word when ``expr`` is a broker command tuple literal
    like ``("XACK", stream, group, id)``."""
    if isinstance(expr, ast.Tuple) and expr.elts:
        head = expr.elts[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str) \
                and head.value.isupper():
            return head.value
    return None


# --------------------------------------------------------- record-ack-leak

class _LoopPlan:
    """Everything needed to solve one entry loop: its CFG blocks, the
    loop targets, derived/ack-valued locals, and the settlement blocks."""

    __slots__ = ("loop", "head", "after", "first_target", "derived",
                 "ack_vals", "settle_blocks", "complex")

    def __init__(self, loop: ast.AST):
        self.loop = loop
        self.head: int = -1
        self.after: int = -1
        self.first_target: str = ""
        self.derived: Set[str] = set()
        self.ack_vals: Set[str] = set()
        self.settle_blocks: Set[int] = set()
        self.complex = False


@register
class RecordAckLeak(Rule):
    """A dequeued record that neither acks nor re-bins on some path.

    Serving files only, and only functions that speak the ack protocol
    (mention ``"XACK"`` or call ``.xack``): for every loop over a
    broker-obtained entry collection, each iteration path must settle
    the entry exactly once — append its ack, re-bin the whole entry
    (value containing the entry-id loop target), or ``xack`` it
    directly. Separately, every local list accumulating XACK command
    tuples must be flushed (passed to a call — ``pipeline``,
    ``_mark_done``...) or escape (returned) on every path to exit; an
    ``if acks:`` truthiness guard is understood. Exception paths that
    propagate out of the function are *not* leaks — the lease/redelivery
    contract covers them — which keeps the rule quiet on code that lets
    errors escape to a supervised loop."""

    id = "record-ack-leak"
    description = "broker entry may exit a path un-acked and un-retained"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if "serving" not in ctx.path.split("/")[:-1]:
            return
        for fn in _functions(ctx):
            if not self._has_ack_machinery(ctx, fn):
                continue
            yield from self._check_function(ctx, fn)

    @staticmethod
    def _has_ack_machinery(ctx: FileContext, fn: ast.AST) -> bool:
        for n in ctx.walk(fn):
            if isinstance(n, ast.Constant) and n.value in _ACK_COMMANDS:
                return True
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "xack":
                return True
        return False

    # ---------------------------------------------- entry collections
    def _entry_collections(self, ctx: FileContext,
                           fn: ast.AST) -> Tuple[Set[str], Set[str]]:
        """(local names, ``self.<attr>`` names) holding leased entries,
        by fixpoint over obtain calls, aliasing, slices, and re-bins."""
        locs: Set[str] = set()
        attrs: Set[str] = set()
        stmts = [n for n in ctx.walk(fn)]
        for _ in range(5):
            changed = False
            for n in stmts:
                if isinstance(n, ast.Assign) and len(n.targets) == 1:
                    tgt = n.targets[0]
                    if self._entryish(n.value, locs, attrs):
                        if isinstance(tgt, ast.Name) and tgt.id not in locs:
                            locs.add(tgt.id)
                            changed = True
                        elif isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self" and \
                                tgt.attr not in attrs:
                            attrs.add(tgt.attr)
                            changed = True
                elif isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in _BIN_MUTATORS and len(n.args) == 1:
                    # a collection receiving whole records re-binned out
                    # of a tracked entry loop is an entry collection too
                    if not self._rebin_value(n.args[0], locs, attrs):
                        continue
                    recv = n.func.value
                    if isinstance(recv, ast.Name) and recv.id not in locs:
                        locs.add(recv.id)
                        changed = True
                    elif isinstance(recv, ast.Attribute) and \
                            isinstance(recv.value, ast.Name) and \
                            recv.value.id == "self" and \
                            recv.attr not in attrs:
                        attrs.add(recv.attr)
                        changed = True
            if not changed:
                break
        return locs, attrs

    def _entryish(self, expr: ast.AST, locs: Set[str],
                  attrs: Set[str]) -> bool:
        """Does ``expr`` evaluate to an entry collection (or part of
        one)? Obtain calls, tracked names/attrs, slices and
        concatenations of them."""
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Attribute):
                if f.attr in _OBTAIN_TAILS:
                    return True
                if f.attr in ("popleft", "pop") and \
                        self._entryish(f.value, locs, attrs):
                    return True
            return False
        if isinstance(expr, ast.Name):
            return expr.id in locs
        if isinstance(expr, ast.Attribute):
            return isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and expr.attr in attrs
        if isinstance(expr, ast.Subscript):
            return self._entryish(expr.value, locs, attrs)
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            return self._entryish(expr.left, locs, attrs) or \
                self._entryish(expr.right, locs, attrs)
        return False

    def _rebin_value(self, expr: ast.AST, locs: Set[str],
                     attrs: Set[str]) -> bool:
        """A non-command value built from a *tracked* entry loop's
        targets — i.e. a whole record moving between collections."""
        if _command_tuple(expr) is not None:
            return False
        loop = _nearest_loop(expr)
        if loop is None or not isinstance(loop, (ast.For, ast.AsyncFor)):
            return False
        if not self._entryish(loop.iter, locs, attrs):
            return False
        first = self._first_target(loop)
        return bool(first) and first in _names_in(expr)

    @staticmethod
    def _first_target(loop: ast.AST) -> str:
        tgt = loop.target
        while isinstance(tgt, (ast.Tuple, ast.List)) and tgt.elts:
            tgt = tgt.elts[0]
        return tgt.id if isinstance(tgt, ast.Name) else ""

    # --------------------------------------------- per-iteration check
    def _check_function(self, ctx: FileContext,
                        fn: ast.AST) -> Iterable[Finding]:
        locs, attrs = self._entry_collections(ctx, fn)
        loops = []
        for n in ctx.walk(fn):
            if isinstance(n, (ast.For, ast.AsyncFor)) and \
                    _nearest_function(n) is fn and \
                    self._consuming_iter(n.iter, locs):
                loops.append(n)
        ack_lists = self._ack_lists(ctx, fn)
        if not loops and not ack_lists:
            return
        cfg = ctx.cfg(fn)
        for loop in loops:
            yield from self._solve_loop(ctx, fn, cfg, loop)
        for name, first_line in sorted(ack_lists.items()):
            yield from self._solve_flush(ctx, fn, cfg, name, first_line)

    def _consuming_iter(self, it: ast.AST, locs: Set[str]) -> bool:
        """Loops over *local* entry collections consume their records;
        iterating ``self._asm`` directly is a read-only peek."""
        if isinstance(it, ast.Name):
            return it.id in locs
        if isinstance(it, ast.Subscript):
            return self._consuming_iter(it.value, locs)
        return False

    def _plan(self, ctx: FileContext, fn: ast.AST, cfg: CFG,
              loop: ast.AST) -> Optional[_LoopPlan]:
        plan = _LoopPlan(loop)
        heads = cfg.blocks_of(loop)
        if not heads:
            return None
        plan.head = heads[0]
        exits = [d for d, k in cfg.block(plan.head).succs if k == "false"]
        plan.after = exits[0] if exits else -1
        plan.first_target = self._first_target(loop)
        if not plan.first_target:
            return None
        # derived locals + ack-valued locals, by fixpoint over the body
        body_stmts = [n for n in ctx.walk(loop)
                      if isinstance(n, ast.Assign) and len(n.targets) == 1
                      and isinstance(n.targets[0], ast.Name)
                      and _nearest_function(n) is fn]
        plan.derived = set(_names_in(loop.target))
        for _ in range(4):
            grew = False
            for a in body_stmts:
                tname = a.targets[0].id
                if tname in plan.derived:
                    continue
                if _names_in(a.value) & plan.derived:
                    plan.derived.add(tname)
                    if _command_tuple(a.value) in _ACK_COMMANDS:
                        plan.ack_vals.add(tname)
                    grew = True
            if not grew:
                break
        # settlement statements → blocks
        for n in ctx.walk(loop):
            if not (isinstance(n, ast.Call) and
                    isinstance(n.func, ast.Attribute)):
                continue
            kind = self._settles(n, plan)
            if kind is None:
                continue
            if _nearest_loop(n) is not loop:
                # a settlement in a nested loop settles 0..n times per
                # outer iteration — counting would lie either way
                plan.complex = True
                return plan
            plan.settle_blocks.update(_stmt_blocks(cfg, ctx, n))
        return plan

    def _settles(self, call: ast.Call, plan: _LoopPlan) -> Optional[str]:
        attr = call.func.attr
        if attr == "xack":
            args: Set[str] = set()
            for a in call.args:
                args |= _names_in(a)
            if plan.first_target in args or args & plan.derived:
                return "ack"
            return None
        if attr not in _BIN_MUTATORS or len(call.args) != 1:
            return None
        val = call.args[0]
        cmd = _command_tuple(val)
        if cmd is not None:
            return "ack" if cmd in _ACK_COMMANDS else None
        if isinstance(val, ast.Name) and val.id in plan.ack_vals:
            return "ack"
        if plan.first_target in _names_in(val):
            return "rebin"
        return None

    def _solve_loop(self, ctx: FileContext, fn: ast.AST, cfg: CFG,
                    loop: ast.AST) -> Iterable[Finding]:
        plan = self._plan(ctx, fn, cfg, loop)
        if plan is None or plan.complex or not plan.settle_blocks:
            # zero settlement statements at all: a transform/peek loop,
            # not a consume loop — the flush check still applies
            return
        head, after = plan.head, plan.after
        bottom: frozenset = frozenset()

        def transfer(block, fact):
            if block.idx in plan.settle_blocks:
                return frozenset(min(c + 1, 2) for c in fact)
            return fact

        def edge_fn(src, kind, fact):
            if src.idx == head and kind == "true":
                return frozenset((0,))      # fresh iteration
            return fact

        facts = dataflow(cfg, transfer, init=frozenset((0,)),
                         bottom=bottom, join=lambda a, b: a | b,
                         edge_fn=edge_fn)
        iter_ends: List[int] = []
        for b in cfg.blocks:
            for dst, kind in b.succs:
                if dst == head and kind in ("back", "continue"):
                    iter_ends.append(b.idx)
                elif dst == after and kind == "break":
                    iter_ends.append(b.idx)
                elif kind == "return" and \
                        isinstance(b.stmt, ast.Return) and \
                        _nearest_loop(b.stmt) is loop:
                    iter_ends.append(b.idx)
        leak = doubled = False
        for b in iter_ends:
            out = transfer(cfg.block(b), facts.get(b, bottom))
            leak = leak or 0 in out
            doubled = doubled or 2 in out
        it_name = _recv_text(loop.iter)
        if leak:
            yield Finding(
                self.id, ctx.path, loop.lineno, loop.col_offset,
                f"a record dequeued from `{it_name}` can finish a loop "
                "iteration without being acked or re-binned on some path "
                "— every leased entry must settle exactly once (ack it, "
                "append it to a bucket, or push it back)")
        if doubled:
            yield Finding(
                self.id, ctx.path, loop.lineno, loop.col_offset,
                f"a record dequeued from `{it_name}` settles more than "
                "once on some path (e.g. acked and re-binned) — it would "
                "be double-served or double-acked")

    # ------------------------------------------------- ack-list flush
    def _ack_lists(self, ctx: FileContext, fn: ast.AST) -> Dict[str, int]:
        """Locals born as ``[]``/``list()`` that accumulate XACK command
        tuples → first ack-append line."""
        born: Set[str] = set()
        for n in ctx.walk(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                v = n.value
                if (isinstance(v, ast.List) and not v.elts) or \
                        (isinstance(v, ast.Call) and
                         isinstance(v.func, ast.Name) and
                         v.func.id == "list" and not v.args):
                    born.add(n.targets[0].id)
        out: Dict[str, int] = {}
        for n in ctx.walk(fn):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in _BIN_MUTATORS and \
                    isinstance(n.func.value, ast.Name) and \
                    n.func.value.id in born and len(n.args) == 1:
                v = n.args[0]
                acky = _command_tuple(v) in _ACK_COMMANDS
                if not acky and isinstance(v, ast.Name):
                    acky = any(
                        isinstance(a, ast.Assign) and
                        len(a.targets) == 1 and
                        isinstance(a.targets[0], ast.Name) and
                        a.targets[0].id == v.id and
                        _command_tuple(a.value) in _ACK_COMMANDS
                        for a in ctx.walk(fn) if isinstance(a, ast.Assign))
                if acky:
                    name = n.func.value.id
                    out.setdefault(name, n.lineno)
        return out

    def _solve_flush(self, ctx: FileContext, fn: ast.AST, cfg: CFG,
                     name: str, first_line: int) -> Iterable[Finding]:
        gen_blocks: Set[int] = set()
        kill_blocks: Set[int] = set()
        for n in ctx.walk(fn):
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Attribute) and \
                        n.func.attr in _BIN_MUTATORS and \
                        isinstance(n.func.value, ast.Name) and \
                        n.func.value.id == name:
                    gen_blocks.update(_stmt_blocks(cfg, ctx, n))
                elif any(name in _names_in(a) for a in n.args) or \
                        any(name in _names_in(k.value) for k in n.keywords):
                    # flushed / handed off
                    kill_blocks.update(_stmt_blocks(cfg, ctx, n))
            elif isinstance(n, (ast.Return, ast.Yield)) and \
                    name in _names_in(getattr(n, "value", None)):
                # escapes to caller
                kill_blocks.update(_stmt_blocks(cfg, ctx, n))
        if not gen_blocks:
            return
        kill_blocks -= gen_blocks

        def transfer(block, fact):
            if block.idx in kill_blocks:
                return frozenset((0,))
            if block.idx in gen_blocks:
                return frozenset((1,))
            return fact

        def edge_fn(src, kind, fact):
            # `if acks:` — the false edge proves the list is empty
            test = None
            if src.label in ("branch", "loop-head") and \
                    isinstance(src.stmt, (ast.If, ast.While)):
                test = src.stmt.test
            if test is None:
                return fact
            plain, negated = self._truthiness_names(test)
            if kind == "false" and name in plain:
                return frozenset((0,))
            if kind == "true" and name in negated:
                return frozenset((0,))
            return fact

        facts = dataflow(cfg, transfer, init=frozenset((0,)),
                         bottom=frozenset(), join=lambda a, b: a | b,
                         edge_fn=edge_fn)
        if 1 in facts.get(cfg.exit, frozenset()):
            yield Finding(
                self.id, ctx.path, first_line, 0,
                f"ack list `{name}` can reach the end of "
                f"`{getattr(fn, 'name', '?')}` without being flushed or "
                "returned on some path — those XACKs would be dropped "
                "and the entries redelivered forever")

    @staticmethod
    def _truthiness_names(test: ast.AST) -> Tuple[Set[str], Set[str]]:
        """(names whose falsiness the false edge proves, names whose
        falsiness the true edge proves) for ``if a or b:`` / ``if not
        a:`` shaped tests."""
        plain: Set[str] = set()
        negated: Set[str] = set()
        leaves = test.values if isinstance(test, ast.BoolOp) and \
            isinstance(test.op, ast.Or) else [test]
        for leaf in leaves:
            if isinstance(leaf, ast.Name):
                plain.add(leaf.id)
            elif isinstance(leaf, ast.UnaryOp) and \
                    isinstance(leaf.op, ast.Not) and \
                    isinstance(leaf.operand, ast.Name):
                negated.add(leaf.operand.id)
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
                and isinstance(test.operand, ast.Name):
            negated.add(test.operand.id)
        return plain, negated


# ----------------------------------------------- exit-coverage analyses

def _must_do_before_exit(ctx: FileContext, cfg: CFG, site: ast.AST,
                         done_blocks: Set[int]) -> bool:
    """True when every path from ``site``'s normal successors to any
    exit — the raise exit included — passes a ``done`` block. Backward
    reach-avoid: a block's fact says "an exit is reachable from my exit
    without doing it"."""

    def transfer(block, fact):
        return False if block.idx in done_blocks else fact

    facts = dataflow(cfg, transfer, init=True, bottom=False,
                     join=lambda a, b: a or b, backward=True)
    b = _stmt_block(cfg, ctx, site)
    if b is None:
        return True
    for dst, kind in cfg.block(b).succs:
        if kind == "exc":
            continue        # the acquire itself raising holds nothing
        if transfer(cfg.block(dst), facts.get(dst, False)):
            return False
    return True


def _matching_calls(ctx: FileContext, fn: ast.AST, attr: str,
                    recv: str) -> List[ast.Call]:
    out = []
    for n in ctx.walk(fn):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == attr \
                and _recv_text(n.func.value) == recv \
                and _nearest_function(n) is fn:
            out.append(n)
    return out


@register
class LockReleasePath(Rule):
    """A bare ``.acquire()`` that some path never releases.

    Expression-statement ``acquire()`` calls on lockish receivers
    (``*lock*``, ``*sem*``, ``*cond*``...) must reach a ``.release()``
    on the same receiver on every path to every exit — the raise exit
    included, so an unguarded call between acquire and release is
    itself a finding. Acquires whose result is assigned/tested
    (``if not lock.acquire(timeout=...):``) are skipped; ``with lock:``
    never fires. Fix: use ``with``, or release in ``finally``."""

    id = "lock-release-path"
    description = "explicit lock acquire without release on every path"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        for fn in _functions(ctx):
            sites = []
            for n in ctx.walk(fn):
                if isinstance(n, ast.Expr) and isinstance(n.value, ast.Call) \
                        and isinstance(n.value.func, ast.Attribute) \
                        and n.value.func.attr == "acquire" \
                        and _is_lockish_expr(n.value.func.value) \
                        and _nearest_function(n) is fn:
                    sites.append(n)
            if not sites:
                continue
            cfg = ctx.cfg(fn)
            for site in sites:
                recv = _recv_text(site.value.func.value)
                done: Set[int] = set()
                for rel in _matching_calls(ctx, fn, "release", recv):
                    done.update(_stmt_blocks(cfg, ctx, rel))
                if _must_do_before_exit(ctx, cfg, site, done):
                    continue
                yield Finding(
                    self.id, ctx.path, site.lineno, site.col_offset,
                    f"`{recv}.acquire()` is not matched by "
                    f"`{recv}.release()` on every exit path (an exception "
                    "or early return leaves it held) — use `with "
                    f"{recv}:` or release in a `finally`")


#: call tails that take pages out of a shared KV pool
_KV_ALLOC_TAILS = frozenset({"alloc_pages"})


@register
class KvPageLeak(Rule):
    """KV pages allocated from the shared pool that some path strands.

    For every ``x = <pool>.alloc_pages(...)`` binding, each path from
    the allocation to each function exit — the raise exit included —
    must settle ownership of ``x``: free it back (``free_pages(x)``),
    hand it to a new owner (``x`` passed to any call — a cache
    constructor, an ``extend`` — or stored into object/collection state
    via an attribute/subscript assignment), or return/yield it to the
    caller. An unguarded early return or an unprotected call between
    the alloc and the settlement is itself a finding — the fix is a
    ``try/except: free_pages(x); raise`` around the handoff (the
    scheduler's admission path is the reference shape). A leaked page
    list never rejoins the free list, shrinking the pool for every
    future admission."""

    id = "kv-page-leak"
    description = "allocated KV pages may exit a path unfreed and unowned"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        for fn in _functions(ctx):
            sites = []
            for n in ctx.walk(fn):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name) \
                        and isinstance(n.value, ast.Call) \
                        and isinstance(n.value.func, ast.Attribute) \
                        and n.value.func.attr in _KV_ALLOC_TAILS \
                        and _nearest_function(n) is fn:
                    sites.append(n)
            if not sites:
                continue
            cfg = ctx.cfg(fn)
            for site in sites:
                name = site.targets[0].id
                done = self._settle_blocks(ctx, fn, cfg, site, name)
                if _must_do_before_exit(ctx, cfg, site, done):
                    continue
                yield Finding(
                    self.id, ctx.path, site.lineno, site.col_offset,
                    f"pages allocated into `{name}` can reach a function "
                    "exit without being freed or handed off on some path "
                    "(an early return or an exception between the "
                    "alloc_pages and its settlement) — free them in an "
                    "except/finally or move the handoff adjacent to the "
                    "allocation")

    @staticmethod
    def _settle_blocks(ctx: FileContext, fn: ast.AST, cfg: CFG,
                       site: ast.AST, name: str) -> Set[int]:
        """Blocks where ownership of ``name`` settles: the pages are
        freed, passed to any call (handoff — the callee owns them now),
        stored into attribute/subscript state, or escape via
        return/yield."""
        done: Set[int] = set()
        for n in ctx.walk(fn):
            if _nearest_function(n) is not fn or n is site:
                continue
            if isinstance(n, ast.Call):
                if any(name in _names_in(a) for a in n.args) or \
                        any(name in _names_in(k.value)
                            for k in n.keywords):
                    done.update(_stmt_blocks(cfg, ctx, n))
            elif isinstance(n, (ast.Return, ast.Yield)) and \
                    name in _names_in(getattr(n, "value", None)):
                done.update(_stmt_blocks(cfg, ctx, n))
            elif isinstance(n, ast.Assign) and \
                    any(isinstance(t, (ast.Attribute, ast.Subscript))
                        for t in n.targets) and \
                    name in _names_in(n.value):
                done.update(_stmt_blocks(cfg, ctx, n))
        return done


#: enter-call tail -> exit-call tail for paired lifecycle calls
_SPAN_PAIRS = {
    "attach": "detach", "add_hook": "remove_hook", "arm": "disarm",
    "register": "unregister", "subscribe": "unsubscribe",
    "start_span": "end_span",
}


@register
class SpanPairing(Rule):
    """An enter/exit call pair that some path leaves unbalanced.

    For each expression-statement enter call (``attach``, ``add_hook``,
    ``arm``, ``register``, ``subscribe``, ``start_span``) whose matching
    exit call on the *same receiver* exists somewhere in the function,
    every path from the enter to every exit — exceptions included — must
    pass the exit call. Functions that attach without ever detaching
    (process-lifetime hooks like ``get_flight_recorder``) are out of
    scope by construction. Fix: move the exit call to a ``finally`` or
    wrap the pair in a context manager."""

    id = "span-pairing"
    description = "enter/exit pair unbalanced on some path"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        for fn in _functions(ctx):
            sites = []
            for n in ctx.walk(fn):
                if isinstance(n, ast.Expr) and isinstance(n.value, ast.Call) \
                        and isinstance(n.value.func, ast.Attribute) \
                        and n.value.func.attr in _SPAN_PAIRS \
                        and _nearest_function(n) is fn:
                    sites.append(n)
            if not sites:
                continue
            cfg = None
            for site in sites:
                enter = site.value.func.attr
                exit_attr = _SPAN_PAIRS[enter]
                recv = _recv_text(site.value.func.value)
                exits = _matching_calls(ctx, fn, exit_attr, recv)
                if not exits:
                    continue        # long-lived attach: not our contract
                if cfg is None:
                    cfg = ctx.cfg(fn)
                done: Set[int] = set()
                for x in exits:
                    done.update(_stmt_blocks(cfg, ctx, x))
                if _must_do_before_exit(ctx, cfg, site, done):
                    continue
                yield Finding(
                    self.id, ctx.path, site.lineno, site.col_offset,
                    f"`{recv}.{enter}()` is not balanced by "
                    f"`{recv}.{exit_attr}()` on every path to function "
                    "exit (an exception or early return skips it) — pair "
                    "them in a `finally` or a context manager")
