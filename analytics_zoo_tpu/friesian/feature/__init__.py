from analytics_zoo_tpu.friesian.feature.table import (  # noqa: F401
    Table, FeatureTable, StringIndex,
)
