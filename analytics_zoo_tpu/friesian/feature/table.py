"""Friesian FeatureTable: recsys tabular feature engineering.

Rebuild of ref ``pyzoo/zoo/friesian/feature/table.py`` (Table/FeatureTable/
StringIndex, 723 LoC) and the Scala kernels
``zoo/.../friesian/feature/Utils.scala:27-167``. The reference runs on Spark
DataFrames; here tables are ``HostXShards`` of pandas DataFrames, so every
per-row op is an embarrassingly parallel shard transform and only the
aggregations (string-index fit, median, min/max) do a gather. The output of
a feature pipeline is fixed-shape int/float ndarrays ready for the jitted
train step — padding/masking (``pad``/``mask``) is the ragged→static bridge.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np
import pandas as pd

from analytics_zoo_tpu.data.shard import HostXShards


def _as_list(x) -> List[str]:
    return [x] if isinstance(x, str) else list(x)


def _shard_seed(d: pd.DataFrame) -> int:
    """Deterministic, shard-content-dependent RNG seed: equal-length shards
    with different rows draw different randoms, and reruns reproduce."""
    hashable = d.select_dtypes(exclude=["object"])
    if hashable.shape[1] == 0:
        hashable = d.astype(str)
    h = pd.util.hash_pandas_object(hashable, index=False).to_numpy()
    return int(h.sum() % np.uint64(2**31 - 1))


class Table:
    """Base distributed table (ref table.py:35)."""

    def __init__(self, shards: HostXShards):
        self.shards = shards

    # ---------- constructors ----------

    @classmethod
    def from_pandas(cls, df: pd.DataFrame, num_shards: Optional[int] = None):
        n = num_shards or 1
        idx = np.array_split(np.arange(len(df)), max(1, n))
        return cls(HostXShards([df.iloc[i].reset_index(drop=True) for i in idx]))

    @classmethod
    def read_parquet(cls, paths: Union[str, List[str]]):
        """(ref table.py:285)"""
        paths = _as_list(paths)
        files = []
        for p in paths:
            if os.path.isdir(p):
                files += [os.path.join(p, f) for f in sorted(os.listdir(p))
                          if f.endswith(".parquet")]
            else:
                files.append(p)
        dfs = [pd.read_parquet(f) for f in files]
        return cls(HostXShards(dfs))

    @classmethod
    def read_json(cls, paths: Union[str, List[str]], cols=None):
        """(ref table.py:296)"""
        dfs = [pd.read_json(p, lines=True) for p in _as_list(paths)]
        if cols:
            dfs = [d[_as_list(cols)] for d in dfs]
        return cls(HostXShards(dfs))

    # ---------- internals ----------

    def _clone(self, shards: HostXShards) -> "Table":
        return type(self)(shards)

    def _map(self, fn: Callable[[pd.DataFrame], pd.DataFrame]) -> "Table":
        return self._clone(self.shards.transform_shard(fn))

    def to_pandas(self) -> pd.DataFrame:
        dfs = self.shards.collect()
        return pd.concat(dfs, ignore_index=True) if dfs else pd.DataFrame()

    def compute(self) -> "Table":
        """(ref table.py:64 — materialize; shards are eager here)"""
        self.shards.cache()
        return self

    @property
    def df(self) -> pd.DataFrame:
        return self.to_pandas()

    @property
    def schema(self):
        return self.shards.collect()[0].dtypes

    def size(self) -> int:
        """(ref table.py:79)"""
        return sum(len(s) for s in self.shards.collect())

    def __len__(self):
        return self.size()

    # ---------- row/column ops ----------

    def select(self, *cols) -> "Table":
        cols = [c for group in cols for c in _as_list(group)]
        return self._map(lambda d: d[cols])

    def drop(self, *cols) -> "Table":
        """(ref table.py:94)"""
        drop = [c for group in cols for c in _as_list(group)]
        return self._map(lambda d: d.drop(columns=drop))

    def fillna(self, value, columns: Optional[Sequence[str]]) -> "Table":
        """(ref table.py:106)"""
        def fill(d):
            d = d.copy()
            cols = _as_list(columns) if columns else list(d.columns)
            d[cols] = d[cols].fillna(value)
            return d
        return self._map(fill)

    def dropna(self, columns=None, how="any", thresh=None) -> "Table":
        """(ref table.py:132)"""
        kw = {"thresh": thresh} if thresh is not None else {"how": how}
        return self._map(lambda d: d.dropna(
            subset=_as_list(columns) if columns else None,
            **kw).reset_index(drop=True))

    def distinct(self) -> "Table":
        """(ref table.py:148; global dedup needs the gather)"""
        full = self.to_pandas().drop_duplicates().reset_index(drop=True)
        n = max(1, self.shards.num_partitions())
        idx = np.array_split(np.arange(len(full)), n)
        return self._clone(HostXShards(
            [full.iloc[i].reset_index(drop=True) for i in idx]))

    def filter(self, condition: Union[str, Callable]) -> "Table":
        """(ref table.py:155; condition is a pandas query string or a
        row-mask callable)"""
        if callable(condition):
            return self._map(
                lambda d: d[condition(d)].reset_index(drop=True))
        return self._map(lambda d: d.query(condition).reset_index(drop=True))

    def rename(self, columns: Dict[str, str]) -> "Table":
        """(ref table.py:252)"""
        return self._map(lambda d: d.rename(columns=columns))

    def clip(self, columns, min=None, max=None) -> "Table":
        """(ref table.py:166)"""
        cols = _as_list(columns)

        def f(d):
            d = d.copy()
            d[cols] = d[cols].clip(lower=min, upper=max)
            return d
        return self._map(f)

    def log(self, columns, clipping: bool = True) -> "Table":
        """log(x + 1), clipping negatives to 0 first (ref table.py:188)"""
        cols = _as_list(columns)

        def f(d):
            d = d.copy()
            for c in cols:
                v = d[c].astype(float)
                if clipping:
                    v = v.clip(lower=0)
                d[c] = np.log1p(v)
            return d
        return self._map(f)

    def median(self, columns) -> "Table":
        """table of (column, median) (ref table.py:223)"""
        cols = _as_list(columns)
        full = self.to_pandas()
        med = pd.DataFrame({"column": cols,
                            "median": [full[c].median() for c in cols]})
        return Table.from_pandas(med, 1)

    def fill_median(self, columns) -> "Table":
        """(ref table.py:206)"""
        cols = _as_list(columns)
        full = self.to_pandas()
        meds = {c: full[c].median() for c in cols}

        def f(d):
            d = d.copy()
            for c in cols:
                d[c] = d[c].fillna(meds[c])
            return d
        return self._map(f)

    def merge_cols(self, columns, target: str) -> "Table":
        """merge columns into one array column (ref table.py:240)"""
        cols = _as_list(columns)

        def f(d):
            d = d.copy()
            d[target] = d[cols].values.tolist()
            return d.drop(columns=cols)
        return self._map(f)

    def transform_python_udf(self, in_col, out_col, udf_func) -> "Table":
        """(ref table.py:521)"""
        def f(d):
            d = d.copy()
            d[out_col] = d[in_col].map(udf_func)
            return d
        return self._map(f)

    def join(self, table: "Table", on=None, how="inner") -> "Table":
        """(ref table.py:534; hash-join via the gathered right side —
        the broadcast-join analog)"""
        right = table.to_pandas()
        on = _as_list(on) if on is not None else None
        return self._map(lambda d: d.merge(right, on=on, how=how))

    def show(self, n: int = 20, truncate: bool = True):
        """(ref table.py:268)"""
        print(self.to_pandas().head(n))

    def write_parquet(self, path: str, mode: str = "overwrite"):
        """(ref table.py:279)"""
        os.makedirs(path, exist_ok=True)
        for i, shard in enumerate(self.shards.collect()):
            shard.to_parquet(os.path.join(path, f"part-{i:05d}.parquet"))

    def col_names(self) -> List[str]:
        return list(self.shards.collect()[0].columns)


class FeatureTable(Table):
    """(ref table.py:282 FeatureTable)"""

    # ---------- categorical encoding ----------

    def gen_string_idx(self, columns, freq_limit: Optional[int] = None
                       ) -> List["StringIndex"]:
        """Build per-column StringIndex: value → 1-based id ordered by
        frequency desc (ref table.py:326 + Utils.scala; ids of frequent
        values are small so embedding tables stay cache-friendly).
        ``freq_limit`` drops values seen fewer times."""
        cols = _as_list(columns)
        full = self.to_pandas()
        out = []
        for c in cols:
            vc = full[c].dropna().value_counts()
            if freq_limit:
                vc = vc[vc >= int(freq_limit)]
            idx_df = pd.DataFrame({
                c: vc.index,
                "id": np.arange(1, len(vc) + 1, dtype=np.int64)})
            out.append(StringIndex(HostXShards([idx_df]), c))
        return out

    def encode_string(self, columns, indices) -> "FeatureTable":
        """Replace string values by their index id; unseen → 0
        (ref table.py:299)."""
        cols = _as_list(columns)
        if not isinstance(indices, list):
            indices = [indices]
        maps = []
        for ind in indices:
            if isinstance(ind, StringIndex):
                maps.append(ind.to_dict())
            else:
                maps.append(dict(ind))

        def f(d):
            d = d.copy()
            for c, m in zip(cols, maps):
                d[c] = d[c].map(m).fillna(0).astype(np.int64)
            return d
        return self._map(f)

    def gen_ind2ind(self, cols, indices) -> "FeatureTable":
        """Table of the indexed projection of ``cols`` (ref table.py:356)."""
        projected = self.encode_string(cols, indices).select(cols)
        return FeatureTable(projected.shards)

    def cross_columns(self, crossed_columns: List[List[str]],
                      bucket_sizes: List[int]) -> "FeatureTable":
        """Hash-cross column groups into buckets; new column is named
        ``a_b`` (ref table.py:371, the wide-and-deep cross features)."""
        def f(d):
            d = d.copy()
            for group, size in zip(crossed_columns, bucket_sizes):
                name = "_".join(group)
                joined = d[list(group)].astype(str).agg("_".join, axis=1)
                # vectorized, deterministic across runs and hosts
                d[name] = (pd.util.hash_pandas_object(joined, index=False)
                           % np.uint64(size)).astype(np.int64)
            return d
        return self._map(f)

    def category_encode(self, columns, freq_limit=None):
        indices = self.gen_string_idx(columns, freq_limit)
        return self.encode_string(columns, indices), indices

    # ---------- numeric ----------

    def normalize(self, columns) -> "FeatureTable":
        """Global min-max scale to [0,1] (ref table.py:382 MinMaxScaler)."""
        cols = _as_list(columns)
        full = self.to_pandas()
        lo = {c: float(full[c].min()) for c in cols}
        hi = {c: float(full[c].max()) for c in cols}

        def f(d):
            d = d.copy()
            for c in cols:
                span = hi[c] - lo[c]
                d[c] = 0.0 if span == 0 else (d[c] - lo[c]) / span
            return d
        return self._map(f)

    # ---------- recsys sequence features ----------

    def add_negative_samples(self, item_size: int, item_col: str = "item",
                             label_col: str = "label", neg_num: int = 1
                             ) -> "FeatureTable":
        """Each row becomes 1 positive (label 1) + ``neg_num`` negatives with
        a random different item (label 0) (ref table.py:429; item ids are
        1-based like the string-index output)."""
        def f(d):
            rng = np.random.RandomState(_shard_seed(d))
            rows = [d.assign(**{label_col: np.int64(1)})]
            for _ in range(neg_num):
                neg = d.copy()
                rand = rng.randint(1, item_size, size=len(d))
                # resample collisions with the positive item
                pos = d[item_col].to_numpy()
                coll = rand >= pos  # shift to skip the positive id
                rand = np.where(coll, rand + 1, rand)
                neg[item_col] = rand
                neg[label_col] = np.int64(0)
                rows.append(neg)
            return pd.concat(rows, ignore_index=True)
        return self._map(f)

    def add_hist_seq(self, user_col: str, cols, sort_col: str = "time",
                     min_len: int = 1, max_len: int = 100) -> "FeatureTable":
        """Per user (sorted by ``sort_col``) attach the preceding visit
        history as ``<col>_hist_seq`` lists; rows with history shorter than
        ``min_len`` are dropped (ref table.py:443)."""
        cols = _as_list(cols)
        full = self.to_pandas().sort_values([user_col, sort_col])
        out_rows = []
        for _, grp in full.groupby(user_col, sort=False):
            vals = {c: grp[c].tolist() for c in cols}
            for i in range(len(grp)):
                if i < min_len:
                    continue
                row = grp.iloc[i].to_dict()
                for c in cols:
                    row[f"{c}_hist_seq"] = vals[c][max(0, i - max_len):i]
                out_rows.append(row)
        out = pd.DataFrame(out_rows)
        return FeatureTable.from_pandas(
            out, self.shards.num_partitions()) if len(out) else \
            FeatureTable(HostXShards([out]))

    def add_neg_hist_seq(self, item_size: int, item_history_col: str,
                         neg_num: int) -> "FeatureTable":
        """For every history list attach ``neg_num`` random negative lists
        of the same length as ``neg_<col>`` (ref table.py:458)."""
        def f(d):
            rng = np.random.RandomState(_shard_seed(d))
            d = d.copy()
            d[f"neg_{item_history_col}"] = [
                [[int(x) for x in rng.randint(1, item_size + 1, size=len(h))]
                 for _ in range(neg_num)]
                for h in d[item_history_col]]
            return d
        return self._map(f)

    def pad(self, padding_cols, seq_len: int = 100) -> "FeatureTable":
        """Pad/truncate list columns to ``seq_len`` with 0
        (ref table.py:473; the ragged→static-shape bridge for jit)."""
        cols = _as_list(padding_cols)

        def pad_one(h):
            h = list(h)[:seq_len]
            if h and isinstance(h[0], (list, np.ndarray)):
                inner = len(h[0])
                h = [list(x) for x in h]
                return h + [[0] * inner] * (seq_len - len(h))
            return h + [0] * (seq_len - len(h))

        def f(d):
            d = d.copy()
            for c in cols:
                d[c] = d[c].map(pad_one)
            return d
        return self._map(f)

    def mask(self, mask_cols, seq_len: int = 100) -> "FeatureTable":
        """Attach ``<col>_mask`` 0/1 validity vectors (ref table.py:485)."""
        cols = _as_list(mask_cols)

        def f(d):
            d = d.copy()
            for c in cols:
                d[f"{c}_mask"] = d[c].map(
                    lambda h: [1] * min(len(h), seq_len) +
                              [0] * max(seq_len - len(h), 0))
            return d
        return self._map(f)

    def mask_pad(self, padding_cols, mask_cols, seq_len: int = 100
                 ) -> "FeatureTable":
        """(ref table.py:508)"""
        return self.mask(mask_cols, seq_len).pad(padding_cols, seq_len)

    def add_length(self, col_name: str) -> "FeatureTable":
        """Attach ``<col>_length`` (ref table.py:497)."""
        def f(d):
            d = d.copy()
            d[f"{col_name}_length"] = d[col_name].map(len)
            return d
        return self._map(f)

    def add_feature(self, item_cols, feature_tbl: "FeatureTable",
                    default_value) -> "FeatureTable":
        """Map item ids (scalars or lists) through a (key→feature) lookup
        table; the lookup's first column is the key, second the feature
        (ref table.py:548)."""
        cols = _as_list(item_cols)
        lookup_df = feature_tbl.to_pandas()
        key_c, val_c = lookup_df.columns[:2]
        lookup = dict(zip(lookup_df[key_c], lookup_df[val_c]))

        def get(v):
            if isinstance(v, (list, np.ndarray)):
                return [lookup.get(x, default_value) for x in v]
            return lookup.get(v, default_value)

        def f(d):
            d = d.copy()
            for c in cols:
                d[f"{c}_feature"] = d[c].map(get)
            return d
        return self._map(f)

    # ---------- model feed ----------

    def to_sharded_arrays(self, feature_cols, label_col: Optional[str] = None):
        """{'x': [...], 'y': ...} ndarray shards for Estimator.fit."""
        cols = _as_list(feature_cols)

        def f(d):
            xs = [np.stack(d[c].map(np.asarray).to_list())
                  if d[c].map(lambda v: isinstance(v, (list, np.ndarray))).any()
                  else d[c].to_numpy()
                  for c in cols]
            out = {"x": xs[0] if len(xs) == 1 else xs}
            if label_col:
                out["y"] = d[label_col].to_numpy()
            return out
        return self.shards.transform_shard(f)


class StringIndex(Table):
    """value→id mapping table (ref table.py:586)."""

    def __init__(self, shards: HostXShards, col_name: str):
        super().__init__(shards)
        self.col_name = col_name

    def _clone(self, shards):
        return StringIndex(shards, self.col_name)

    @classmethod
    def read_parquet(cls, paths, col_name: Optional[str] = None):
        """(ref table.py:596 — col name = the non-'id' column)"""
        t = Table.read_parquet(paths)
        cols = [c for c in t.col_names() if c != "id"]
        return cls(t.shards, col_name or cols[0])

    def to_dict(self) -> Dict:
        df = self.to_pandas()
        return dict(zip(df[self.col_name], df["id"]))

    def size(self) -> int:
        return super().size()
