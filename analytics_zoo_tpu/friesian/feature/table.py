"""Friesian FeatureTable: recsys tabular feature engineering.

Rebuild of ref ``pyzoo/zoo/friesian/feature/table.py`` (Table/FeatureTable/
StringIndex, 723 LoC) and the Scala kernels
``zoo/.../friesian/feature/Utils.scala:27-167``. The reference runs on Spark
DataFrames; here tables are ``HostXShards`` of pandas DataFrames, so every
per-row op is an embarrassingly parallel shard transform. The output of a
feature pipeline is fixed-shape int/float ndarrays ready for the jitted
train step — padding/masking (``pad``/``mask``) is the ragged→static bridge.

Two data-plane generations coexist (docs/data_plane.md):

* the **fast path** (default): hot transforms are fixed-width numpy kernels
  and aggregations (``gen_string_idx``, ``normalize``, ``median``,
  ``distinct``, ``size``) are map-side combines over shards via
  ``HostXShards.map_reduce_shard`` — nothing gathers the table, so
  ``DISK_n``/``NATIVE_n`` tiers keep their bounded residency end to end;
* the **legacy path** (``ZOO_DATA_VECTORIZE=0``): the original row-at-a-time
  bodies, kept as the bitwise-parity baseline (tests/test_friesian_parity.py
  runs both paths on the same inputs and compares element for element).
"""

from __future__ import annotations

import glob
import os
import re
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np
import pandas as pd

from analytics_zoo_tpu.data.shard import HostXShards


def _as_list(x) -> List[str]:
    return [x] if isinstance(x, str) else list(x)


def _fast_enabled() -> bool:
    """``ZOO_DATA_VECTORIZE=0`` restores every legacy body — row-wise
    kernels *and* gather-style aggregations — as one parity/bench toggle."""
    return os.environ.get("ZOO_DATA_VECTORIZE", "1").strip().lower() \
        not in ("0", "false", "off")


def _shard_seed(d: pd.DataFrame) -> int:
    """Deterministic, shard-content-dependent RNG seed: equal-length shards
    with different rows draw different randoms, and reruns reproduce."""
    hashable = d.select_dtypes(exclude=["object"])
    if hashable.shape[1] == 0:
        hashable = d.astype(str)
    h = pd.util.hash_pandas_object(hashable, index=False).to_numpy()
    return int(h.sum() % np.uint64(2**31 - 1))


# ------------------------------------------------------ vectorized kernels

def _pad_one_rowwise(h, seq_len: int):
    """The legacy pad kernel — kept for ragged-inner cells the rectangular
    fill cannot express, and as the ``ZOO_DATA_VECTORIZE=0`` baseline."""
    h = list(h)[:seq_len]
    if h and isinstance(h[0], (list, np.ndarray)):
        inner = len(h[0])
        h = [list(x) for x in h]
        return h + [[0] * inner] * (seq_len - len(h))
    return h + [0] * (seq_len - len(h))


def _pad_cells(col: pd.Series, seq_len: int) -> pd.Series:
    """Pad/truncate every cell of a list column to ``seq_len`` with a single
    preallocated ``(rows, seq_len)`` (or ``(rows, seq_len, inner)``) zeros
    fill per group. Bitwise-matches ``_pad_one_rowwise`` — including the
    quirk that an *empty* cell inside a nested-list column pads flat to
    ``[0]*seq_len`` (it carries no inner width to copy)."""
    values = list(col)
    out: List = [None] * len(values)
    flat_idx: List[int] = []
    nested: Dict[int, List] = {}
    for i, h in enumerate(values):
        if seq_len > 0 and len(h) and isinstance(h[0], (list, np.ndarray)):
            try:
                arr = np.asarray([np.asarray(x) for x in h[:seq_len]])
            except ValueError:
                arr = None
            if arr is None or arr.ndim != 2 or arr.dtype.kind not in "biuf":
                out[i] = _pad_one_rowwise(h, seq_len)  # ragged/odd inner
            else:
                nested.setdefault(arr.shape[1], []).append((i, arr))
        else:
            flat_idx.append(i)
    if flat_idx:
        lens = np.fromiter((min(len(values[i]), seq_len) for i in flat_idx),
                           np.int64, count=len(flat_idx))
        parts = [np.asarray(values[i][:seq_len])
                 for i in flat_idx if min(len(values[i]), seq_len)]
        flat = np.concatenate(parts) if parts else None
        if flat is not None and flat.dtype.kind not in "biuf":
            for i in flat_idx:
                out[i] = _pad_one_rowwise(values[i], seq_len)
        else:
            mat = np.zeros((len(flat_idx), seq_len),
                           dtype=np.int64 if flat is None else flat.dtype)
            if flat is not None:
                mat[np.arange(seq_len) < lens[:, None]] = flat
            for j, i in enumerate(flat_idx):
                out[i] = mat[j]
    for inner, items in nested.items():
        lens = np.fromiter((a.shape[0] for _, a in items), np.int64,
                           count=len(items))
        dtype = np.result_type(*(a.dtype for _, a in items))
        big = np.zeros((len(items), seq_len, inner), dtype=dtype)
        stacked = np.concatenate([a for _, a in items], axis=0)
        big[np.arange(seq_len) < lens[:, None]] = stacked.astype(
            dtype, copy=False)
        for j, (i, _) in enumerate(items):
            out[i] = big[j]
    return pd.Series(out, index=col.index, dtype=object)


def _mask_cells(col: pd.Series, seq_len: int) -> pd.Series:
    lens = np.fromiter((min(len(h), seq_len) for h in col),
                       np.int64, count=len(col))
    mat = (np.arange(seq_len) < lens[:, None]).astype(np.int64)
    return pd.Series(list(mat), index=col.index, dtype=object)


class Table:
    """Base distributed table (ref table.py:35)."""

    def __init__(self, shards: HostXShards):
        self.shards = shards

    # ---------- constructors ----------

    @classmethod
    def from_pandas(cls, df: pd.DataFrame, num_shards: Optional[int] = None):
        n = num_shards or 1
        idx = np.array_split(np.arange(len(df)), max(1, n))
        return cls(HostXShards([df.iloc[i].reset_index(drop=True) for i in idx]))

    @classmethod
    def read_parquet(cls, paths: Union[str, List[str]]):
        """(ref table.py:285)"""
        paths = _as_list(paths)
        files = []
        for p in paths:
            if os.path.isdir(p):
                files += [os.path.join(p, f) for f in sorted(os.listdir(p))
                          if f.endswith(".parquet")]
            else:
                files.append(p)
        dfs = [pd.read_parquet(f) for f in files]
        return cls(HostXShards(dfs))

    @classmethod
    def read_json(cls, paths: Union[str, List[str]], cols=None):
        """(ref table.py:296)"""
        dfs = [pd.read_json(p, lines=True) for p in _as_list(paths)]
        if cols:
            dfs = [d[_as_list(cols)] for d in dfs]
        return cls(HostXShards(dfs))

    # ---------- internals ----------

    def _clone(self, shards: HostXShards) -> "Table":
        return type(self)(shards)

    def _map(self, fn: Callable[[pd.DataFrame], pd.DataFrame],
             op: str = "map") -> "Table":
        return self._clone(self.shards.transform_shard(fn, op=op))

    def to_pandas(self) -> pd.DataFrame:
        dfs = self.shards.collect()
        return pd.concat(dfs, ignore_index=True) if dfs else pd.DataFrame()

    def compute(self) -> "Table":
        """(ref table.py:64 — materialize; shards are eager here)"""
        self.shards.cache()
        return self

    @property
    def df(self) -> pd.DataFrame:
        return self.to_pandas()

    @property
    def schema(self):
        # shard 0 only — collect() would re-read every DISK_n spill file
        return self.shards.first().dtypes

    def size(self) -> int:
        """(ref table.py:79)"""
        if _fast_enabled():
            return int(self.shards.map_reduce_shard(
                len, lambda a, b: a + b, op="size"))
        return sum(len(s) for s in self.shards.collect())

    def __len__(self):
        return self.size()

    # ---------- row/column ops ----------

    def select(self, *cols) -> "Table":
        cols = [c for group in cols for c in _as_list(group)]
        return self._map(lambda d: d[cols], op="select")

    def drop(self, *cols) -> "Table":
        """(ref table.py:94)"""
        drop = [c for group in cols for c in _as_list(group)]
        return self._map(lambda d: d.drop(columns=drop), op="drop")

    def fillna(self, value, columns: Optional[Sequence[str]]) -> "Table":
        """(ref table.py:106)"""
        def fill(d):
            d = d.copy()
            cols = _as_list(columns) if columns else list(d.columns)
            d[cols] = d[cols].fillna(value)
            return d
        return self._map(fill, op="fillna")

    def dropna(self, columns=None, how="any", thresh=None) -> "Table":
        """(ref table.py:132)"""
        kw = {"thresh": thresh} if thresh is not None else {"how": how}
        return self._map(lambda d: d.dropna(
            subset=_as_list(columns) if columns else None,
            **kw).reset_index(drop=True), op="dropna")

    def distinct(self) -> "Table":
        """(ref table.py:148). Fast path: per-shard dedup, then pairwise
        concat+dedup in shard order — same first-occurrence rows and order
        as the gathered dedup, without materializing the table."""
        if _fast_enabled():
            full = self.shards.map_reduce_shard(
                lambda d: d.drop_duplicates(),
                lambda a, b: pd.concat([a, b],
                                       ignore_index=True).drop_duplicates(),
                op="distinct").reset_index(drop=True)
        else:
            full = self.to_pandas().drop_duplicates().reset_index(drop=True)
        n = max(1, self.shards.num_partitions())
        idx = np.array_split(np.arange(len(full)), n)
        return self._clone(HostXShards(
            [full.iloc[i].reset_index(drop=True) for i in idx]))

    def filter(self, condition: Union[str, Callable]) -> "Table":
        """(ref table.py:155; condition is a pandas query string or a
        row-mask callable)"""
        if callable(condition):
            return self._map(
                lambda d: d[condition(d)].reset_index(drop=True), op="filter")
        return self._map(lambda d: d.query(condition).reset_index(drop=True),
                         op="filter")

    def rename(self, columns: Dict[str, str]) -> "Table":
        """(ref table.py:252)"""
        return self._map(lambda d: d.rename(columns=columns), op="rename")

    def clip(self, columns, min=None, max=None) -> "Table":
        """(ref table.py:166)"""
        cols = _as_list(columns)

        def f(d):
            d = d.copy()
            d[cols] = d[cols].clip(lower=min, upper=max)
            return d
        return self._map(f, op="clip")

    def log(self, columns, clipping: bool = True) -> "Table":
        """log(x + 1), clipping negatives to 0 first (ref table.py:188)"""
        cols = _as_list(columns)

        def f(d):
            d = d.copy()
            for c in cols:
                v = d[c].astype(float)
                if clipping:
                    v = v.clip(lower=0)
                d[c] = np.log1p(v)
            return d
        return self._map(f, op="log")

    def _medians(self, cols: List[str]) -> Dict[str, float]:
        """Per-column medians. Fast path gathers only the non-null *column
        values* (not the table) as per-shard partials."""
        if _fast_enabled():
            parts = self.shards.map_reduce_shard(
                lambda d: {c: d[c].dropna().to_numpy(dtype=float)
                           for c in cols},
                lambda a, b: {c: np.concatenate([a[c], b[c]]) for c in cols},
                op="median")
            return {c: (float(np.median(parts[c])) if parts[c].size
                        else float("nan")) for c in cols}
        full = self.to_pandas()
        return {c: full[c].median() for c in cols}

    def median(self, columns) -> "Table":
        """table of (column, median) (ref table.py:223)"""
        cols = _as_list(columns)
        meds = self._medians(cols)
        med = pd.DataFrame({"column": cols,
                            "median": [meds[c] for c in cols]})
        return Table.from_pandas(med, 1)

    def fill_median(self, columns) -> "Table":
        """(ref table.py:206)"""
        cols = _as_list(columns)
        meds = self._medians(cols)

        def f(d):
            d = d.copy()
            for c in cols:
                d[c] = d[c].fillna(meds[c])
            return d
        return self._map(f, op="fill_median")

    def merge_cols(self, columns, target: str) -> "Table":
        """merge columns into one array column (ref table.py:240; already a
        single numpy conversion per shard)"""
        cols = _as_list(columns)

        def f(d):
            d = d.copy()
            d[target] = d[cols].values.tolist()
            return d.drop(columns=cols)
        return self._map(f, op="merge_cols")

    def transform_python_udf(self, in_col, out_col, udf_func) -> "Table":
        """(ref table.py:521 — the explicit row-wise escape hatch)"""
        def f(d):
            d = d.copy()
            d[out_col] = d[in_col].map(udf_func)
            return d
        return self._map(f, op="python_udf")

    def join(self, table: "Table", on=None, how="inner") -> "Table":
        """(ref table.py:534; hash-join via the gathered right side —
        the broadcast-join analog)"""
        right = table.to_pandas()
        on = _as_list(on) if on is not None else None
        return self._map(lambda d: d.merge(right, on=on, how=how), op="join")

    def show(self, n: int = 20, truncate: bool = True):
        """(ref table.py:268). Streams shards until ``n`` rows — never
        materializes (or re-reads the spill files of) the whole table."""
        heads, got = [], 0
        for s in self.shards._iter_shards():
            heads.append(s.head(n - got))
            got += len(heads[-1])
            if got >= n:
                break
        print(pd.concat(heads, ignore_index=True) if heads
              else pd.DataFrame())

    def write_parquet(self, path: str, mode: str = "overwrite"):
        """(ref table.py:279). ``overwrite`` clears stale ``part-*.parquet``
        from a previous larger write; ``append`` continues the part
        numbering; anything else raises."""
        if mode not in ("overwrite", "append"):
            raise ValueError(
                f"write_parquet mode must be 'overwrite' or 'append', "
                f"got {mode!r}")
        os.makedirs(path, exist_ok=True)
        existing = sorted(glob.glob(os.path.join(path, "part-*.parquet")))
        if mode == "overwrite":
            for f in existing:
                os.remove(f)
            start = 0
        else:
            nums = [int(m.group(1)) for f in existing
                    if (m := re.search(r"part-(\d+)\.parquet$", f))]
            start = max(nums, default=-1) + 1
        for i, shard in enumerate(self.shards._iter_shards()):
            shard.to_parquet(
                os.path.join(path, f"part-{start + i:05d}.parquet"))

    def col_names(self) -> List[str]:
        # shard 0 only (satellite: collect() re-read every spill file)
        return list(self.shards.first().columns)


class FeatureTable(Table):
    """(ref table.py:282 FeatureTable)"""

    # ---------- categorical encoding ----------

    def gen_string_idx(self, columns, freq_limit: Optional[int] = None
                       ) -> List["StringIndex"]:
        """Build per-column StringIndex: value → 1-based id ordered by
        frequency desc (ref table.py:326 + Utils.scala; ids of frequent
        values are small so embedding tables stay cache-friendly).
        ``freq_limit`` drops values seen fewer times.

        Fast path: merged per-shard ``value_counts`` kept in first-appearance
        order, then one stable sort — ties break by first appearance, same
        as the gathered hashtable order, so both paths agree."""
        cols = _as_list(columns)
        if _fast_enabled():
            def mapper(d):
                out = {}
                for c in cols:
                    s = d[c].dropna()
                    out[c] = s.value_counts().reindex(pd.unique(s))
                return out

            def reducer(a, b):
                out = {}
                for c in cols:
                    merged = a[c].add(b[c], fill_value=0)
                    new = b[c].index[~b[c].index.isin(a[c].index)]
                    out[c] = merged.reindex(a[c].index.append(new))
                return out

            counts = self.shards.map_reduce_shard(mapper, reducer,
                                                  op="gen_string_idx")
            vcs = {c: counts[c].astype(np.int64).sort_values(
                ascending=False, kind="stable") for c in cols}
        else:
            full = self.to_pandas()
            vcs = {c: full[c].dropna().value_counts() for c in cols}
        out = []
        for c in cols:
            vc = vcs[c]
            if freq_limit:
                vc = vc[vc >= int(freq_limit)]
            idx_df = pd.DataFrame({
                c: vc.index,
                "id": np.arange(1, len(vc) + 1, dtype=np.int64)})
            out.append(StringIndex(HostXShards([idx_df]), c))
        return out

    def encode_string(self, columns, indices) -> "FeatureTable":
        """Replace string values by their index id; unseen → 0
        (ref table.py:299)."""
        cols = _as_list(columns)
        if not isinstance(indices, list):
            indices = [indices]
        maps = []
        for ind in indices:
            if isinstance(ind, StringIndex):
                maps.append(ind.to_dict())
            else:
                maps.append(dict(ind))

        def f(d):
            d = d.copy()
            for c, m in zip(cols, maps):
                d[c] = d[c].map(m).fillna(0).astype(np.int64)
            return d
        return self._map(f, op="encode_string")

    def gen_ind2ind(self, cols, indices) -> "FeatureTable":
        """Table of the indexed projection of ``cols`` (ref table.py:356)."""
        projected = self.encode_string(cols, indices).select(cols)
        return FeatureTable(projected.shards)

    def cross_columns(self, crossed_columns: List[List[str]],
                      bucket_sizes: List[int]) -> "FeatureTable":
        """Hash-cross column groups into buckets; new column is named
        ``a_b`` (ref table.py:371, the wide-and-deep cross features)."""
        def f(d):
            d = d.copy()
            for group, size in zip(crossed_columns, bucket_sizes):
                name = "_".join(group)
                joined = d[list(group)].astype(str).agg("_".join, axis=1)
                # vectorized, deterministic across runs and hosts
                d[name] = (pd.util.hash_pandas_object(joined, index=False)
                           % np.uint64(size)).astype(np.int64)
            return d
        return self._map(f, op="cross_columns")

    def category_encode(self, columns, freq_limit=None):
        indices = self.gen_string_idx(columns, freq_limit)
        return self.encode_string(columns, indices), indices

    # ---------- numeric ----------

    def normalize(self, columns) -> "FeatureTable":
        """Global min-max scale to [0,1] (ref table.py:382 MinMaxScaler).
        Fast path: per-shard (min, max) partials, NaN-skipping combine."""
        cols = _as_list(columns)
        if _fast_enabled():
            ext = self.shards.map_reduce_shard(
                lambda d: {c: (d[c].min(), d[c].max()) for c in cols},
                lambda a, b: {c: (np.fmin(a[c][0], b[c][0]),
                                  np.fmax(a[c][1], b[c][1])) for c in cols},
                op="normalize")
            lo = {c: float(ext[c][0]) for c in cols}
            hi = {c: float(ext[c][1]) for c in cols}
        else:
            full = self.to_pandas()
            lo = {c: float(full[c].min()) for c in cols}
            hi = {c: float(full[c].max()) for c in cols}

        def f(d):
            d = d.copy()
            for c in cols:
                span = hi[c] - lo[c]
                d[c] = 0.0 if span == 0 else (d[c] - lo[c]) / span
            return d
        return self._map(f, op="normalize")

    # ---------- recsys sequence features ----------

    def add_negative_samples(self, item_size: int, item_col: str = "item",
                             label_col: str = "label", neg_num: int = 1
                             ) -> "FeatureTable":
        """Each row becomes 1 positive (label 1) + ``neg_num`` negatives with
        a random different item (label 0) (ref table.py:429; item ids are
        1-based like the string-index output). The RNG seed derives from the
        shard *content* (``_shard_seed``), so parallel execution draws the
        same negatives as serial."""
        def f(d):
            rng = np.random.RandomState(_shard_seed(d))
            rows = [d.assign(**{label_col: np.int64(1)})]
            for _ in range(neg_num):
                neg = d.copy()
                rand = rng.randint(1, item_size, size=len(d))
                # resample collisions with the positive item
                pos = d[item_col].to_numpy()
                coll = rand >= pos  # shift to skip the positive id
                rand = np.where(coll, rand + 1, rand)
                neg[item_col] = rand
                neg[label_col] = np.int64(0)
                rows.append(neg)
            return pd.concat(rows, ignore_index=True)
        return self._map(f, op="negative_samples")

    def _add_hist_seq_legacy(self, user_col, cols, sort_col, min_len,
                             max_len) -> "FeatureTable":
        full = self.to_pandas().sort_values([user_col, sort_col])
        out_rows = []
        for _, grp in full.groupby(user_col, sort=False):
            vals = {c: grp[c].tolist() for c in cols}
            for i in range(len(grp)):
                if i < min_len:
                    continue
                row = grp.iloc[i].to_dict()
                for c in cols:
                    row[f"{c}_hist_seq"] = vals[c][max(0, i - max_len):i]
                out_rows.append(row)
        out = pd.DataFrame(out_rows)
        return FeatureTable.from_pandas(
            out, self.shards.num_partitions()) if len(out) else \
            FeatureTable(HostXShards([out]))

    def add_hist_seq(self, user_col: str, cols, sort_col: str = "time",
                     min_len: int = 1, max_len: int = 100) -> "FeatureTable":
        """Per user (sorted by ``sort_col``) attach the preceding visit
        history as ``<col>_hist_seq`` lists; rows with history shorter than
        ``min_len`` are dropped (ref table.py:443).

        Fast path: reshuffle by ``user_col`` (``partition_by``, so each
        user's rows land in one shard), then a per-shard sort + groupby with
        array-slice history building — no global gather, no per-row
        ``iloc``/``to_dict``. Row order is per-partition rather than global,
        which training never depends on (shards are shuffled downstream)."""
        cols = _as_list(cols)
        if not _fast_enabled():
            return self._add_hist_seq_legacy(user_col, cols, sort_col,
                                             min_len, max_len)
        parts = self.shards.partition_by(user_col,
                                         self.shards.num_partitions())

        def per_shard(d):
            def empty_like():
                out = d.iloc[0:0].copy()
                for c in cols:
                    out[f"{c}_hist_seq"] = pd.Series([], dtype=object)
                return out
            if not len(d):
                return empty_like()
            d2 = d.sort_values([user_col, sort_col], kind="stable")
            pieces = []
            for _, grp in d2.groupby(user_col, sort=False):
                if len(grp) <= min_len:
                    continue
                take = grp.iloc[min_len:].copy()
                for c in cols:
                    a = grp[c].to_numpy()
                    take[f"{c}_hist_seq"] = pd.Series(
                        [a[max(0, i - max_len):i].tolist()
                         for i in range(min_len, len(grp))],
                        index=take.index, dtype=object)
                pieces.append(take)
            if not pieces:
                return empty_like()
            return pd.concat(pieces, ignore_index=True)

        return FeatureTable(parts.transform_shard(per_shard,
                                                  op="add_hist_seq"))

    def add_neg_hist_seq(self, item_size: int, item_history_col: str,
                         neg_num: int) -> "FeatureTable":
        """For every history list attach ``neg_num`` random negative lists
        of the same length as ``neg_<col>`` (ref table.py:458)."""
        def f(d):
            rng = np.random.RandomState(_shard_seed(d))
            d = d.copy()
            d[f"neg_{item_history_col}"] = [
                [[int(x) for x in rng.randint(1, item_size + 1, size=len(h))]
                 for _ in range(neg_num)]
                for h in d[item_history_col]]
            return d
        return self._map(f, op="neg_hist_seq")

    def _pad_legacy(self, cols, seq_len) -> "FeatureTable":
        def f(d):
            d = d.copy()
            for c in cols:
                d[c] = d[c].map(lambda h: _pad_one_rowwise(h, seq_len))
            return d
        return self._map(f, op="pad")

    def pad(self, padding_cols, seq_len: int = 100) -> "FeatureTable":
        """Pad/truncate list columns to ``seq_len`` with 0
        (ref table.py:473; the ragged→static-shape bridge for jit)."""
        cols = _as_list(padding_cols)
        if not _fast_enabled():
            return self._pad_legacy(cols, seq_len)

        def f(d):
            d = d.copy()
            for c in cols:
                d[c] = _pad_cells(d[c], seq_len)
            return d
        return self._map(f, op="pad")

    def _mask_legacy(self, cols, seq_len) -> "FeatureTable":
        def f(d):
            d = d.copy()
            for c in cols:
                d[f"{c}_mask"] = d[c].map(
                    lambda h: [1] * min(len(h), seq_len) +
                              [0] * max(seq_len - len(h), 0))
            return d
        return self._map(f, op="mask")

    def mask(self, mask_cols, seq_len: int = 100) -> "FeatureTable":
        """Attach ``<col>_mask`` 0/1 validity vectors (ref table.py:485);
        int64 rows of one broadcast comparison on the fast path."""
        cols = _as_list(mask_cols)
        if not _fast_enabled():
            return self._mask_legacy(cols, seq_len)

        def f(d):
            d = d.copy()
            for c in cols:
                d[f"{c}_mask"] = _mask_cells(d[c], seq_len)
            return d
        return self._map(f, op="mask")

    def mask_pad(self, padding_cols, mask_cols, seq_len: int = 100
                 ) -> "FeatureTable":
        """(ref table.py:508)"""
        return self.mask(mask_cols, seq_len).pad(padding_cols, seq_len)

    def add_length(self, col_name: str) -> "FeatureTable":
        """Attach ``<col>_length`` (ref table.py:497)."""
        if not _fast_enabled():
            def g(d):
                d = d.copy()
                d[f"{col_name}_length"] = d[col_name].map(len)
                return d
            return self._map(g, op="add_length")

        def f(d):
            d = d.copy()
            d[f"{col_name}_length"] = np.fromiter(
                (len(h) for h in d[col_name]), np.int64, count=len(d))
            return d
        return self._map(f, op="add_length")

    def _add_feature_legacy(self, cols, lookup,
                            default_value) -> "FeatureTable":
        def get(v):
            if isinstance(v, (list, np.ndarray)):
                return [lookup.get(x, default_value) for x in v]
            return lookup.get(v, default_value)

        def f(d):
            d = d.copy()
            for c in cols:
                d[f"{c}_feature"] = d[c].map(get)
            return d
        return self._map(f, op="add_feature")

    def add_feature(self, item_cols, feature_tbl: "FeatureTable",
                    default_value) -> "FeatureTable":
        """Map item ids (scalars or lists) through a (key→feature) lookup
        table; the lookup's first column is the key, second the feature
        (ref table.py:548). Fast path: one sorted-key ``searchsorted`` take
        per column (list cells concatenated, looked up once, and split back
        by offsets)."""
        cols = _as_list(item_cols)
        lookup_df = feature_tbl.to_pandas()
        key_c, val_c = lookup_df.columns[:2]
        # dict first so duplicate keys resolve last-wins, like the legacy map
        lookup = dict(zip(lookup_df[key_c].tolist(),
                          lookup_df[val_c].tolist()))
        if not _fast_enabled():
            return self._add_feature_legacy(cols, lookup, default_value)
        keys = np.asarray(list(lookup.keys()))
        vals = np.asarray(list(lookup.values()))
        if keys.dtype.kind not in "biuf" or vals.dtype.kind not in "biuf":
            return self._add_feature_legacy(cols, lookup, default_value)
        order = np.argsort(keys, kind="stable")
        sk, sv = keys[order], vals[order]

        def take(arr):
            arr = np.asarray(arr)
            if not len(sk):
                return np.full(arr.shape, default_value)
            pos = np.clip(np.searchsorted(sk, arr), 0, len(sk) - 1)
            hit = sk[pos] == arr
            return np.where(hit, sv[pos], default_value)

        def f(d):
            d = d.copy()
            for c in cols:
                col = d[c]
                listy = [isinstance(v, (list, np.ndarray)) for v in col]
                if not any(listy):
                    d[f"{c}_feature"] = take(col.to_numpy())
                elif all(listy):
                    lens = np.fromiter((len(v) for v in col), np.int64,
                                       count=len(col))
                    flat = np.concatenate(
                        [np.asarray(v) for v in col]) if lens.sum() \
                        else np.zeros(0, sk.dtype)
                    looked = take(flat)
                    cells = [a.tolist() for a in np.split(
                        looked, np.cumsum(lens)[:-1])]
                    d[f"{c}_feature"] = pd.Series(cells, index=d.index,
                                                  dtype=object)
                else:
                    cells = [take(np.asarray(v)).tolist()
                             if isinstance(v, (list, np.ndarray))
                             else take(np.asarray([v]))[0].item()
                             for v in col]
                    d[f"{c}_feature"] = pd.Series(cells, index=d.index,
                                                  dtype=object)
            return d
        return self._map(f, op="add_feature")

    # ---------- model feed ----------

    def _to_sharded_arrays_legacy(self, cols, label_col):
        def f(d):
            xs = [np.stack(d[c].map(np.asarray).to_list())
                  if d[c].map(lambda v: isinstance(v, (list, np.ndarray))).any()
                  else d[c].to_numpy()
                  for c in cols]
            out = {"x": xs[0] if len(xs) == 1 else xs}
            if label_col:
                out["y"] = d[label_col].to_numpy()
            return out
        return self.shards.transform_shard(f, op="to_arrays")

    def to_sharded_arrays(self, feature_cols, label_col: Optional[str] = None):
        """{'x': [...], 'y': ...} ndarray shards for Estimator.fit; the fast
        path emits C-contiguous arrays ready for ``pad_to_rung``."""
        cols = _as_list(feature_cols)
        if not _fast_enabled():
            return self._to_sharded_arrays_legacy(cols, label_col)

        def f(d):
            xs = []
            for c in cols:
                col = d[c]
                if col.dtype == object and any(
                        isinstance(v, (list, np.ndarray)) for v in col):
                    arr = np.stack([np.asarray(v) for v in col])
                else:
                    arr = col.to_numpy()
                xs.append(np.ascontiguousarray(arr))
            out = {"x": xs[0] if len(xs) == 1 else xs}
            if label_col:
                out["y"] = np.ascontiguousarray(d[label_col].to_numpy())
            return out
        return self.shards.transform_shard(f, op="to_arrays")

    def to_streaming_dataset(self, feature_cols, label_col=None,
                             prefetch_depth: Optional[int] = None):
        """Feed ``Estimator.fit`` straight from the (possibly tiered) raw
        DataFrame shards: each window's pandas→numpy conversion runs on the
        data pool concurrently with device steps (``prefetch_depth``
        windows in flight; docs/data_plane.md)."""
        from analytics_zoo_tpu.data.dataset import StreamingShardedDataset
        return StreamingShardedDataset(self.shards,
                                       feature_cols=_as_list(feature_cols),
                                       label_cols=label_col,
                                       prefetch_depth=prefetch_depth)


class StringIndex(Table):
    """value→id mapping table (ref table.py:586)."""

    def __init__(self, shards: HostXShards, col_name: str):
        super().__init__(shards)
        self.col_name = col_name

    def _clone(self, shards):
        return StringIndex(shards, self.col_name)

    @classmethod
    def read_parquet(cls, paths, col_name: Optional[str] = None):
        """(ref table.py:596 — col name = the non-'id' column)"""
        t = Table.read_parquet(paths)
        cols = [c for c in t.col_names() if c != "id"]
        return cls(t.shards, col_name or cols[0])

    def to_dict(self) -> Dict:
        df = self.to_pandas()
        return dict(zip(df[self.col_name], df["id"]))

    def size(self) -> int:
        return super().size()
