"""Friesian: recsys feature engineering on the sharded data layer
(TPU-native rebuild of ref ``pyzoo/zoo/friesian/`` + Scala
``zoo/.../friesian/``)."""
