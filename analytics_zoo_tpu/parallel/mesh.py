"""Device mesh management.

This is the spine that replaces all four of the reference's communication
fabrics (SURVEY.md §2.6): BigDL AllReduceParameter-over-BlockManager
(ref zoo/.../keras/models/Topology.scala:1204), TF MultiWorkerMirrored gRPC
rings (ref pyzoo/zoo/orca/learn/tf2/tf_runner.py:281-318), gloo/Horovod
(ref torch_runner.py:136-152), and MXNet kvstore. On TPU, a single
``jax.sharding.Mesh`` + sharding specs makes XLA emit the collectives
(all-reduce / reduce-scatter / all-gather / all-to-all) over ICI/DCN directly;
there is no hand-written comm layer to maintain.

Canonical axis names (used by strategies, kernels and the model zoo):

- ``data``   — data parallel (batch dim)
- ``fsdp``   — parameter sharding over the data axis (ZeRO-3 analog)
- ``model``  — tensor parallel
- ``seq``    — sequence/context parallel (ring attention rides this axis)
- ``expert`` — MoE expert parallel
- ``pipe``   — pipeline stages
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"

_default_mesh = None


def build_mesh(axes: Optional[Sequence[str]] = None,
               shape: Optional[Sequence[int]] = None,
               devices=None,
               set_default: bool = True):
    """Create a ``jax.sharding.Mesh``.

    Defaults to a 1-D data-parallel mesh over all devices — the TPU analog of
    the reference's one-replica-per-core data parallelism
    (ref Topology.scala:1237 initThreadModels caches per-core replicas).

    ``shape`` may contain one ``-1`` which absorbs the remaining devices.
    """
    global _default_mesh
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)

    if axes is None:
        axes = (DATA_AXIS,)
    axes = tuple(axes)
    if shape is None:
        if len(axes) == 1:
            shape = (n,)
        else:
            raise ValueError("mesh_shape required when len(mesh_axes) > 1")
    shape = list(shape)
    if -1 in shape:
        i = shape.index(-1)
        rest = math.prod(s for s in shape if s != -1)
        if n % rest:
            raise ValueError(f"cannot infer -1 in mesh shape {shape} over {n} devices")
        shape[i] = n // rest
    if math.prod(shape) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")

    dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, axes)
    if set_default:
        _default_mesh = mesh
    return mesh


def get_default_mesh():
    """Return the process-wide default mesh, creating a 1-D data mesh lazily."""
    global _default_mesh
    if _default_mesh is None:
        build_mesh()
    return _default_mesh


def set_default_mesh(mesh):
    global _default_mesh
    _default_mesh = mesh


def mesh_axis_size(mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1) if hasattr(mesh.shape, "get") else dict(
        zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)


def place_on_mesh(tree, mesh, spec_fn):
    """Place a pytree of host arrays on the mesh as global jax.Arrays.

    ``spec_fn(ndarray) -> PartitionSpec`` chooses each leaf's layout. dtypes
    are canonicalised for device (f64→f32, i64→i32; x64 stays host-side).

    Replaces the reference's FeatureSet→DistributedDataSet minibatch handoff
    (ref zoo/.../feature/FeatureSet.scala:109) and the Spark→Ray shard
    transfer (ref pyzoo/zoo/orca/data/ray_xshards.py:67-94): data stays on the
    host that read it; ``make_array_from_process_local_data`` forms the global
    view without a central shuffle.
    """
    import jax
    from jax.sharding import NamedSharding

    def _one(x):
        a = np.asarray(x)
        if a.dtype == np.float64:
            a = a.astype(np.float32)
        elif a.dtype == np.int64:
            a = a.astype(np.int32)
        sharding = NamedSharding(mesh, spec_fn(a))
        if jax.process_count() == 1:
            return jax.device_put(a, sharding)
        return jax.make_array_from_process_local_data(sharding, a)

    return jax.tree_util.tree_map(_one, tree)


def local_batch_to_global(batch, mesh, axis_name: str = DATA_AXIS):
    """place_on_mesh with the default batch layout: leading dim sharded over
    ``axis_name``, everything else replicated."""
    from jax.sharding import PartitionSpec as P
    return place_on_mesh(
        batch, mesh, lambda a: P(axis_name, *([None] * (np.ndim(a) - 1))))
