"""Mesh-sharded model executables — one serving replica, many chips.

`ClusterServing` scales out by adding replicas (consumer-group fan-out,
PR 9); this module scales the *other* axis: a single replica whose model
is too big for one chip dispatches onto a ``ShardedExecutable`` — the
apply function AOT-compiled against a ``jax.sharding.Mesh`` with the
parameters partitioned by a :class:`~analytics_zoo_tpu.parallel.strategy.
ShardingStrategy` (tp / fsdp / dp rules, first match wins). The replica
seam above it (`InferenceModel`, the engine's assembly loop, the bucket
ladder) is unchanged: `ExecutableCache` keys on batch shape/dtype, and a
compiled sharded executable auto-places uncommitted host batches per its
compiled input shardings, so numpy batches from the serve thread hit the
mesh-lowered rungs directly.

Per-shard HBM accounting rides along: :meth:`ShardedExecutable.
shard_hbm_bytes` sums each parameter leaf's addressable shards by
device, publishing ``zoo_shard_hbm_bytes{shard}`` — the gauge that
*proves* no single device holds the full model.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.common import compile_ahead, telemetry
from analytics_zoo_tpu.parallel.strategy import ShardingStrategy


def _canonical(a):
    """Device-canonical host view of one leaf (f64→f32, i64→i32) —
    mirrors mesh.place_on_mesh so sharded params match unsharded ones."""
    if hasattr(a, "sharding"):            # already a committed jax.Array
        return a
    a = np.asarray(a)
    if a.dtype == np.float64:
        a = a.astype(np.float32)
    elif a.dtype == np.int64:
        a = a.astype(np.int32)
    return a


class ShardedExecutable:
    """An apply function + mesh-sharded params behind the cache seam.

    ``__call__(*batch)`` dispatches through a
    :class:`~analytics_zoo_tpu.common.compile_ahead.ExecutableCache`
    whose rungs were warmed with **sharded** avals (params carry their
    ``NamedSharding``, batch avals carry the strategy's batch spec), so
    the hot path never recompiles and never gathers the model onto one
    device.
    """

    def __init__(self, apply_fn, params, strategy="tp", *,
                 param_rules=None, mesh=None, devices=None,
                 name: str = "sharded"):
        import jax

        self.name = name
        self.strategy = ShardingStrategy.parse(strategy,
                                               param_rules=param_rules)
        if mesh is None:
            mesh = self.strategy.build_mesh(devices=devices,
                                            set_default=False)
        self.mesh = mesh
        shardings = self.strategy.param_shardings(params, mesh)
        host = jax.tree_util.tree_map(_canonical, params)
        self.params = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, s), host, shardings)
        self._jitted = telemetry.instrument_jit(apply_fn, name=name)
        self.cache = compile_ahead.ExecutableCache(self._jitted, name=name)
        self._m_shard_hbm = telemetry.get_registry().gauge(
            "zoo_shard_hbm_bytes",
            "Parameter bytes resident per mesh shard (device) — "
            "max(shard) < total proves the model never fits one device",
            ("shard",))
        self.shard_hbm_bytes()

    # ------------------------------------------------------------ avals
    def batch_sharding(self, ndim: int):
        from jax.sharding import NamedSharding
        return NamedSharding(self.mesh, self.strategy.batch_spec(ndim))

    def param_avals(self):
        """Params as avals that carry their shardings, so an AOT build
        lowers to exactly the executable the live dispatch needs."""
        import jax

        def aval(a):
            sh = getattr(a, "sharding", None)
            if sh is not None:
                try:
                    return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                sharding=sh)
                except TypeError:       # older jax: no sharding kwarg
                    pass
            return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)

        return jax.tree_util.tree_map(aval, self.params)

    def batch_avals(self, spec: Sequence[Tuple], rung: int):
        """Batch avals for one ladder rung, carrying the strategy's
        batch sharding. ``spec`` is the per-sample ``((shape, dtype),
        ...)`` form `InferenceModel` records."""
        import jax

        out = []
        for shape, dtype in spec:
            shp = (int(rung),) + tuple(shape)
            try:
                out.append(jax.ShapeDtypeStruct(
                    shp, dtype, sharding=self.batch_sharding(len(shp))))
            except TypeError:
                out.append(jax.ShapeDtypeStruct(shp, dtype))
        return tuple(out)

    def aval_set(self, spec, rung):
        return (self.param_avals(),) + self.batch_avals(spec, rung)

    # ---------------------------------------------------------- dispatch
    def __call__(self, *xs):
        return self.cache(self.params, *xs)

    def warm(self, spec, rungs, block: bool = True, cpu_also: bool = False):
        todo = [self.aval_set(spec, r) for r in rungs]
        if block:
            for avals in todo:
                self.cache.warm(*avals)
        else:
            self.cache.warm_async(todo, cpu_also=cpu_also)
        return self

    # ------------------------------------------------------ accounting
    @property
    def n_shards(self) -> int:
        return int(self.mesh.devices.size)

    def total_param_bytes(self) -> int:
        import jax
        return int(sum(int(getattr(leaf, "nbytes", 0))
                       for leaf in jax.tree_util.tree_leaves(self.params)))

    def shard_hbm_bytes(self, publish: bool = True) -> Dict[str, int]:
        """Parameter bytes resident on each mesh device, from the live
        arrays' addressable shards — real per-device accounting, not
        ``total / n`` arithmetic."""
        import jax

        totals: Dict[str, int] = {
            str(d.id): 0 for d in self.mesh.devices.flat}
        for leaf in jax.tree_util.tree_leaves(self.params):
            for s in getattr(leaf, "addressable_shards", ()):
                key = str(s.device.id)
                totals[key] = totals.get(key, 0) + int(s.data.nbytes)
        if publish:
            for shard, nbytes in totals.items():
                self._m_shard_hbm.labels(shard=shard).set(nbytes)
        return totals
