"""Pipeline parallelism — GPipe schedule over the ``pipe`` mesh axis.

NEW capability vs the reference (SURVEY.md §2.6: "TP / PP / SP / EP / CP —
absent in reference"; its only parallelism is per-core data parallel,
Topology.scala:1145-1550). The TPU idiom: identical pipeline stages hold
their parameters stacked on a leading stage dimension that is sharded over
the ``pipe`` axis; inside ``shard_map`` each device runs its stage and
hands activations to the next device with ``lax.ppermute`` over ICI, while
``lax.scan`` drives the microbatch schedule. Total ticks =
n_micro + n_stages - 1 (the GPipe bubble); grads flow through ppermute, so
the same ``jax.grad`` training path works unchanged.

Heterogeneous prologue/epilogue (embedding, head) stay outside the
pipelined region — they run data-parallel as usual.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import numpy as np

from analytics_zoo_tpu.parallel import mesh as mesh_lib


def stack_stage_params(params_list):
    """Stack S per-stage pytrees (identical structure) along a new leading
    stage axis — the layout ``gpipe`` expects (shard dim 0 over ``pipe``)."""
    import jax
    return jax.tree_util.tree_map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *params_list)


def gpipe(stage_fn: Callable, stacked_params, x, *, mesh=None,
          n_microbatches: int, axis: str = mesh_lib.PIPE_AXIS):
    """Run ``x`` through S pipeline stages with the GPipe schedule.

    - ``stage_fn(stage_params, activation) -> activation`` — one stage;
      activations must keep one shape across stages.
    - ``stacked_params``: pytree whose leaves have leading dim S
      (``stack_stage_params``), sharded over ``axis``.
    - ``x``: [batch, ...]; batch must divide into ``n_microbatches``.

    Returns [batch, ...] outputs, replicated over the pipe axis. Jittable
    and differentiable (use under ``jax.grad`` for training).
    """
    import inspect
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map as _smap
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as _smap
    # jax >= 0.8 renamed/removed check_rep; psum over the pipe axis yields
    # a replicated output either way
    _kw = {}
    sig = inspect.signature(_smap).parameters
    if "check_rep" in sig:
        _kw["check_rep"] = False
    elif "check_vma" in sig:
        _kw["check_vma"] = False
    shard_map = partial(_smap, **_kw)
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        mesh = mesh_lib.get_default_mesh()
    S = mesh_lib.mesh_axis_size(mesh, axis)
    if S < 2:
        raise ValueError(f"mesh has no usable {axis!r} axis: "
                         f"{dict(zip(mesh.axis_names, mesh.devices.shape))}")
    # split the batch over the data axis (when present) so each dp group
    # pipelines only its own slice — P() here would all-gather the global
    # batch and make every dp replica redundantly run all microbatches
    dp = mesh_lib.mesh_axis_size(mesh, mesh_lib.DATA_AXIS)
    batch_spec_axis = mesh_lib.DATA_AXIS if dp > 1 else None
    b = x.shape[0]
    M = int(n_microbatches)
    if b % (M * max(dp, 1)):
        raise ValueError(f"batch {b} not divisible by n_microbatches {M} "
                         f"x dp {dp}")
    mb = b // M // max(dp, 1)

    first = jax.tree_util.tree_leaves(stacked_params)[0]
    if first.shape[0] != S:
        raise ValueError(
            f"stacked params leading dim {first.shape[0]} != pipe size {S}")

    params_spec = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)
    x_spec = P(batch_spec_axis)

    @partial(shard_map, mesh=mesh, in_specs=(params_spec, x_spec),
             out_specs=x_spec)
    def run(p_stage, x_all):
        # p_stage leaves: [1, ...] (this device's stage) — drop the dim.
        # x_all: this dp group's batch slice [b/dp, ...]
        p_stage = jax.tree_util.tree_map(lambda a: a[0], p_stage)
        idx = jax.lax.axis_index(axis)
        micro = x_all.reshape((M, mb) + x_all.shape[1:])
        out_buf = jnp.zeros((M, mb) + x_all.shape[1:], x_all.dtype)
        carry0 = jnp.zeros((mb,) + x_all.shape[1:], x_all.dtype)

        def tick(state, t):
            carry, out_buf = state
            # stage 0 ingests microbatch t (clamped; masked later)
            feed = micro[jnp.minimum(t, M - 1)]
            inp = jnp.where(idx == 0, feed, carry)
            out = stage_fn(p_stage, inp)
            # last stage writes its result for microbatch t-(S-1)
            slot = jnp.clip(t - (S - 1), 0, M - 1)
            valid = jnp.logical_and(idx == S - 1, t >= S - 1)
            upd = jnp.where(valid, out, out_buf[slot])
            out_buf = jax.lax.dynamic_update_index_in_dim(out_buf, upd,
                                                          slot, 0)
            # hand activations down the pipe: i -> i+1 (ring; stage 0
            # ignores what it receives from S-1)
            nxt = jax.lax.ppermute(
                out, axis, [(i, (i + 1) % S) for i in range(S)])
            return (nxt, out_buf), None

        (_, out_buf), _ = jax.lax.scan(
            tick, (carry0, out_buf), jnp.arange(M + S - 1))
        # result lives on the last stage; replicate over the pipe axis
        out_buf = jnp.where(idx == S - 1, out_buf, 0.0)
        out_buf = jax.lax.psum(out_buf, axis)
        return out_buf.reshape((x_all.shape[0],) + x_all.shape[1:])

    return run(stacked_params, x)


class PipelinedMLP:
    """Convenience model: S identical Dense+activation stages pipelined
    over the pipe axis; prologue/epilogue dense layers replicated.

    Exposes ``init(rng, x)`` / ``apply(params, x)`` so it plugs into
    ``Estimator.from_fn`` — pipeline-parallel training through the standard
    engine."""

    def __init__(self, hidden: int, out_dim: int, n_stages: int,
                 n_microbatches: int = 4, mesh=None):
        self.hidden, self.out_dim = hidden, out_dim
        self.S, self.M = n_stages, n_microbatches
        self.mesh = mesh

    def init(self, rng, x):
        import jax
        k_in, k_stage, k_out = jax.random.split(rng, 3)
        f_in = x.shape[-1]
        scale = 1.0 / np.sqrt(self.hidden)
        return {
            "w_in": jax.random.normal(k_in, (f_in, self.hidden)) / np.sqrt(f_in),
            "stages": {
                "w": jax.random.normal(
                    k_stage, (self.S, self.hidden, self.hidden)) * scale,
                "b": np.zeros((self.S, self.hidden), np.float32),
            },
            "w_out": jax.random.normal(k_out, (self.hidden, self.out_dim))
            * scale,
        }

    def apply(self, params, x):
        import jax.numpy as jnp

        def stage_fn(p, h):
            return jnp.tanh(h @ p["w"] + p["b"])

        h = x @ params["w_in"]
        h = gpipe(stage_fn, params["stages"], h, mesh=self.mesh,
                  n_microbatches=self.M)
        return h @ params["w_out"]

    def param_rules(self):
        """Shard the stacked stage dim over ``pipe`` for the Estimator."""
        return [(r"stages/(w|b)", (mesh_lib.PIPE_AXIS,))]
