"""Pipeline parallelism — GPipe schedule over the ``pipe`` mesh axis.

NEW capability vs the reference (SURVEY.md §2.6: "TP / PP / SP / EP / CP —
absent in reference"; its only parallelism is per-core data parallel,
Topology.scala:1145-1550). The TPU idiom: identical pipeline stages hold
their parameters stacked on a leading stage dimension that is sharded over
the ``pipe`` axis; inside ``shard_map`` each device runs its stage and
hands activations to the next device with ``lax.ppermute`` over ICI, while
``lax.scan`` drives the microbatch schedule. Total ticks =
n_micro + n_stages - 1 (the GPipe bubble); grads flow through ppermute, so
the same ``jax.grad`` training path works unchanged.

Heterogeneous prologue/epilogue (embedding, head) stay outside the
pipelined region — they run data-parallel as usual.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import numpy as np

from analytics_zoo_tpu.parallel import mesh as mesh_lib


def _shard_map():
    """shard_map with the replication check disabled, across jax versions
    (jax >= 0.8 renamed check_rep → check_vma; older jax keeps it under
    experimental)."""
    import inspect
    try:
        from jax import shard_map as smap
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as smap
    sig = inspect.signature(smap).parameters
    kw = {}
    if "check_rep" in sig:
        kw["check_rep"] = False
    elif "check_vma" in sig:
        kw["check_vma"] = False
    return partial(smap, **kw)


def _batch_layout(mesh, axis, batch: int, n_microbatches: int):
    """(pipe size S, dp size, batch spec axis, microbatch rows mb); raises
    when the batch does not divide over microbatches × dp."""
    S = mesh_lib.mesh_axis_size(mesh, axis)
    dp = mesh_lib.mesh_axis_size(mesh, mesh_lib.DATA_AXIS)
    batch_axis = mesh_lib.DATA_AXIS if dp > 1 else None
    M = int(n_microbatches)
    if batch % (M * max(dp, 1)):
        raise ValueError(f"batch {batch} not divisible by n_microbatches "
                         f"{M} x dp {dp}")
    return S, dp, batch_axis, batch // M // max(dp, 1)


def stack_stage_params(params_list):
    """Stack S per-stage pytrees (identical structure) along a new leading
    stage axis — the layout ``gpipe`` expects (shard dim 0 over ``pipe``)."""
    import jax
    return jax.tree_util.tree_map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *params_list)


def gpipe(stage_fn: Callable, stacked_params, x, *, mesh=None,
          n_microbatches: int, axis: str = mesh_lib.PIPE_AXIS):
    """Run ``x`` through S pipeline stages with the GPipe schedule.

    - ``stage_fn(stage_params, activation) -> activation`` — one stage;
      activations must keep one shape across stages.
    - ``stacked_params``: pytree whose leaves have leading dim S
      (``stack_stage_params``), sharded over ``axis``.
    - ``x``: [batch, ...]; batch must divide into ``n_microbatches``.

    Returns [batch, ...] outputs, replicated over the pipe axis. Jittable
    and differentiable (use under ``jax.grad`` for training).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    shard_map = _shard_map()
    if mesh is None:
        mesh = mesh_lib.get_default_mesh()
    if mesh_lib.mesh_axis_size(mesh, axis) < 2:
        raise ValueError(f"mesh has no usable {axis!r} axis: "
                         f"{dict(zip(mesh.axis_names, mesh.devices.shape))}")
    # split the batch over the data axis (when present) so each dp group
    # pipelines only its own slice — P() here would all-gather the global
    # batch and make every dp replica redundantly run all microbatches
    S, dp, batch_spec_axis, mb = _batch_layout(mesh, axis, x.shape[0],
                                               n_microbatches)
    M = int(n_microbatches)

    first = jax.tree_util.tree_leaves(stacked_params)[0]
    if first.shape[0] != S:
        raise ValueError(
            f"stacked params leading dim {first.shape[0]} != pipe size {S}")

    params_spec = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)
    x_spec = P(batch_spec_axis)

    @partial(shard_map, mesh=mesh, in_specs=(params_spec, x_spec),
             out_specs=x_spec)
    def run(p_stage, x_all):
        # p_stage leaves: [1, ...] (this device's stage) — drop the dim.
        # x_all: this dp group's batch slice [b/dp, ...]
        p_stage = jax.tree_util.tree_map(lambda a: a[0], p_stage)
        idx = jax.lax.axis_index(axis)
        micro = x_all.reshape((M, mb) + x_all.shape[1:])
        out_buf = jnp.zeros((M, mb) + x_all.shape[1:], x_all.dtype)
        carry0 = jnp.zeros((mb,) + x_all.shape[1:], x_all.dtype)

        def tick(state, t):
            carry, out_buf = state
            # stage 0 ingests microbatch t (clamped; masked later)
            feed = micro[jnp.minimum(t, M - 1)]
            inp = jnp.where(idx == 0, feed, carry)
            out = stage_fn(p_stage, inp)
            # last stage writes its result for microbatch t-(S-1)
            slot = jnp.clip(t - (S - 1), 0, M - 1)
            valid = jnp.logical_and(idx == S - 1, t >= S - 1)
            upd = jnp.where(valid, out, out_buf[slot])
            out_buf = jax.lax.dynamic_update_index_in_dim(out_buf, upd,
                                                          slot, 0)
            # hand activations down the pipe: i -> i+1 (ring; stage 0
            # ignores what it receives from S-1)
            nxt = jax.lax.ppermute(
                out, axis, [(i, (i + 1) % S) for i in range(S)])
            return (nxt, out_buf), None

        (_, out_buf), _ = jax.lax.scan(
            tick, (carry0, out_buf), jnp.arange(M + S - 1))
        # result lives on the last stage; replicate over the pipe axis
        out_buf = jnp.where(idx == S - 1, out_buf, 0.0)
        out_buf = jax.lax.psum(out_buf, axis)
        return out_buf.reshape((x_all.shape[0],) + x_all.shape[1:])

    return run(stacked_params, x)


def pack_stage_params(params_list):
    """Pack S per-stage pytrees of DIFFERENT structures into one
    ``[S, maxlen]`` float array (rows zero-padded) + the per-stage unravel
    functions. The packed array shards row-wise over ``pipe`` — that is
    how heterogeneous stages (embedding / block / head) become one SPMD
    tensor."""
    import jax
    from jax.flatten_util import ravel_pytree

    flats, unravels, sizes = [], [], []
    for p in params_list:
        flat, unravel = ravel_pytree(p)
        flats.append(np.asarray(flat, np.float32))
        unravels.append(unravel)
        sizes.append(flat.size)
    maxlen = max(sizes)
    packed = np.stack([np.pad(f, (0, maxlen - f.size)) for f in flats])
    return packed, unravels, sizes


def gpipe_hetero(stage_fns, unravels, sizes, packed, feed, *, mesh=None,
                 n_microbatches: int, act_shape, out_shape,
                 act_dtype=None, out_dtype=None,
                 axis: str = mesh_lib.PIPE_AXIS):
    """GPipe over HETEROGENEOUS stages (embedding → blocks → head all
    inside the schedule).

    SPMD trick: every device runs the same program; ``lax.switch`` on the
    device's stage index selects its branch, which slices+unravels its row
    of ``packed`` into that stage's real param pytree and applies its own
    computation. Contract for ``stage_fns[s](params_s, act, feed_mb)``:
    returns ``(act_out, final_out)`` where ``act_out`` has per-microbatch
    shape ``(mb,) + act_shape`` for EVERY stage (the ppermute carry) and
    ``final_out`` has ``(mb,) + out_shape`` (zeros except on the last
    stage). ``feed``: the raw per-example model input (e.g. token ids),
    consumed by stage 0.

    Differentiable in ``packed`` — the whole pipeline trains through the
    standard Estimator with a ``pipe``-sharded parameter row per device.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    smap = _shard_map()
    if mesh is None:
        mesh = mesh_lib.get_default_mesh()
    if mesh_lib.mesh_axis_size(mesh, axis) != len(stage_fns):
        raise ValueError(f"{len(stage_fns)} stages but pipe axis size "
                         f"{mesh_lib.mesh_axis_size(mesh, axis)}")
    S, dp, batch_axis, mb = _batch_layout(mesh, axis, feed.shape[0],
                                          n_microbatches)
    M = int(n_microbatches)
    act_dtype = act_dtype or jnp.float32
    out_dtype = out_dtype or jnp.float32

    def make_branch(s):
        def branch(vec, act, tok):
            p = unravels[s](vec[:sizes[s]])
            return stage_fns[s](p, act, tok)
        return branch

    branches = [make_branch(s) for s in range(S)]

    @partial(smap, mesh=mesh, in_specs=(P(axis), P(batch_axis)),
             out_specs=P(batch_axis))
    def run(p_rows, feed_all):
        vec = p_rows[0]                       # this device's stage row
        idx = jax.lax.axis_index(axis)
        micro = feed_all.reshape((M, mb) + feed_all.shape[1:])
        carry0 = jnp.zeros((mb,) + tuple(act_shape), act_dtype)
        out_buf = jnp.zeros((M, mb) + tuple(out_shape), out_dtype)

        def tick(state, t):
            carry, out_buf = state
            tok = micro[jnp.minimum(t, M - 1)]
            act_out, fin = jax.lax.switch(idx, branches, vec, carry, tok)
            slot = jnp.clip(t - (S - 1), 0, M - 1)
            valid = jnp.logical_and(idx == S - 1, t >= S - 1)
            upd = jnp.where(valid, fin, out_buf[slot])
            out_buf = jax.lax.dynamic_update_index_in_dim(out_buf, upd,
                                                          slot, 0)
            nxt = jax.lax.ppermute(
                act_out, axis, [(i, (i + 1) % S) for i in range(S)])
            return (nxt, out_buf), None

        (_, out_buf), _ = jax.lax.scan(
            tick, (carry0, out_buf), jnp.arange(M + S - 1))
        out_buf = jnp.where(idx == S - 1, out_buf, 0.0)
        out_buf = jax.lax.psum(out_buf, axis)
        return out_buf.reshape((feed_all.shape[0],) + tuple(out_shape))

    return run(packed, feed)


def _ln(x, g, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    import jax
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def _block_apply(p, h, nh):
    """Pre-LN causal transformer block on [mb, L, D] (plain-pytree params:
    the pipelined region cannot use flax modules — stage params are
    unraveled from the packed row). ``nh``: static head count."""
    import jax
    import jax.numpy as jnp

    D = h.shape[-1]
    x = _ln(h, p["ln1_g"], p["ln1_b"])
    q = x @ p["wq"]
    k = x @ p["wk"]
    v = x @ p["wv"]
    L = h.shape[1]
    hd = D // nh
    def split(a):
        return a.reshape(a.shape[0], L, nh, hd)
    scores = jnp.einsum("bqhd,bkhd->bhqk", split(q), split(k)) / np.sqrt(hd)
    cmask = jnp.tril(jnp.ones((L, L), bool))
    scores = jnp.where(cmask, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores, axis=-1)
    attn = jnp.einsum("bhqk,bkhd->bqhd", probs, split(v))
    h = h + attn.reshape(h.shape[0], L, D) @ p["wo"]
    x = _ln(h, p["ln2_g"], p["ln2_b"])
    h = h + jax.nn.gelu(x @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]
    return h


class PipelinedTransformerLM:
    """Causal transformer LM whose embedding, blocks AND head all live
    inside the gpipe schedule (heterogeneous stages): stage 0 =
    embedding + block, middle stages = block, last stage = block + LM
    head. Plugs into ``Estimator.from_fn`` for dp×pp training; the single
    trainable leaf is the pipe-sharded packed parameter matrix.

    ``apply_sequential`` runs the identical stages without the pipeline —
    the correctness oracle the tests compare against."""

    def __init__(self, vocab: int, d_model: int = 32, n_heads: int = 4,
                 d_ff: int = 64, seq_len: int = 16, n_stages: int = 4,
                 n_microbatches: int = 4, mesh=None):
        self.vocab, self.D, self.nh = vocab, d_model, n_heads
        self.d_ff, self.L = d_ff, seq_len
        self.S, self.M = n_stages, n_microbatches
        self.mesh = mesh
        self._unravels = None
        self._sizes = None

    # ---- per-stage param construction ----
    def _block_params(self, rng):
        import jax
        D, F = self.D, self.d_ff
        ks = jax.random.split(rng, 6)
        s = 1.0 / np.sqrt(D)
        return {
            "ln1_g": np.ones((D,), np.float32),
            "ln1_b": np.zeros((D,), np.float32),
            "ln2_g": np.ones((D,), np.float32),
            "ln2_b": np.zeros((D,), np.float32),
            "wq": np.asarray(jax.random.normal(ks[0], (D, D))) * s,
            "wk": np.asarray(jax.random.normal(ks[1], (D, D))) * s,
            "wv": np.asarray(jax.random.normal(ks[2], (D, D))) * s,
            "wo": np.asarray(jax.random.normal(ks[3], (D, D))) * s,
            "w1": np.asarray(jax.random.normal(ks[4], (D, F))) * s,
            "b1": np.zeros((F,), np.float32),
            "w2": np.asarray(jax.random.normal(ks[5], (F, D)))
            / np.sqrt(F),
            "b2": np.zeros((D,), np.float32),
        }

    def _stage_param_list(self, rng):
        import jax
        keys = jax.random.split(rng, self.S + 3)
        stages = []
        for s in range(self.S):
            p = {"block": self._block_params(keys[s])}
            if s == 0:
                p["emb"] = np.asarray(jax.random.normal(
                    keys[-3], (self.vocab, self.D))) * 0.02
                p["pos"] = np.asarray(jax.random.normal(
                    keys[-2], (self.L, self.D))) * 0.02
            if s == self.S - 1:
                p["head"] = np.asarray(jax.random.normal(
                    keys[-1], (self.D, self.vocab))) / np.sqrt(self.D)
            stages.append(p)
        return stages

    # ---- stage functions (gpipe_hetero contract) ----
    def _stage_fns(self):
        import jax.numpy as jnp
        V, L, D, nh = self.vocab, self.L, self.D, self.nh

        def first(p, act, tok):
            h = p["emb"][tok.astype(jnp.int32)] + p["pos"][None, :, :]
            h = _block_apply(p["block"], h, nh)
            return h, jnp.zeros((tok.shape[0], L, V), jnp.float32)

        def mid(p, act, tok):
            h = _block_apply(p["block"], act, nh)
            return h, jnp.zeros((act.shape[0], L, V), jnp.float32)

        def last(p, act, tok):
            h = _block_apply(p["block"], act, nh)
            return h, _ln(h, jnp.ones((D,)), jnp.zeros((D,))) @ p["head"]

        return [first] + [mid] * (self.S - 2) + [last]

    # ---- Estimator.from_fn surface ----
    def init(self, rng, tokens):
        packed, unravels, sizes = pack_stage_params(
            self._stage_param_list(rng))
        self._unravels, self._sizes = unravels, sizes
        return {"pipe": packed}

    def apply(self, params, tokens):
        assert self._unravels is not None, "init first"
        return gpipe_hetero(
            self._stage_fns(), self._unravels, self._sizes,
            params["pipe"], tokens, mesh=self.mesh,
            n_microbatches=self.M, act_shape=(self.L, self.D),
            out_shape=(self.L, self.vocab))

    def apply_sequential(self, params, tokens):
        """Same stages, no pipeline — the correctness oracle."""
        import jax.numpy as jnp
        fns = self._stage_fns()
        act = jnp.zeros((tokens.shape[0], self.L, self.D))
        out = None
        for s, fn in enumerate(fns):
            vec = params["pipe"][s][:self._sizes[s]]
            act, out = fn(self._unravels[s](vec), act, tokens)
        return out

    def param_rules(self):
        return [(r"pipe", (mesh_lib.PIPE_AXIS,))]


class PipelinedMLP:
    """Convenience model: S identical Dense+activation stages pipelined
    over the pipe axis; prologue/epilogue dense layers replicated.

    Exposes ``init(rng, x)`` / ``apply(params, x)`` so it plugs into
    ``Estimator.from_fn`` — pipeline-parallel training through the standard
    engine."""

    def __init__(self, hidden: int, out_dim: int, n_stages: int,
                 n_microbatches: int = 4, mesh=None):
        self.hidden, self.out_dim = hidden, out_dim
        self.S, self.M = n_stages, n_microbatches
        self.mesh = mesh

    def init(self, rng, x):
        import jax
        k_in, k_stage, k_out = jax.random.split(rng, 3)
        f_in = x.shape[-1]
        scale = 1.0 / np.sqrt(self.hidden)
        return {
            "w_in": jax.random.normal(k_in, (f_in, self.hidden)) / np.sqrt(f_in),
            "stages": {
                "w": jax.random.normal(
                    k_stage, (self.S, self.hidden, self.hidden)) * scale,
                "b": np.zeros((self.S, self.hidden), np.float32),
            },
            "w_out": jax.random.normal(k_out, (self.hidden, self.out_dim))
            * scale,
        }

    def apply(self, params, x):
        import jax.numpy as jnp

        def stage_fn(p, h):
            return jnp.tanh(h @ p["w"] + p["b"])

        h = x @ params["w_in"]
        h = gpipe(stage_fn, params["stages"], h, mesh=self.mesh,
                  n_microbatches=self.M)
        return h @ params["w_out"]

    def param_rules(self):
        """Shard the stacked stage dim over ``pipe`` for the Estimator."""
        return [(r"stages/(w|b)", (mesh_lib.PIPE_AXIS,))]
