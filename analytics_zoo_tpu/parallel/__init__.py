from analytics_zoo_tpu.parallel.mesh import build_mesh, get_default_mesh  # noqa: F401
from analytics_zoo_tpu.parallel.sharded_executable import ShardedExecutable  # noqa: F401
from analytics_zoo_tpu.parallel.strategy import ShardingStrategy  # noqa: F401
