"""Sharding strategies — parallelism as a first-class Estimator option.

The reference supports *only* synchronous data parallelism (SURVEY.md §2.6:
"TP / PP / SP / EP / CP — absent in reference"). Here every strategy is a
declarative sharding layout over the mesh; ``pjit`` lowers it to XLA
collectives:

- DP    — batch split over ``data``; params replicated; XLA inserts the
          gradient all-reduce (replaces BigDL AllReduceParameter,
          ref Topology.scala:1204).
- FSDP  — params/opt-state sharded over ``fsdp`` (reduce-scatter + all-gather).
- TP    — tensor parallel over ``model`` via per-parameter rules.
- SP/CP — sequence dim over ``seq`` (ring attention, ops/ring_attention.py).
- EP    — experts over ``expert``.

Spell: ``"dp"``, ``"fsdp"``, ``"dp2,tp4"``, ``"dp2,sp2,tp2"`` — sizes omitted
or ``-1`` absorb the remaining devices.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from analytics_zoo_tpu.parallel import mesh as mesh_lib

_TOKEN_RE = re.compile(r"^(dp|fsdp|tp|sp|ep|pp)(-?\d*)$")

_AXIS_OF = {
    "dp": mesh_lib.DATA_AXIS,
    "fsdp": mesh_lib.FSDP_AXIS,
    "tp": mesh_lib.MODEL_AXIS,
    "sp": mesh_lib.SEQ_AXIS,
    "ep": mesh_lib.EXPERT_AXIS,
    "pp": mesh_lib.PIPE_AXIS,
}


@dataclass
class ShardingStrategy:
    """A mesh layout + parameter partition rules.

    ``param_rules``: list of ``(path_regex, PartitionSpec-as-tuple)`` tried in
    order against the '/'-joined parameter path; first match wins. Unmatched
    params are replicated (or fsdp-sharded if fsdp is active).
    """

    sizes: List[Tuple[str, int]] = field(default_factory=lambda: [("dp", -1)])
    param_rules: List[Tuple[str, Tuple]] = field(default_factory=list)

    @classmethod
    def parse(cls, spec: "str | ShardingStrategy | None",
              param_rules=None) -> "ShardingStrategy":
        if spec is None:
            return cls(param_rules=list(param_rules or []))
        if isinstance(spec, ShardingStrategy):
            return spec
        sizes = []
        for tok in str(spec).replace(" ", "").split(","):
            if not tok:
                continue
            m = _TOKEN_RE.match(tok)
            if not m:
                raise ValueError(f"bad strategy token {tok!r}; expected e.g. dp, tp2, fsdp-1")
            kind, num = m.group(1), m.group(2)
            sizes.append((kind, int(num) if num not in ("", "-") else -1))
        if not any(k == "dp" for k, _ in sizes) and not any(n == -1 for _, n in sizes):
            sizes.insert(0, ("dp", -1))
        return cls(sizes=sizes, param_rules=list(param_rules or []))

    # ---- mesh ----
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(_AXIS_OF[k] for k, _ in self.sizes)

    def build_mesh(self, devices=None, set_default: bool = True):
        shape = [n for _, n in self.sizes]
        if sum(1 for n in shape if n == -1) > 1:
            raise ValueError("at most one -1 axis size")
        return mesh_lib.build_mesh(axes=self.axis_names(), shape=shape,
                                   devices=devices, set_default=set_default)

    @property
    def uses(self):
        return {k for k, _ in self.sizes}

    # ---- shardings ----
    def batch_axes(self) -> Tuple[str, ...]:
        axes = []
        if "dp" in self.uses:
            axes.append(mesh_lib.DATA_AXIS)
        if "fsdp" in self.uses:
            axes.append(mesh_lib.FSDP_AXIS)
        return tuple(axes)

    def batch_spec(self, ndim: int):
        from jax.sharding import PartitionSpec as P
        axes = self.batch_axes()
        lead = axes if len(axes) != 1 else axes[0]
        return P(lead, *([None] * (ndim - 1))) if axes else P()

    def batch_feed_fraction(self, mesh) -> float:
        """Fraction of each GLOBAL batch this process must supply to
        ``make_array_from_process_local_data`` under this strategy's batch
        sharding: ``1/process_count`` when the batch axes span the
        processes (the standard data-parallel feed, each host provides its
        contiguous block), ``1.0`` when the batch is replicated across
        processes (pure tp/pp layouts — every host must feed the FULL
        global batch, so callers give every process the full dataset)."""
        import jax
        if jax.process_count() == 1:
            return 1.0
        from jax.sharding import NamedSharding
        n = 1
        for ax in self.batch_axes():
            n *= mesh_lib.mesh_axis_size(mesh, ax)
        if n <= 1:
            return 1.0          # batch replicated: everyone feeds all rows
        sh = NamedSharding(mesh, self.batch_spec(1))
        imap = sh.addressable_devices_indices_map((n,))
        starts = sorted({(s[0].start or 0) for s in imap.values()})
        if len(starts) == n:
            # The batch IS sharded but every index is process-local (e.g.
            # "tp2,dp4" on 2 hosts: the model axis spans the processes, so
            # each host's devices cover all data indices). Feeding each
            # host's LOCAL data slice here would give the cross-process
            # replicas of every batch shard DIFFERENT rows — silently
            # wrong gradients. Refuse instead of guessing.
            raise ValueError(
                f"strategy {self}: the batch axes {self.batch_axes()} do "
                f"not span the processes (every batch index is local to "
                f"each host) — put the batch axes first in the strategy "
                f"(process-major, e.g. 'dp2,tp4' not 'tp4,dp2') so each "
                f"host feeds its own contiguous block")
        pc, pid = jax.process_count(), jax.process_index()
        h = n // pc
        if starts != list(range(pid * h, (pid + 1) * h)):
            raise ValueError(
                f"strategy {self}: batch rows owned by process {pid} are "
                f"{starts}, not the contiguous block the per-host feed "
                f"contract requires — reorder the mesh axes so the batch "
                f"axes are process-major (e.g. dp first)")
        return 1.0 / pc

    def param_spec(self, path: str, shape: Sequence[int], mesh):
        """PartitionSpec for one parameter. A rule whose sharded dims don't
        divide by the mesh axis size is dropped for that parameter, which
        then gets the default layout (fsdp sharding when the fsdp axis is
        active, else replication) — e.g. a 5-class output head under tp2.
        Rules referencing axes absent from the mesh are inapplicable and
        skipped (so stale tp/ep rules survive a strategy downgrade to a
        plain dp mesh instead of crashing)."""
        from jax.sharding import PartitionSpec as P
        for pattern, spec in self.param_rules:
            if re.search(pattern, path):
                if not self._axes_in_mesh(spec, mesh):
                    continue
                if self._divisible(spec, shape, mesh):
                    return P(*spec)
                break
        if "fsdp" in self.uses:
            size = mesh_lib.mesh_axis_size(mesh, mesh_lib.FSDP_AXIS)
            # shard the largest divisible dim
            order = sorted(range(len(shape)), key=lambda i: -shape[i])
            for i in order:
                if shape[i] % size == 0 and shape[i] >= size:
                    spec = [None] * len(shape)
                    spec[i] = mesh_lib.FSDP_AXIS
                    return P(*spec)
        return P()

    @staticmethod
    def _axes_in_mesh(spec, mesh) -> bool:
        names = set(mesh.axis_names)
        for axes in spec:
            if axes is None:
                continue
            for ax in (axes if isinstance(axes, tuple) else (axes,)):
                if ax not in names:
                    return False
        return True

    @staticmethod
    def _divisible(spec, shape, mesh) -> bool:
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        if len(spec) > len(shape):
            return False
        for dim, axes in enumerate(spec):
            if axes is None:
                continue
            axes = axes if isinstance(axes, tuple) else (axes,)
            total = 1
            for ax in axes:
                total *= sizes.get(ax, 1)
            if total > 1 and shape[dim] % total:
                return False
        return True

    def param_shardings(self, params, mesh):
        """NamedSharding pytree matching ``params``."""
        import jax
        from jax.sharding import NamedSharding

        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        out = []
        for path, leaf in flat:
            path_str = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                                for p in path)
            spec = self.param_spec(path_str, getattr(leaf, "shape", ()), mesh)
            out.append(NamedSharding(mesh, spec))
        return jax.tree_util.tree_unflatten(treedef, out)

    def __str__(self):
        return ",".join(f"{k}{'' if n == -1 else n}" for k, n in self.sizes)
