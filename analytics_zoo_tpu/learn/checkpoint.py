"""Versioned checkpoint/resume.

Ref: BigDL-style snapshots ``model.<iter>`` + ``optimMethod-<name>.<iter>``
under a timestamped dir (zoo/.../keras/models/Topology.scala:1245-1252) and
Orca ``find_latest_checkpoint`` / ``load_orca_checkpoint``
(pyzoo/zoo/orca/learn/utils.py:24, orca/learn/tf/estimator.py:270-289).

Format: ``<dir>/ckpt-<iteration>/`` containing ``state.msgpack`` (params +
opt_state + rng, via flax msgpack serialization of host-gathered arrays) and
``meta.json`` (iteration, epoch, wall time). Retention respects
``OrcaContext.checkpoint_max_to_keep``.
"""

from __future__ import annotations

import glob
import json
import os
import re
import shutil
import time
from typing import Any, Optional, Tuple

import jax
import numpy as np


def _to_host(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(a), tree)


def save_checkpoint(ckpt_dir: str, state: Any, iteration: int, epoch: int,
                    max_to_keep: Optional[int] = None) -> str:
    from flax import serialization
    if max_to_keep is None:
        from analytics_zoo_tpu.common.context import OrcaContext
        max_to_keep = OrcaContext.checkpoint_max_to_keep

    path = os.path.join(ckpt_dir, f"ckpt-{iteration}")
    tmp = path + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    with open(os.path.join(tmp, "state.msgpack"), "wb") as fh:
        fh.write(serialization.to_bytes(_to_host(state)))
    with open(os.path.join(tmp, "meta.json"), "w") as fh:
        json.dump({"iteration": iteration, "epoch": epoch,
                   "time": time.time()}, fh)  # zoolint: disable=wallclock-hotpath (metadata)
    if os.path.exists(path):
        shutil.rmtree(path)
    os.replace(tmp, path)

    # retention
    versions = sorted(_list_versions(ckpt_dir))
    for v in versions[:-max_to_keep]:
        shutil.rmtree(os.path.join(ckpt_dir, f"ckpt-{v}"), ignore_errors=True)
    return path


def _list_versions(ckpt_dir: str):
    out = []
    for p in glob.glob(os.path.join(ckpt_dir, "ckpt-*")):
        m = re.match(r".*ckpt-(\d+)$", p)
        if m and os.path.isdir(p):
            out.append(int(m.group(1)))
    return out


def find_latest_checkpoint(ckpt_dir: str) -> Optional[Tuple[str, int]]:
    """(ref orca/learn/utils.py find_latest_checkpoint)"""
    versions = _list_versions(ckpt_dir)
    if not versions:
        return None
    v = max(versions)
    return os.path.join(ckpt_dir, f"ckpt-{v}"), v


def validate_state(state: Any, target: Any) -> None:
    """Check a restored ``state`` against the live ``target`` pytree:
    same tree structure, and every array leaf with the shape/dtype the
    live state expects. Raises ``ValueError`` on any mismatch — the
    auto-resume path treats that exactly like a torn file and falls back
    to the previous version instead of resuming into garbage."""
    s_leaves, s_def = jax.tree_util.tree_flatten(state)
    t_leaves, t_def = jax.tree_util.tree_flatten(target)
    if s_def != t_def:
        raise ValueError(
            f"checkpoint tree structure mismatch: {s_def} != {t_def}")
    for i, (s, t) in enumerate(zip(s_leaves, t_leaves)):
        ss, ts = np.shape(s), np.shape(t)
        if ss != ts:
            raise ValueError(
                f"checkpoint leaf {i} shape mismatch: {ss} != {ts}")
        sd = getattr(s, "dtype", None)
        td = getattr(t, "dtype", None)
        if sd is not None and td is not None and np.dtype(sd) != \
                np.dtype(td):
            raise ValueError(
                f"checkpoint leaf {i} dtype mismatch: {sd} != {td}")


def load_checkpoint(path: str, target: Any,
                    validate: bool = True) -> Tuple[Any, dict]:
    """Restore into the structure of ``target`` (a template state pytree).

    With ``validate`` (default), the restored tree is checked against
    ``target`` for structure/shape/dtype drift — a truncated msgpack
    already raises inside flax, but a *complete* file holding the wrong
    model must not restore silently either."""
    from flax import serialization
    with open(os.path.join(path, "state.msgpack"), "rb") as fh:
        state = serialization.from_bytes(_to_host(target), fh.read())
    with open(os.path.join(path, "meta.json")) as fh:
        meta = json.load(fh)
    if validate:
        validate_state(state, target)
    return state, meta


def load_latest_checkpoint(ckpt_dir: str, target: Any
                           ) -> Optional[Tuple[Any, dict, str]]:
    """Restore the newest checkpoint that loads *and validates* against
    ``target``, walking versions newest→oldest past any corrupt one (a
    torn ``state.msgpack`` from a crash mid-write, a missing meta, a
    shape mismatch). Returns ``(state, meta, path)`` or None when no
    version survives — the resilient read side of ``save_checkpoint``'s
    atomic-rename write side, and what ``fit(auto_resume=True)`` reloads
    through."""
    for v in sorted(_list_versions(ckpt_dir), reverse=True):
        path = os.path.join(ckpt_dir, f"ckpt-{v}")
        try:
            state, meta = load_checkpoint(path, target)
            return state, meta, path
        except Exception as e:
            import logging
            logging.getLogger(__name__).warning(
                "checkpoint %s unusable (%s); trying the previous "
                "version", path, e)
    return None
