"""Versioned checkpoint/resume.

Ref: BigDL-style snapshots ``model.<iter>`` + ``optimMethod-<name>.<iter>``
under a timestamped dir (zoo/.../keras/models/Topology.scala:1245-1252) and
Orca ``find_latest_checkpoint`` / ``load_orca_checkpoint``
(pyzoo/zoo/orca/learn/utils.py:24, orca/learn/tf/estimator.py:270-289).

Format: ``<dir>/ckpt-<iteration>/`` containing ``state.msgpack`` (params +
opt_state + rng, via flax msgpack serialization of host-gathered arrays) and
``meta.json`` (iteration, epoch, wall time). Retention respects
``OrcaContext.checkpoint_max_to_keep``.
"""

from __future__ import annotations

import glob
import json
import os
import re
import shutil
import time
from typing import Any, Optional, Tuple

import jax
import numpy as np


def _to_host(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(a), tree)


def save_checkpoint(ckpt_dir: str, state: Any, iteration: int, epoch: int,
                    max_to_keep: Optional[int] = None) -> str:
    from flax import serialization
    if max_to_keep is None:
        from analytics_zoo_tpu.common.context import OrcaContext
        max_to_keep = OrcaContext.checkpoint_max_to_keep

    path = os.path.join(ckpt_dir, f"ckpt-{iteration}")
    tmp = path + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    with open(os.path.join(tmp, "state.msgpack"), "wb") as fh:
        fh.write(serialization.to_bytes(_to_host(state)))
    with open(os.path.join(tmp, "meta.json"), "w") as fh:
        json.dump({"iteration": iteration, "epoch": epoch,
                   "time": time.time()}, fh)  # zoolint: disable=wallclock-hotpath (metadata)
    if os.path.exists(path):
        shutil.rmtree(path)
    os.replace(tmp, path)

    # retention
    versions = sorted(_list_versions(ckpt_dir))
    for v in versions[:-max_to_keep]:
        shutil.rmtree(os.path.join(ckpt_dir, f"ckpt-{v}"), ignore_errors=True)
    return path


def _list_versions(ckpt_dir: str):
    out = []
    for p in glob.glob(os.path.join(ckpt_dir, "ckpt-*")):
        m = re.match(r".*ckpt-(\d+)$", p)
        if m and os.path.isdir(p):
            out.append(int(m.group(1)))
    return out


def find_latest_checkpoint(ckpt_dir: str) -> Optional[Tuple[str, int]]:
    """(ref orca/learn/utils.py find_latest_checkpoint)"""
    versions = _list_versions(ckpt_dir)
    if not versions:
        return None
    v = max(versions)
    return os.path.join(ckpt_dir, f"ckpt-{v}"), v


def load_checkpoint(path: str, target: Any) -> Tuple[Any, dict]:
    """Restore into the structure of ``target`` (a template state pytree)."""
    from flax import serialization
    with open(os.path.join(path, "state.msgpack"), "rb") as fh:
        state = serialization.from_bytes(_to_host(target), fh.read())
    with open(os.path.join(path, "meta.json")) as fh:
        meta = json.load(fh)
    return state, meta
