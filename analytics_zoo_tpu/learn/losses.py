"""Loss functions (objectives) — ref zoo Keras objectives
(``pyzoo/zoo/pipeline/api/keras/objectives.py`` lowering to BigDL criterions).

Every loss is ``fn(y_true, y_pred) -> per-sample loss [batch]`` so the train
step can apply padding masks before reduction.
"""

from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-7


def _flatten_trailing(a):
    a = jnp.asarray(a)
    return a.reshape(a.shape[0], -1) if a.ndim > 1 else a[:, None]


def mean_squared_error(y_true, y_pred):
    y_pred = _f32(y_pred)
    return jnp.square(_flatten_trailing(y_pred) - _flatten_trailing(y_true)).mean(-1)


def mean_absolute_error(y_true, y_pred):
    y_pred = _f32(y_pred)
    return jnp.abs(_flatten_trailing(y_pred) - _flatten_trailing(y_true)).mean(-1)


def mean_absolute_percentage_error(y_true, y_pred):
    y_pred, y_true = _f32(y_pred), _f32(y_true)
    t = _flatten_trailing(y_true)
    return (100.0 * jnp.abs((t - _flatten_trailing(y_pred))
                            / jnp.clip(jnp.abs(t), _EPS, None))).mean(-1)


def mean_squared_logarithmic_error(y_true, y_pred):
    y_pred, y_true = _f32(y_pred), _f32(y_true)
    a = jnp.log1p(jnp.clip(_flatten_trailing(y_pred), _EPS, None))
    b = jnp.log1p(jnp.clip(_flatten_trailing(y_true), _EPS, None))
    return jnp.square(a - b).mean(-1)


def _f32(a):
    """Losses compute in fp32 even under a bf16 compute policy: log/exp/
    square/divide of bf16 values costs accuracy for no MXU win (the loss
    is a scalar tail, not a matmul). Applied to predictions everywhere,
    and ALSO to targets wherever the target enters a nonlinear op (the
    log/ratio family: msle, mape, kld, poisson) — a bf16 target inside a
    log would otherwise evaluate the transcendental at bf16 precision
    even though everything around it is fp32."""
    a = jnp.asarray(a)
    return a.astype(jnp.float32) \
        if jnp.issubdtype(a.dtype, jnp.floating) else a


def binary_crossentropy(y_true, y_pred):
    y_pred = _f32(y_pred)
    p = jnp.clip(_flatten_trailing(y_pred), _EPS, 1 - _EPS)
    t = _flatten_trailing(y_true)
    return -(t * jnp.log(p) + (1 - t) * jnp.log1p(-p)).mean(-1)


def binary_crossentropy_from_logits(y_true, y_pred):
    y_pred = _f32(y_pred)
    z = _flatten_trailing(y_pred)
    t = _flatten_trailing(y_true)
    return (jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))).mean(-1)


def categorical_crossentropy(y_true, y_pred):
    y_pred = _f32(y_pred)
    p = jnp.clip(y_pred, _EPS, 1.0)
    return -(y_true * jnp.log(p)).sum(-1)


def sparse_categorical_crossentropy(y_true, y_pred):
    y_pred = _f32(y_pred)
    logp = jnp.log(jnp.clip(y_pred, _EPS, 1.0))
    idx = jnp.asarray(y_true).astype(jnp.int32)
    return -jnp.take_along_axis(logp, idx[..., None], axis=-1)[..., 0]


def sparse_categorical_crossentropy_from_logits(y_true, y_pred):
    y_pred = _f32(y_pred)
    logp = y_pred - jax_logsumexp(y_pred)
    idx = jnp.asarray(y_true).astype(jnp.int32)
    out = -jnp.take_along_axis(logp, idx[..., None], axis=-1)[..., 0]
    if out.ndim > 1:  # e.g. seq models: mean over time
        out = out.mean(axis=tuple(range(1, out.ndim)))
    return out


def jax_logsumexp(x):
    m = jnp.max(x, axis=-1, keepdims=True)
    return m + jnp.log(jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True))


def hinge(y_true, y_pred):
    return jnp.maximum(1.0 - _flatten_trailing(y_true) * _flatten_trailing(y_pred),
                       0.0).mean(-1)


def squared_hinge(y_true, y_pred):
    return jnp.square(jnp.maximum(
        1.0 - _flatten_trailing(y_true) * _flatten_trailing(y_pred), 0.0)).mean(-1)


def kullback_leibler_divergence(y_true, y_pred):
    t = jnp.clip(_f32(y_true), _EPS, 1.0)
    p = jnp.clip(_f32(y_pred), _EPS, 1.0)
    return (t * jnp.log(t / p)).sum(-1)


def poisson(y_true, y_pred):
    y_pred, y_true = _f32(y_pred), _f32(y_true)
    return (_flatten_trailing(y_pred)
            - _flatten_trailing(y_true) * jnp.log(_flatten_trailing(y_pred) + _EPS)
            ).mean(-1)


def cosine_proximity(y_true, y_pred):
    t = _flatten_trailing(y_true)
    p = _flatten_trailing(y_pred)
    t = t / jnp.clip(jnp.linalg.norm(t, axis=-1, keepdims=True), _EPS, None)
    p = p / jnp.clip(jnp.linalg.norm(p, axis=-1, keepdims=True), _EPS, None)
    return -(t * p).sum(-1)


def huber(y_true, y_pred, delta: float = 1.0):
    err = _flatten_trailing(y_pred) - _flatten_trailing(y_true)
    abs_err = jnp.abs(err)
    quad = jnp.minimum(abs_err, delta)
    return (0.5 * quad ** 2 + delta * (abs_err - quad)).mean(-1)


_REGISTRY = {
    "mse": mean_squared_error, "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error, "mean_absolute_error": mean_absolute_error,
    "mape": mean_absolute_percentage_error,
    "msle": mean_squared_logarithmic_error,
    "binary_crossentropy": binary_crossentropy,
    "bce_logits": binary_crossentropy_from_logits,
    "categorical_crossentropy": categorical_crossentropy,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "sparse_categorical_crossentropy_logits":
        sparse_categorical_crossentropy_from_logits,
    "hinge": hinge, "squared_hinge": squared_hinge,
    "kld": kullback_leibler_divergence,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "huber": huber,
}


def get(loss):
    if callable(loss):
        return loss
    if isinstance(loss, str):
        key = loss.lower()
        if key not in _REGISTRY:
            raise ValueError(f"unknown loss {loss!r}; known: {sorted(_REGISTRY)}")
        return _REGISTRY[key]
    raise TypeError(f"loss must be str or callable, got {type(loss)}")
