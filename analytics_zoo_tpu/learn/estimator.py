"""Orca-style Estimator — distributed fit/predict/evaluate on a TPU mesh.

This one class replaces the reference's entire execution-bridge + engine
stack (SURVEY.md §2.3/§2.4): where Analytics Zoo wrapped foreign graphs into
BigDL modules (TFTrainingHelper, zoo/.../tfpark/TFTrainingHelper.scala:33-309;
TorchModel, zoo/.../pipeline/api/net/TorchModel.scala:34-260) and synchronized
gradients through AllReduceParameter-over-BlockManager inside
InternalDistriOptimizer (zoo/.../keras/models/Topology.scala:1145-1550), here
the model is a flax module, the train step is one jitted function over a
``jax.sharding.Mesh``, and XLA emits the gradient collectives implied by the
sharding strategy (DP all-reduce, FSDP reduce-scatter/all-gather, TP
collectives) over ICI.

API parity targets:
- ``Estimator.from_keras`` / ``from_graph``  (ref pyzoo/zoo/orca/learn/tf/estimator.py:291,335)
- ``Estimator.from_torch``                   (ref pyzoo/zoo/orca/learn/pytorch/estimator.py:35)
- ``fit(data, epochs, batch_size, feature_cols, label_cols, validation_data,
  checkpoint_trigger)``, ``predict``, ``evaluate``, ``save``/``load``,
  ``load_orca_checkpoint``, ``get_train_summary``/``get_validation_summary``,
  ``set_constant_gradient_clipping``/``set_l2_norm_gradient_clipping``
  (ref pyzoo/zoo/orca/learn/spark_estimator.py:1-203)

Elastic retry-from-snapshot mirrors Topology.scala:1255-1337 (driver reloads
the latest checkpoint and resumes, up to ``failure_retry_times``).
"""

from __future__ import annotations

import logging
import os
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.common import compile_ahead
from analytics_zoo_tpu.common import profiling as profiling_lib
from analytics_zoo_tpu.common import resilience, telemetry
from analytics_zoo_tpu.data.dataset import ShardedDataset, to_sharded_dataset
from analytics_zoo_tpu.data.shard import HostXShards, XShards
from analytics_zoo_tpu.learn import checkpoint as ckpt_lib
from analytics_zoo_tpu.learn import losses as loss_lib
from analytics_zoo_tpu.learn import metrics as metric_lib
from analytics_zoo_tpu.learn.optimizers import Optimizer
from analytics_zoo_tpu.learn.trigger import EveryEpoch, Trigger
from analytics_zoo_tpu.learn.trigger import fire as _fire_trigger
from analytics_zoo_tpu.parallel.strategy import ShardingStrategy

logger = logging.getLogger(__name__)


def _trigger_needs_score(trigger) -> bool:
    """True if the trigger (transitively) contains a MaxScore."""
    from analytics_zoo_tpu.learn.trigger import MaxScore
    if isinstance(trigger, MaxScore):
        return True
    return any(_trigger_needs_score(t)
               for t in getattr(trigger, "triggers", ()))


def _as_args(x):
    return x if isinstance(x, tuple) else (x,)


class _ProfileWindow:
    """Defers ``jax.profiler.start_trace`` until training enters a
    fit-relative step window and stops it when the window closes — whole-run
    traces of long fits are too large to open in TensorBoard/Perfetto, a
    20-step window is not. Thresholds are absolute ``_py_step`` values
    computed at fit start; ``on_step`` is called after every optimizer
    loop and ``close()`` from fit's ``finally``."""

    def __init__(self, log_dir: str, start_step: int, stop_step: int):
        if stop_step <= start_step:
            raise ValueError(
                f"profile_steps window must be non-empty, got "
                f"({start_step}, {stop_step})")
        self.log_dir = log_dir
        self.start_step = int(start_step)
        self.stop_step = int(stop_step)
        self.active = False
        self.done = False

    def on_step(self, py_step: int):
        import jax
        if not self.active and not self.done and \
                py_step >= self.start_step:
            jax.profiler.start_trace(self.log_dir)
            self.active = True
            logger.info("jax profiler tracing steps [%d, %d) to %s",
                        self.start_step, self.stop_step, self.log_dir)
        if self.active and py_step >= self.stop_step:
            self.close()

    def close(self):
        if self.active:
            import jax
            jax.profiler.stop_trace()
            self.active = False
            self.done = True


class FlaxModelAdapter:
    """Uniform call surface over a flax.linen module: handles multi-input
    tuples, the optional ``train`` kwarg, dropout rngs and mutable
    collections (batch_stats)."""

    def __init__(self, module, sample_input, rng=None, params=None,
                 model_state=None):
        import jax
        self.module = module
        self.n_inputs = len(_as_args(sample_input))
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._takes_train = None
        if params is None:
            variables = self._init(rng, sample_input)
            variables = dict(variables)
            # a parameterless graph (e.g. a pure merge model) has no
            # "params" collection at all
            params = variables.pop("params", {})
            # "aux_loss" is a per-step sown output (e.g. MoE load-balance
            # loss), not persistent state — it is consumed by the train step
            # and must not ride model_state across steps (sow appends, so
            # carrying it would grow the collection every iteration)
            model_state = {k: v for k, v in variables.items()
                           if k != "aux_loss"}
        self.params = params
        self.model_state = model_state or {}

    def _init(self, rng, sample_input):
        args = _as_args(sample_input)
        rngs = {"params": rng, "dropout": rng}
        try:
            out = self.module.init(rngs, *args, train=False)
            self._takes_train = True
            return out
        except TypeError:
            self._takes_train = False
            return self.module.init(rngs, *args)

    def apply(self, params, model_state, x, train: bool, rng):
        variables = {"params": params, **model_state}
        args = _as_args(x)
        kwargs = {}
        if self._takes_train:
            kwargs["train"] = train
        rngs = {"dropout": rng} if rng is not None else None
        if train:
            # "aux_loss" mutable lets sown per-step losses (MoE load
            # balancing) surface; the train step pops it off the returned
            # collections before they become the next model_state
            out, mut = self.module.apply(
                variables, *args, rngs=rngs,
                mutable=list(model_state.keys()) + ["aux_loss"], **kwargs)
            return out, dict(mut)
        out = self.module.apply(variables, *args, rngs=rngs, **kwargs)
        return out, model_state


class FnModelAdapter:
    """Adapter over a bare pure function — used by ``from_torch``
    (translated torch graphs) and ``from_fn``.

    Two conventions: without ``buffers`` the fn is
    ``apply_fn(params, *inputs)``; with ``buffers`` it is
    ``apply_fn({"params", "buffers"}, *inputs)`` and the buffers ride the
    estimator's model_state — frozen (no grads, no optimizer updates), which
    is how translated BatchNorm running statistics stay fixed."""

    def __init__(self, apply_fn, params, n_inputs: int, buffers=None,
                 supports_train: bool = False):
        self._fn = apply_fn
        self._variables_style = buffers is not None
        self._supports_train = supports_train
        self.params = params
        self.model_state = buffers or {}
        self.n_inputs = n_inputs

    def apply(self, params, model_state, x, train: bool, rng):
        if self._variables_style:
            kwargs = ({"train": train, "rng": rng}
                      if self._supports_train else {})
            out = self._fn({"params": params, "buffers": model_state},
                           *_as_args(x), **kwargs)
        else:
            out = self._fn(params, *_as_args(x))
        return out, model_state


class Estimator:
    """Factory façade (ref orca/learn/tf/estimator.py Estimator)."""

    @staticmethod
    def from_flax(*, model, loss, optimizer="adam", metrics=None,
                  sample_input, model_dir: Optional[str] = None,
                  strategy="dp", param_rules=None, seed: int = 0,
                  aux_loss_weight: float = 0.01, param_penalty=None,
                  backend: str = "tpu") -> "JaxEstimator":
        """Build an estimator from a flax.linen module.

        ``sample_input``: one example input (or tuple of inputs) with a
        batch dim of any size — used to initialise parameters and infer
        input structure (plays the role of the reference's TF graph export,
        tf_optimizer.py:252-287).
        """
        import jax
        adapter = FlaxModelAdapter(model, sample_input,
                                   rng=jax.random.PRNGKey(seed))
        return JaxEstimator(adapter, loss=loss, optimizer=optimizer,
                            metrics=metrics, model_dir=model_dir,
                            strategy=strategy, param_rules=param_rules,
                            seed=seed, aux_loss_weight=aux_loss_weight,
                            param_penalty=param_penalty)

    @staticmethod
    def from_torch(*, model, loss, optimizer="adam", metrics=None,
                   sample_input, model_dir: Optional[str] = None,
                   strategy="dp", param_rules=None, seed: int = 0
                   ) -> "JaxEstimator":
        """Train a PyTorch ``nn.Module`` on the TPU mesh
        (ref pyzoo/zoo/orca/learn/pytorch/estimator.py:35 Estimator.from_torch).

        The reference runs torch itself inside executors (Jep/DDP); here the
        module is translated to a pure jax function (net/torch_net.py) so
        the SAME pjit train step applies — grads flow through the translated
        graph, not through torch autograd."""
        from analytics_zoo_tpu.net.torch_net import torch_to_jax
        apply_fn, variables = torch_to_jax(model)
        adapter = FnModelAdapter(apply_fn, variables["params"],
                                 len(_as_args(sample_input)),
                                 buffers=variables["buffers"],
                                 supports_train=True)
        return JaxEstimator(adapter, loss=loss, optimizer=optimizer,
                            metrics=metrics, model_dir=model_dir,
                            strategy=strategy, param_rules=param_rules,
                            seed=seed)

    @staticmethod
    def from_fn(*, apply_fn, params, loss, optimizer="adam", metrics=None,
                n_inputs: int = 1, model_dir: Optional[str] = None,
                strategy="dp", param_rules=None, seed: int = 0
                ) -> "JaxEstimator":
        """Escape hatch: any pure ``apply_fn(params, *inputs)``."""
        adapter = FnModelAdapter(apply_fn, params, n_inputs)
        return JaxEstimator(adapter, loss=loss, optimizer=optimizer,
                            metrics=metrics, model_dir=model_dir,
                            strategy=strategy, param_rules=param_rules,
                            seed=seed)

    # reference-compatible spellings
    @staticmethod
    def from_keras(*, keras_model, loss=None, optimizer=None,
                   metrics=None, model_dir: Optional[str] = None,
                   strategy=None, param_rules=None) -> "JaxEstimator":
        """Estimator over a zoo-keras model
        (ref pyzoo/zoo/orca/learn/tf/estimator.py:335 Estimator.from_keras).
        Settings already on the model (a prior ``compile``, a prior
        ``set_strategy``) are kept; explicit non-None arguments override."""
        from analytics_zoo_tpu.keras.models import KerasNet
        model = getattr(keras_model, "model", keras_model)  # ZooModel wrap
        if not isinstance(model, KerasNet):
            raise TypeError(
                f"from_keras expects a zoo keras model, got "
                f"{type(keras_model).__name__}; use from_flax for raw "
                "flax modules")
        compiled = model._compile_args or {}
        if loss is None and compiled.get("loss") is None:
            raise ValueError(
                "no loss: pass loss=... or compile the model first (every "
                "other training entry point errors here too)")
        if strategy is not None or param_rules is not None:
            model.set_strategy(strategy or model._strategy,
                               param_rules=param_rules)
        model.compile(
            optimizer=optimizer if optimizer is not None
            else compiled.get("optimizer", "adam"),
            loss=loss if loss is not None else compiled["loss"],
            metrics=metrics if metrics is not None
            else compiled.get("metrics"))
        est = model._ensure_estimator(for_training=True)
        if model_dir:
            est.model_dir = model_dir
        return est

    @staticmethod
    def from_graph(*, inputs, outputs, loss, optimizer="adam",
                   metrics=None, model_dir: Optional[str] = None,
                   strategy="dp", param_rules=None) -> "JaxEstimator":
        """Estimator over a symbolic layer graph — Input()/layer Nodes
        (ref orca/learn/tf/estimator.py:291 Estimator.from_graph, which
        takes TF1 graph tensors; here the graph is the zoo keras graph)."""
        from analytics_zoo_tpu.keras.models import Model
        model = Model(inputs, outputs)
        return Estimator.from_keras(
            keras_model=model, loss=loss, optimizer=optimizer,
            metrics=metrics, model_dir=model_dir, strategy=strategy,
            param_rules=param_rules)

    @staticmethod
    def latest_checkpoint(model_dir: str):
        found = ckpt_lib.find_latest_checkpoint(model_dir)
        return found[0] if found else None


class JaxEstimator:
    """The engine (ref TensorFlowEstimator orca/learn/tf/estimator.py:429 +
    Scala Estimator zoo/.../pipeline/estimator/Estimator.scala:68-309)."""

    def __init__(self, adapter: FlaxModelAdapter, loss, optimizer,
                 metrics=None, model_dir: Optional[str] = None,
                 strategy="dp", param_rules=None, seed: int = 0,
                 aux_loss_weight: float = 0.01, param_penalty=None):
        import jax

        self.adapter = adapter
        # optional pure params→scalar regularization penalty added to the
        # training objective (keras W/b regularizers; ref BigDL applies
        # these inside the optimizer)
        self.param_penalty = param_penalty
        self.loss_fn = loss_lib.get(loss)
        self.optimizer = Optimizer.get(optimizer)
        self.metrics = [metric_lib.get(m) for m in (metrics or [])]
        self.model_dir = model_dir
        self.strategy = ShardingStrategy.parse(strategy, param_rules=param_rules)
        self.seed = seed
        # weight on sown "aux_loss" values (MoE load balancing; Switch
        # Transformer uses 0.01) — added to the data loss in the train step
        self.aux_loss_weight = float(aux_loss_weight)
        self.failure_retry_times = 5  # ref Topology.scala:1256 bigdl.failure.retryTimes

        self._grad_clip = None  # ("norm", v) | ("const", min, max)
        self._mesh = None
        self._state = None
        self._train_step = None
        self._eval_step = None
        self._predict_fn = None
        self._precompile_thread = None
        self._epoch = 0
        self._py_step = 0  # host-side mirror of state["step"]: no device sync
        self._train_writer = None
        self._val_writer = None
        self._tb_dirs = None
        self._base_rng = jax.random.PRNGKey(seed + 17)

    # ------------- gradient clipping (ref spark_estimator.py:150-180) ----
    def set_constant_gradient_clipping(self, min_value: float, max_value: float):
        self._grad_clip = ("const", float(min_value), float(max_value))
        self._on_tx_changed()

    def set_l2_norm_gradient_clipping(self, clip_norm: float):
        self._grad_clip = ("norm", float(clip_norm))
        self._on_tx_changed()

    def clear_gradient_clipping(self):
        self._grad_clip = None
        self._on_tx_changed()

    def _on_tx_changed(self):
        """The optax chain changed shape — rebuild opt_state around the
        current params (training progress in params/step is kept)."""
        self._train_step = None
        if self._state is not None:
            import jax
            tx = self._tx()
            params = self._state["params"]
            host_params = jax.device_get(params)
            new_opt = self._unalias_opt_state(tx.init(host_params),
                                              host_params)
            state = dict(self._state)
            state["opt_state"] = new_opt
            shardings = self._state_shardings(
                {"step": state["step"], "params": jax.device_get(params),
                 "opt_state": new_opt, "model_state": state["model_state"]},
                self._ensure_mesh())
            self._state = jax.device_put(jax.device_get(state), shardings)
            self._state_sharding_tree = shardings

    # ------------- summaries (ref estimator.py:167-220) ------------------
    def set_tensorboard(self, log_dir: str, app_name: str):
        self._tb_dirs = (os.path.join(log_dir, app_name, "train"),
                         os.path.join(log_dir, app_name, "validation"))
        if self._train_writer is not None:  # redirect future events
            self._train_writer.close()
            self._val_writer.close()
            self._train_writer = self._val_writer = None

    def _writers(self):
        from analytics_zoo_tpu.common.summary import SummaryWriter
        if self._train_writer is None:
            if self._tb_dirs is None:
                base = self.model_dir or os.path.join(".", "zoo_tpu_logs")
                self._tb_dirs = (os.path.join(base, "train"),
                                 os.path.join(base, "validation"))
            self._train_writer = SummaryWriter(self._tb_dirs[0])
            self._val_writer = SummaryWriter(self._tb_dirs[1])
        return self._train_writer, self._val_writer

    def get_train_summary(self, tag: str):
        """("Loss" | "Throughput" | "LearningRate"...) → [(step, value)]
        (ref Topology.scala:208-240)."""
        return self._train_writer.get_scalar(tag) if self._train_writer else []

    def get_validation_summary(self, tag: str):
        return self._val_writer.get_scalar(tag) if self._val_writer else []

    # ------------- compile machinery -------------------------------------
    def _tx(self):
        import optax
        tx = self.optimizer.to_optax()
        if self._grad_clip:
            if self._grad_clip[0] == "norm":
                clip = optax.clip_by_global_norm(self._grad_clip[1])
            else:
                lo, hi = self._grad_clip[1], self._grad_clip[2]
                mag = max(abs(lo), abs(hi))
                clip = optax.clip(mag)
            tx = optax.chain(clip, tx)
        return tx

    def _ensure_mesh(self):
        if self._mesh is None:
            from analytics_zoo_tpu.parallel import mesh as mesh_lib
            needed = set(self.strategy.axis_names())
            cur = mesh_lib.get_default_mesh()
            if set(cur.axis_names) >= needed:
                self._mesh = cur
            else:
                self._mesh = self.strategy.build_mesh()
        return self._mesh

    @staticmethod
    def _unalias_opt_state(opt_state, params):
        """Some optax states alias buffers — either the passed params
        (lbfgs keeps the previous params) or each other (jax dedupes the
        identical zeros arrays lbfgs uses for its history buffers). The
        train step donates the whole state, and XLA rejects the same
        buffer donated twice — copy every repeated leaf."""
        import jax
        seen = {id(leaf) for leaf in jax.tree_util.tree_leaves(params)}

        def uniq(leaf):
            if id(leaf) in seen:
                leaf = leaf.copy()
            seen.add(id(leaf))
            return leaf

        return jax.tree_util.tree_map(uniq, opt_state)

    def _init_state(self):
        import jax
        if self._state is not None:
            return
        mesh = self._ensure_mesh()
        tx = self._tx()
        params = self.adapter.params
        opt_state = self._unalias_opt_state(tx.init(params), params)
        state = {"step": np.zeros((), np.int32),
                 "params": params,
                 "opt_state": opt_state,
                 "model_state": self.adapter.model_state}
        shardings = self._state_shardings(state, mesh)
        self._state = jax.device_put(state, shardings)
        self._state_sharding_tree = shardings

    def _state_shardings(self, state, mesh):
        """Sharding pytree for the full train state. Optimizer-state leaves
        inherit the sharding of the parameter whose path suffix they carry
        (so FSDP shards Adam moments exactly like weights — the analog of the
        reference's per-partition weight-range ownership,
        Topology.scala:1094-1104)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        param_specs = {}
        flat, _ = jax.tree_util.tree_flatten_with_path(state["params"])
        for path, leaf in flat:
            p = _path_str(path)
            param_specs[p] = self.strategy.param_spec(p, leaf.shape, mesh)

        def spec_for(path_str, leaf):
            for p, spec in param_specs.items():
                # '/'-boundary suffix match so 'q_proj/kernel' never matches
                # a rule for 'proj/kernel'
                if (path_str == p or path_str.endswith("/" + p)) \
                        and np.shape(leaf) and \
                        tuple(np.shape(leaf)) == tuple(np.shape(_get_by_path(
                            state["params"], p))):
                    return spec
            return P()

        flat_state, treedef = jax.tree_util.tree_flatten_with_path(state)
        out = []
        for path, leaf in flat_state:
            ps = _path_str(path)
            if ps.startswith("params/"):
                spec = param_specs.get(ps[len("params/"):], P())
            elif ps.startswith("opt_state"):
                spec = spec_for(ps, leaf)
            else:
                spec = P()
            out.append(NamedSharding(mesh, spec))
        return jax.tree_util.tree_unflatten(treedef, out)

    def _build_train_step(self):
        import jax
        import jax.numpy as jnp
        import optax

        if self._train_step is not None:
            return
        self._init_state()
        tx = self._tx()
        adapter, loss_fn, base_rng = self.adapter, self.loss_fn, self._base_rng
        aux_weight = self.aux_loss_weight
        penalty_fn = self.param_penalty

        def step_fn(state, x, y):
            rng = jax.random.fold_in(base_rng, state["step"])

            def compute_loss(params):
                preds, new_mut = adapter.apply(params, state["model_state"],
                                               x, True, rng)
                per = loss_fn(y, preds)
                loss = per.mean()
                if penalty_fn is not None:
                    loss = loss + penalty_fn(params)
                # consume sown per-step losses (MoE load balance): they add
                # to the objective and are stripped so model_state keeps its
                # across-step structure
                if isinstance(new_mut, dict) and "aux_loss" in new_mut:
                    new_mut = dict(new_mut)
                    aux = new_mut.pop("aux_loss")
                    aux_terms = [jnp.sum(jnp.asarray(leaf))
                                 for leaf in jax.tree_util.tree_leaves(aux)]
                    if aux_terms:
                        loss = loss + aux_weight * sum(aux_terms)
                return loss, new_mut

            (loss_val, new_mut), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(state["params"])
            updates, new_opt = tx.update(grads, state["opt_state"],
                                         state["params"])
            new_params = optax.apply_updates(state["params"], updates)
            new_state = {"step": state["step"] + 1,
                         "params": new_params,
                         "opt_state": new_opt,
                         "model_state": new_mut}
            return new_state, {"loss": loss_val.astype(jnp.float32)}

        # instrument_jit = jax.jit + recompile accounting: the
        # zoo_jit_cache_misses_total{fn=...} counter stays flat across
        # steady-state steps and increments exactly when the avals
        # signature changes (new batch bucket, dtype drift)
        self._train_step = telemetry.instrument_jit(
            step_fn, name="estimator_train_step", donate_argnums=0)

        def scan_fn(state, batches):
            # K steps in ONE dispatch: for small models per-step launch
            # overhead dominates, and scan amortizes it (the analog of the
            # reference keeping its hot loop inside the JVM task,
            # Topology.scala:1262 optimizeModels)
            def body(s, xy):
                s2, logs = step_fn(s, xy[0], xy[1])
                return s2, logs["loss"]

            state, losses = jax.lax.scan(body, state, batches)
            return state, losses

        self._train_scan = telemetry.instrument_jit(
            scan_fn, name="estimator_train_scan", donate_argnums=0)

        def epoch_fn(state, x_full, y_full, key, bs, do_shuffle):
            # HBM-cached epoch: the WHOLE dataset is device-resident, the
            # permutation is drawn on device, and every optimizer step of
            # the epoch runs in one compiled dispatch — the "HBM tier"
            # counterpart of the reference's DRAM FeatureSet, sized for
            # datasets that fit on-chip (NCF/tabular scale). Nothing but
            # one PRNG key crosses the host↔device link per epoch.
            n = jax.tree_util.tree_leaves(x_full)[0].shape[0]
            n_steps = n // bs
            order = jax.random.permutation(key, n) if do_shuffle \
                else jnp.arange(n)
            idx = order[:n_steps * bs].reshape(n_steps, bs)

            def body(s, ib):
                bx = jax.tree_util.tree_map(lambda a: a[ib], x_full)
                by = jax.tree_util.tree_map(lambda a: a[ib], y_full)
                s2, logs = step_fn(s, bx, by)
                return s2, logs["loss"]

            state, losses = jax.lax.scan(body, state, idx)
            return state, losses

        self._train_epoch_cached = telemetry.instrument_jit(
            epoch_fn, name="estimator_epoch_cached", donate_argnums=0,
            static_argnums=(4, 5))

    def _build_eval_step(self):
        import jax
        import jax.numpy as jnp

        if self._eval_step is not None:
            return
        adapter, loss_fn, metrics = self.adapter, self.loss_fn, self.metrics

        def eval_fn(state, metric_states, x, y, mask):
            preds, _ = adapter.apply(state["params"], state["model_state"],
                                     x, False, None)
            per = loss_fn(y, preds)
            m = jnp.ones_like(per) if mask is None else mask
            loss_sum = (per * m).sum()
            new_states = [metric.update(ms, y, preds, mask)
                          for metric, ms in zip(metrics, metric_states)]
            return new_states, loss_sum, m.sum()

        self._eval_step_masked = jax.jit(eval_fn, static_argnames=())
        self._eval_step = jax.jit(
            lambda s, ms, x, y: eval_fn(s, ms, x, y, None))

    def _build_predict(self):
        import jax
        if self._predict_fn is not None:
            return
        adapter = self.adapter

        def pred_fn(state, x):
            preds, _ = adapter.apply(state["params"], state["model_state"],
                                     x, False, None)
            return preds

        self._predict_fn = telemetry.instrument_jit(
            pred_fn, name="estimator_predict")

    def _start_precompile(self, ds, batch_size: int,
                          steps_per_loop: int = 1,
                          with_eval: bool = False):
        """AOT-compile the train (scan/eval) steps on a background daemon
        thread, concurrently with first-batch staging. The AOT build seeds
        JAX's persistent compilation cache, so the hot loop's first jit
        dispatch deserializes the executable instead of compiling it —
        step 0 overlaps compile with data load. Entirely best-effort: any
        failure (streaming dataset with no materialized shapes, exotic
        shardings) leaves the plain jit path untouched. Returns the
        warmup thread, or None when there was nothing to precompile."""
        import threading

        import jax

        if getattr(ds, "x", None) is None:
            # streaming datasets hold no whole-dataset tensors to derive
            # avals from (x is None; tree_map would silently produce None
            # avals and warm a step that crashes on them) — the hot loop's
            # plain jit path handles the first window instead
            logger.debug("step precompile skipped: streaming dataset")
            return None
        compile_ahead.configure_persistent_cache()
        bs = int(batch_size)

        def batched(extra_lead):
            mesh = self._ensure_mesh()

            def f(a):
                shape = getattr(a, "shape", None)
                dtype = getattr(a, "dtype", None)
                if shape is None or dtype is None:
                    raise TypeError("dataset tensors are not materialized")
                shp = tuple(extra_lead) + (bs,) + tuple(shape[1:])
                # the hot loop feeds committed mesh-placed batches
                # (device_iterator/device_scan_iterator shard the batch
                # dim per the strategy, scan lead unsharded); an aval
                # without that sharding lowers a different executable,
                # so the "precompiled" step silently recompiles on its
                # first real batch
                try:
                    from jax.sharding import (
                        NamedSharding, PartitionSpec as P,
                    )
                    base = self.strategy.batch_spec(
                        len(shp) - len(extra_lead))
                    spec = P(*([None] * len(extra_lead)), *base) \
                        if extra_lead else base
                    return jax.ShapeDtypeStruct(
                        shp, dtype, sharding=NamedSharding(mesh, spec))
                except TypeError:   # older jax: no sharding kwarg
                    return jax.ShapeDtypeStruct(shp, dtype)
            return f

        def state_avals(with_sharding: bool):
            def f(a):
                if with_sharding:
                    sh = getattr(a, "sharding", None)
                    if sh is not None:
                        try:
                            return jax.ShapeDtypeStruct(
                                a.shape, a.dtype, sharding=sh)
                        except TypeError:  # older jax: no sharding kwarg
                            pass
                arr = a if hasattr(a, "shape") else np.asarray(a)
                return jax.ShapeDtypeStruct(
                    tuple(arr.shape), arr.dtype)
            return jax.tree_util.tree_map(f, self._state)

        try:
            x_avals = jax.tree_util.tree_map(batched(()), ds.x)
            y_avals = jax.tree_util.tree_map(batched(()), ds.y)
            targets = []
            if steps_per_loop > 1:
                k = int(steps_per_loop)
                scan_x = jax.tree_util.tree_map(batched((k,)), ds.x)
                scan_y = jax.tree_util.tree_map(batched((k,)), ds.y)
                targets.append(("estimator_train_scan", self._train_scan,
                                ((scan_x, scan_y),)))
            else:
                targets.append(("estimator_train_step", self._train_step,
                                (x_avals, y_avals)))
            if with_eval and self._eval_step is not None:
                ms = [m.init_state() for m in self.metrics]
                ms_avals = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(
                        np.shape(a), np.asarray(a).dtype), ms)
                targets.append(("estimator_eval_step", self._eval_step,
                                (ms_avals, x_avals, y_avals)))
        except Exception:
            logger.debug("step precompile skipped: dataset shapes "
                         "unavailable", exc_info=True)
            return None

        def worker():
            # the eval step takes the state WITHOUT donating it, the train
            # step donates — but the aval signature is identical, so one
            # state tree serves every target
            for sharded in (True, False):
                sa = state_avals(sharded)
                ok = True
                for name, fn, rest in targets:
                    if compile_ahead.draining():
                        return          # interpreter exit: stop compiling
                    cache = compile_ahead.ExecutableCache(fn, name=name)
                    if not cache.warm(sa, *rest):
                        ok = False
                        break
                if ok:
                    return

        t = threading.Thread(target=worker, daemon=True,
                             name="zoo-warmup-estimator")
        t.start()
        compile_ahead.register_warmup_thread(t)
        self._precompile_thread = t
        return t

    # ------------- public API --------------------------------------------
    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            feature_cols: Optional[Sequence[str]] = None,
            label_cols: Optional[Sequence[str]] = None,
            validation_data=None,
            checkpoint_trigger: Optional[Trigger] = None,
            summary_interval: int = 20,
            shuffle: bool = True,
            steps_per_loop: int = 1,
            cache: Optional[str] = None,
            profile: bool = False,
            profile_steps: Optional[Sequence[int]] = None,
            auto_resume: bool = False
            ) -> Dict[str, List[float]]:
        """(ref orca/learn/tf/estimator.py fit:486; batch_size is the GLOBAL
        batch — the reference required batch_size % num_workers == 0, here it
        must divide the data-axis size of the mesh).

        ``steps_per_loop > 1`` fuses that many optimizer steps into one
        compiled ``lax.scan`` dispatch — a large win for small models where
        per-step launch overhead dominates. Checkpoint triggers are then
        evaluated once per loop, not per step.

        ``cache="device"`` keeps the whole dataset resident in HBM and runs
        EACH EPOCH as one compiled dispatch with an on-device shuffle — the
        HBM analog of the reference's DRAM FeatureSet tier, for datasets
        that fit on-chip. Requires an unsharded batch (single device or no
        data axis); loss summaries flush once per epoch.

        ``profile=True`` runs ``jax.profiler`` tracing over a bounded
        fit-relative step window — ``profile_steps=(start, stop)``, default
        ``(0, 20)`` — instead of the whole run, so the dump stays small
        enough to actually open. Passing ``profile_steps`` alone implies
        ``profile=True``. Trace files land in
        ``<tensorboard dir>/plugins/profile`` next to the TF-events
        summaries, viewable in TensorBoard's profile tab or Perfetto.

        Independently of ``profile``, every fit publishes the step
        decomposition through the telemetry registry: ``zoo_step_flops``
        (XLA ``cost_analysis`` of the compiled step), ``zoo_mfu``,
        ``zoo_hbm_bytes`` and the ``zoo_train_phase_seconds`` histogram
        (data_wait/dispatch/device/callback) — see docs/observability.md.

        ``auto_resume=True`` hardens the retry-from-snapshot boundary for
        backend loss (a wedged/lost accelerator, or an injected
        ``ZOO_FAULT_PLAN`` fault): the reload goes through
        ``load_latest_checkpoint`` — which validates each version against
        the live state and walks past corrupt ones — the retry budget is
        ``ZOO_FIT_MAX_RESUMES`` (default ``failure_retry_times``), and the
        failure is reported to the backend supervisor when one is
        running. Step/epoch counters and data order restore exactly, so a
        resumed run converges to the bitwise-identical loss of an
        unfaulted one."""
        ds = self._coerce(to_sharded_dataset(data, feature_cols, label_cols))
        val_ds = (self._coerce(to_sharded_dataset(validation_data, feature_cols,
                                                  label_cols))
                  if validation_data is not None else None)
        mesh = self._ensure_mesh()
        self._build_train_step()
        if val_ds is not None:
            self._build_eval_step()
        # compile-ahead: AOT-build the train (and eval) step on a daemon
        # thread WHILE the first batch stages host-side — step 0's jit
        # call then deserializes from the persistent compile cache instead
        # of compiling cold (ISSUE 5 tentpole, third hot path)
        self._start_precompile(ds, batch_size, steps_per_loop,
                               with_eval=val_ds is not None)
        if checkpoint_trigger is None and self.model_dir:
            checkpoint_trigger = EveryEpoch()
        if checkpoint_trigger is not None and \
                _trigger_needs_score(checkpoint_trigger) and val_ds is None:
            warnings.warn(
                "checkpoint_trigger contains MaxScore but fit() got no "
                "validation_data — the trigger can never fire and no "
                "checkpoints will be written")

        train_writer, _ = self._writers()
        history: Dict[str, List[float]] = {"loss": []}
        retries = 0
        target_epoch = self._epoch + epochs

        profile_window = None
        if profile or profile_steps is not None:
            lo, hi = profile_steps if profile_steps is not None else (0, 20)
            profile_window = _ProfileWindow(
                self._tb_dirs[0], self._py_step + int(lo),
                self._py_step + int(hi))
        # per-step phase decomposition + MFU/FLOPs/HBM gauges — always on
        # (sampled steps only are fenced, so the async dispatch overlap is
        # preserved on the other sample_every-1 of steps)
        step_prof = profiling_lib.StepProfiler(
            name="train", sample_every=max(2, summary_interval // 2))

        try:
            while self._epoch < target_epoch:
                try:
                    epoch_loss = self._run_epoch(
                        ds, mesh, batch_size, shuffle, summary_interval,
                        train_writer, checkpoint_trigger,
                        steps_per_loop=steps_per_loop, cache=cache,
                        step_prof=step_prof, profile_window=profile_window)
                except Exception as e:
                    # elastic retry-from-snapshot (ref Topology.scala:1255-1337)
                    retries += 1
                    limit = self.failure_retry_times
                    if auto_resume:
                        resilience.note_backend_loss(e)
                        limit = resilience.fit_max_resumes(limit)
                    if not self.model_dir or retries > limit:
                        raise
                    if auto_resume:
                        # validated reload: walks past torn/corrupt
                        # versions instead of resuming into garbage
                        path = self._auto_resume_reload()
                        if path is None:
                            raise
                    else:
                        found = ckpt_lib.find_latest_checkpoint(
                            self.model_dir)
                        if found is None:
                            raise
                        path = found[0]
                        self.load_orca_checkpoint(path)
                    logger.exception(
                        "training step failed; retry %d/%d from %s",
                        retries, limit, path)
                    continue
                history["loss"].append(epoch_loss)
                self._epoch += 1
                val_score = None
                if val_ds is not None:
                    val = self.evaluate(val_ds, batch_size=batch_size)
                    for k, v in val.items():
                        history.setdefault("val_" + k, []).append(v)
                        self._val_writer.add_scalar(k, v, self._py_step)
                    # the full metrics dict feeds the triggers: MaxScore
                    # picks its named metric (or the first non-loss one,
                    # warning when that is error-style)
                    val_score = val
                if checkpoint_trigger and self.model_dir and \
                        _fire_trigger(checkpoint_trigger, self._epoch,
                                      self._py_step, epoch_loss, val_score):
                    self._save_snapshot()
        finally:
            if profile_window is not None:
                profile_window.close()
        train_writer.flush()
        if self._val_writer:
            self._val_writer.flush()
        return history

    def _coerce(self, ds: ShardedDataset) -> ShardedDataset:
        """If the model is single-input but feature_cols produced one input
        per column (the reference's DataFrame convention,
        tf_dataset.py:1200 DataFrameDataset), stack scalar columns into one
        feature matrix."""
        if (self.adapter.n_inputs == 1 and isinstance(ds.x, tuple)
                and all(np.ndim(a) == 1 for a in ds.x)):
            x = np.column_stack([np.asarray(a) for a in ds.x])
            return ShardedDataset(x, ds.y)
        return ds

    def _iteration(self) -> int:
        return int(np.asarray(self._state["step"]))

    def _current_lr(self, step: int) -> Optional[float]:
        """Best-effort current learning rate: the optimizer wrappers carry
        ``lr`` (+ optional ``schedule``); optax schedules are callables of
        the step. None when the optimizer doesn't expose one (raw optax
        transforms)."""
        from analytics_zoo_tpu.learn.optimizers import _lr as resolve_lr
        opt = self.optimizer
        base = getattr(opt, "lr", None)
        if base is None:
            return None
        try:
            val = resolve_lr(base, getattr(opt, "schedule", None))
            return float(val(step)) if callable(val) else float(val)
        except Exception:
            return None

    def _mirror_train_scalars(self, writer, step: int, loss: float,
                              throughput: float, step_seconds: float):
        """One window's training scalars go BOTH ways: TF-events (the
        existing TensorBoard surface) and the telemetry registry (the
        Prometheus/BENCH surface) — same numbers, one call site."""
        reg = telemetry.get_registry()
        reg.gauge("zoo_training_loss",
                  "Last flushed training loss").set(loss)
        reg.gauge("zoo_training_throughput_samples_per_sec",
                  "Training throughput over the last summary window"
                  ).set(throughput)
        reg.histogram("zoo_training_step_seconds",
                      "Mean per-step wall time per summary window"
                      ).observe(step_seconds)
        lr = self._current_lr(step)
        if lr is not None:
            writer.add_scalar("LearningRate", lr, step)
            reg.gauge("zoo_training_learning_rate",
                      "Learning rate at the last flushed step").set(lr)

    def _run_epoch_cached(self, ds, mesh, batch_size, shuffle,
                          writer) -> float:
        """One fused on-device epoch over the HBM-resident dataset."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if getattr(ds, "x", None) is None or ds.y is None:
            raise ValueError("cache='device' needs a materialized labelled "
                             "dataset (streaming/tiered feeds stay on the "
                             "standard path)")
        from analytics_zoo_tpu.parallel import mesh as mesh_lib

        for ax in self.strategy.batch_axes():
            size = mesh_lib.mesh_axis_size(mesh, ax)
            if size > 1:
                raise ValueError(
                    "cache='device' needs an unsharded batch (single "
                    f"device or batch axis size 1); {ax}={size}. Use the "
                    "standard feed for data-parallel meshes.")
        # strong ref: id() alone could alias a NEW dataset allocated at a
        # freed dataset's address and silently train on stale device data
        if getattr(self, "_cached_ds", None) is not ds:
            repl = NamedSharding(mesh, P())
            self._cached_x = telemetry.traced_device_put(ds.x, repl)
            self._cached_y = telemetry.traced_device_put(ds.y, repl)
            self._cached_ds = ds
        key = jax.random.fold_in(self._base_rng, 977 + self._epoch)
        n_steps = ds.n // batch_size
        if n_steps < 1:
            raise ValueError(f"batch_size {batch_size} > dataset {ds.n}")
        t0 = time.perf_counter()
        self._state, losses = self._train_epoch_cached(
            self._state, self._cached_x, self._cached_y, key,
            int(batch_size), bool(shuffle))
        t_fetch = time.perf_counter()
        losses = np.asarray(telemetry.traced_device_get(losses), np.float64)
        dt = time.perf_counter() - t0
        # the fetch is the only host-blocked part of the fused epoch —
        # everything before it is one async dispatch
        telemetry.observe_device_block(time.perf_counter() - t_fetch,
                                       "train_epoch_cached")
        self._py_step += n_steps
        throughput = n_steps * batch_size / max(dt, 1e-9)
        writer.add_scalar("Loss", float(losses[-1]), self._py_step)
        writer.add_scalar("Throughput", throughput, self._py_step)
        self._mirror_train_scalars(writer, self._py_step,
                                   float(losses[-1]), throughput,
                                   dt / max(n_steps, 1))
        logger.info("cached epoch %d: %d steps in %.3fs (%.0f samples/s)",
                    self._epoch, n_steps, dt,
                    n_steps * batch_size / max(dt, 1e-9))
        return float(losses.mean())

    def _run_epoch(self, ds, mesh, batch_size, shuffle, summary_interval,
                   writer, checkpoint_trigger, steps_per_loop: int = 1,
                   cache: Optional[str] = None, step_prof=None,
                   profile_window=None) -> float:
        if cache == "device":
            return self._run_epoch_cached(ds, mesh, batch_size, shuffle,
                                          writer)
        if cache is not None:
            raise ValueError(f"unknown cache mode {cache!r} "
                             "(supported: 'device')")
        import jax
        losses: List[Any] = []
        pending: List[Any] = []
        pending_steps = 0
        t_epoch = time.perf_counter()
        samples = 0
        t_window = time.perf_counter()

        def flush_window():
            # one host sync per window: fetch the buffered device scalars
            nonlocal pending, pending_steps, t_window
            if not pending:
                return
            t_fetch = time.perf_counter()
            vals = list(np.concatenate(
                [np.atleast_1d(np.asarray(v))
                 for v in telemetry.traced_device_get(pending)]
            ).astype(float))
            telemetry.observe_device_block(
                time.perf_counter() - t_fetch, "train_flush")
            losses.extend(vals)
            step = self._py_step
            writer.add_scalar("Loss", vals[-1], step)
            dt = time.perf_counter() - t_window
            throughput = pending_steps * batch_size / max(dt, 1e-9)
            writer.add_scalar("Throughput", throughput, step)
            self._mirror_train_scalars(writer, step, vals[-1], throughput,
                                       dt / max(pending_steps, 1))
            t_window = time.perf_counter()
            pending = []
            pending_steps = 0

        def after_steps(n_steps):
            nonlocal pending_steps, samples
            start = self._py_step
            self._py_step += n_steps
            pending_steps += n_steps
            samples += n_steps * batch_size
            if pending_steps >= summary_interval:
                flush_window()
            # iteration-granular checkpointing, e.g. SeveralIteration(n)
            # (ref Topology.scala checkpointTrigger evaluated per iteration).
            # With steps_per_loop > 1 every intermediate step is tested so
            # SeveralIteration(n) keeps its cadence (at most one snapshot
            # per loop; it reflects the loop-end state).
            if checkpoint_trigger and self.model_dir:
                last = losses[-1] if losses else None
                if any(checkpoint_trigger(self._epoch, s, last)
                       for s in range(start + 1, self._py_step + 1)):
                    flush_window()
                    self._save_snapshot()

        # the per-step profiler decomposes each loop into data-wait (the
        # next() on the device iterator), dispatch (the async jitted
        # call), device (dispatch→ready, measured by fencing — sampled
        # steps only, so the dispatch overlap survives) and callback
        # (summary flush / checkpoint triggers)
        if steps_per_loop > 1:
            it = iter(ds.device_scan_iterator(
                mesh, self.strategy, batch_size, steps_per_loop,
                shuffle=shuffle, seed=self.seed, epoch=self._epoch))
            while True:
                t0 = time.perf_counter()
                try:
                    x, y, k = next(it)
                except StopIteration:
                    break
                t1 = time.perf_counter()
                sampled = step_prof is not None and \
                    step_prof.should_sample(self._py_step)
                # fault-injection step seam: one arrival per compiled
                # train dispatch (a fused scan counts once)
                resilience.maybe_fault("step")
                self._state, loop_losses = self._train_scan(self._state,
                                                            (x, y))
                t2 = time.perf_counter()
                device_s = None
                if sampled:
                    step_prof.ensure_flops(
                        lambda: profiling_lib.compiled_step_flops(
                            self._train_scan, self._state, (x, y)),
                        per_steps=k)
                    jax.block_until_ready(loop_losses)
                    device_s = time.perf_counter() - t1
                pending.append(loop_losses)
                t3 = time.perf_counter()
                after_steps(k)
                if step_prof is not None:
                    step_prof.observe_step(
                        self._py_step, t0, t1 - t0, t2 - t1, device_s,
                        time.perf_counter() - t3, n_steps=k)
                if profile_window is not None:
                    profile_window.on_step(self._py_step)
        else:
            it = iter(ds.device_iterator(mesh, self.strategy, batch_size,
                                         shuffle=shuffle, seed=self.seed,
                                         epoch=self._epoch,
                                         drop_remainder=True))
            while True:
                t0 = time.perf_counter()
                try:
                    x, y, _ = next(it)
                except StopIteration:
                    break
                t1 = time.perf_counter()
                sampled = step_prof is not None and \
                    step_prof.should_sample(self._py_step)
                # fault-injection step seam: one arrival per train step
                resilience.maybe_fault("step")
                self._state, logs = self._train_step(self._state, x, y)
                t2 = time.perf_counter()
                device_s = None
                if sampled:
                    step_prof.ensure_flops(
                        lambda: profiling_lib.compiled_step_flops(
                            self._train_step, self._state, x, y))
                    jax.block_until_ready(logs["loss"])
                    device_s = time.perf_counter() - t1
                pending.append(logs["loss"])
                t3 = time.perf_counter()
                after_steps(1)
                if step_prof is not None:
                    step_prof.observe_step(
                        self._py_step, t0, t1 - t0, t2 - t1, device_s,
                        time.perf_counter() - t3)
                if profile_window is not None:
                    profile_window.on_step(self._py_step)
        flush_window()
        dt = time.perf_counter() - t_epoch
        logger.info("epoch %d: %d samples in %.2fs (%.0f samples/s)",
                    self._epoch, samples, dt, samples / max(dt, 1e-9))
        return float(np.mean(losses)) if losses else float("nan")

    def evaluate(self, data, batch_size: int = 32,
                 feature_cols=None, label_cols=None) -> Dict[str, float]:
        """(ref orca/learn/tf/estimator.py evaluate:656)"""
        import jax
        ds = self._coerce(to_sharded_dataset(data, feature_cols, label_cols))
        mesh = self._ensure_mesh()
        self._init_state()
        self._build_eval_step()
        metric_states = [m.init_state() for m in self.metrics]
        loss_sum = 0.0
        count = 0.0
        for x, y, mask in ds.device_iterator(mesh, self.strategy, batch_size,
                                             drop_remainder=False):
            if mask is None:
                metric_states, ls, c = self._eval_step(
                    self._state, metric_states, x, y)
            else:
                metric_states, ls, c = self._eval_step_masked(
                    self._state, metric_states, x, y, mask)
            loss_sum += float(ls)
            count += float(c)
        out = {"loss": loss_sum / max(count, 1.0)}
        for m, ms in zip(self.metrics, metric_states):
            out[m.name] = m.result(ms)
        return out

    def predict(self, data, batch_size: int = 32, feature_cols=None,
                pipeline_window: int = 2) -> "np.ndarray | XShards":
        """(ref estimator.py predict:598-654; returns XShards when given
        XShards, ndarray otherwise)

        Batches flow through a bounded in-flight dispatch window
        (common/pipeline_io.py): up to ``pipeline_window`` dispatched
        batches stay on the device while the iterator stages the next
        host→device transfer, and ``device_get`` runs only when the window
        retires a batch — never inline with a dispatch. Outputs are
        bit-identical to the synchronous path (``pipeline_window=1`` is
        the synchronous cadence)."""
        import jax
        from analytics_zoo_tpu.common.pipeline_io import DevicePipeline
        was_shards = isinstance(data, XShards)
        if isinstance(data, tuple):
            # predict takes features only — a tuple is a multi-input x, not
            # an (x, y) pair
            data = {"x": data}
        ds = self._coerce(to_sharded_dataset(data, feature_cols, None))
        if ds.n == 0:
            raise ValueError("predict called on an empty dataset")
        mesh = self._ensure_mesh()
        self._init_state()
        self._build_predict()
        outs = []

        def take(comp):
            if comp.error is not None:
                raise comp.error
            preds, mask = comp.result, comp.ctx
            if mask is not None:
                valid = int(np.asarray(mask).sum())
                preds = jax.tree_util.tree_map(lambda a: a[:valid], preds)
            outs.append(preds)

        pipe = DevicePipeline(lambda x: self._predict_fn(self._state, x),
                              window=max(1, int(pipeline_window)),
                              trace_id="estimator_predict")
        with pipe:
            for x, _, mask in ds.device_iterator(
                    mesh, self.strategy, batch_size, drop_remainder=False):
                for comp in pipe.submit(x, ctx=mask):
                    take(comp)
            for comp in pipe.drain():
                take(comp)
        leaves = [jax.tree_util.tree_leaves(o) for o in outs]
        treedef = jax.tree_util.tree_structure(outs[0])
        merged = jax.tree_util.tree_unflatten(
            treedef,
            [np.concatenate([l[i] for l in leaves]) for i in range(len(leaves[0]))])
        if was_shards:
            return HostXShards([{"prediction": merged}])
        return merged

    # ------------- persistence -------------------------------------------
    def _save_snapshot(self):
        path = ckpt_lib.save_checkpoint(self.model_dir, self._state,
                                        self._py_step, self._epoch)
        logger.info("checkpoint saved: %s", path)
        return path

    def save(self, path: str):
        """Save weights + optimizer state (ref spark_estimator.save)."""
        os.makedirs(path, exist_ok=True)
        self._init_state()
        ckpt_lib.save_checkpoint(path, self._state, self._py_step,
                                 self._epoch, max_to_keep=10 ** 9)
        return path

    def load(self, path: str):
        found = ckpt_lib.find_latest_checkpoint(path)
        target = path if found is None else found[0]
        return self.load_orca_checkpoint(target)

    def load_orca_checkpoint(self, path: str, version: Optional[int] = None):
        """(ref orca/learn/tf/estimator.py:270-289)"""
        import jax
        if version is not None:
            path = os.path.join(path, f"ckpt-{version}")
        self._init_state()
        host_state = jax.device_get(self._state)
        state, meta = ckpt_lib.load_checkpoint(path, host_state)
        self._state = jax.device_put(state, self._state_sharding_tree)
        self._epoch = int(meta.get("epoch", 0))
        self._py_step = int(meta.get("iteration", 0))
        return self

    def _auto_resume_reload(self) -> Optional[str]:
        """Reload the newest checkpoint that validates against the live
        state tree (``fit(auto_resume=True)``'s retry boundary). Restores
        step/epoch counters for metric continuity; returns the restored
        path, or None when no version in ``model_dir`` is usable."""
        import jax
        self._init_state()
        host_state = jax.device_get(self._state)
        loaded = ckpt_lib.load_latest_checkpoint(self.model_dir, host_state)
        if loaded is None:
            return None
        state, meta, path = loaded
        self._state = jax.device_put(state, self._state_sharding_tree)
        self._epoch = int(meta.get("epoch", 0))
        self._py_step = int(meta.get("iteration", 0))
        return path

    def get_model(self):
        """Current host-side params pytree (ref spark_estimator.get_model)."""
        import jax
        self._init_state()
        return jax.device_get(self._state["params"])


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _get_by_path(tree, path_str: str):
    cur = tree
    for part in path_str.split("/"):
        if isinstance(cur, dict):
            cur = cur[part]
        else:
            cur = getattr(cur, part, None)
            if cur is None:
                return None
    return cur
