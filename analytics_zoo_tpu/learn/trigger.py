"""Triggers (ref ``pyzoo/zoo/orca/learn/trigger.py:19-76`` → BigDL Trigger).

A trigger decides when checkpoint/validation fire, evaluated on
``(epoch, iteration, loss)`` driver-side state.
"""

from __future__ import annotations


class Trigger:
    def __call__(self, epoch: int, iteration: int, loss: float,
                 score: "float | None" = None) -> bool:
        raise NotImplementedError

    @staticmethod
    def get(t):
        if t is None or isinstance(t, Trigger):
            return t
        raise TypeError(f"expected Trigger, got {type(t)}")


class EveryEpoch(Trigger):
    """Fires at each epoch boundary (ref trigger.py:19-31): the first observed
    epoch value arms the trigger; every subsequent epoch *change* fires."""

    def __init__(self):
        self._last_epoch = None

    def __call__(self, epoch, iteration, loss, score=None):
        fired = self._last_epoch is not None and epoch != self._last_epoch
        self._last_epoch = epoch
        return fired


class SeveralIteration(Trigger):
    """Fires every n iterations (ref trigger.py:34-49)."""

    def __init__(self, interval: int):
        assert interval > 0
        self.interval = interval

    def __call__(self, epoch, iteration, loss, score=None):
        return iteration > 0 and iteration % self.interval == 0


class MaxEpoch(Trigger):
    def __init__(self, max_epoch: int):
        self.max_epoch = max_epoch

    def __call__(self, epoch, iteration, loss, score=None):
        return epoch >= self.max_epoch


class MaxIteration(Trigger):
    def __init__(self, max_iteration: int):
        self.max_iteration = max_iteration

    def __call__(self, epoch, iteration, loss, score=None):
        return iteration >= self.max_iteration


class MinLoss(Trigger):
    def __init__(self, min_loss: float):
        self.min_loss = min_loss

    def __call__(self, epoch, iteration, loss, score=None):
        return loss is not None and loss < self.min_loss


class MaxScore(Trigger):
    """Fires when the validation score exceeds ``max`` (ref
    util/triggers.py:111 MaxScore — accuracy-style metrics where higher
    is better; the estimator passes the first validation metric)."""

    def __init__(self, max: float):
        self.max = float(max)

    def __call__(self, epoch, iteration, loss, score=None):
        return score is not None and score > self.max


class TriggerAnd(Trigger):
    def __init__(self, *triggers):
        self.triggers = triggers

    def __call__(self, epoch, iteration, loss, score=None):
        return all(t(epoch, iteration, loss, score)
                   for t in self.triggers)


class TriggerOr(Trigger):
    def __init__(self, *triggers):
        self.triggers = triggers

    def __call__(self, epoch, iteration, loss, score=None):
        return any(t(epoch, iteration, loss, score)
                   for t in self.triggers)
