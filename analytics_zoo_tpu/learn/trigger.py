"""Triggers (ref ``pyzoo/zoo/orca/learn/trigger.py:19-76`` → BigDL Trigger).

A trigger decides when checkpoint/validation fire, evaluated on
``(epoch, iteration, loss)`` driver-side state.
"""

from __future__ import annotations


class Trigger:
    def __call__(self, epoch: int, iteration: int, loss: float,
                 score: "float | None" = None) -> bool:
        raise NotImplementedError

    @staticmethod
    def get(t):
        if t is None or isinstance(t, Trigger):
            return t
        raise TypeError(f"expected Trigger, got {type(t)}")


def fire(trigger, epoch, iteration, loss, score=None) -> bool:
    """Evaluate a trigger, passing ``score`` only when its ``__call__``
    accepts it — user subclasses written against the old 3-arg signature
    keep working, at the top level AND nested inside composites.

    ``score`` may be the full validation-metrics dict: MaxScore and the
    composites consume it directly; any other trigger gets the first
    non-loss float (the old protocol), so user float-score subclasses
    keep working."""
    import inspect
    if isinstance(score, dict) and \
            not isinstance(trigger, (MaxScore, TriggerAnd, TriggerOr)):
        score = next((v for k, v in score.items() if k != "loss"), None)
    try:
        sig = inspect.signature(trigger.__call__)
        takes_score = ("score" in sig.parameters or any(
            p.kind is inspect.Parameter.VAR_KEYWORD
            for p in sig.parameters.values()))
    except (TypeError, ValueError):
        takes_score = False
    if takes_score:
        return trigger(epoch, iteration, loss, score=score)
    return trigger(epoch, iteration, loss)


class EveryEpoch(Trigger):
    """Fires at each epoch boundary (ref trigger.py:19-31): the first observed
    epoch value arms the trigger; every subsequent epoch *change* fires."""

    def __init__(self):
        self._last_epoch = None

    def __call__(self, epoch, iteration, loss, score=None):
        fired = self._last_epoch is not None and epoch != self._last_epoch
        self._last_epoch = epoch
        return fired


class SeveralIteration(Trigger):
    """Fires every n iterations (ref trigger.py:34-49)."""

    def __init__(self, interval: int):
        assert interval > 0
        self.interval = interval

    def __call__(self, epoch, iteration, loss, score=None):
        return iteration > 0 and iteration % self.interval == 0


class MaxEpoch(Trigger):
    def __init__(self, max_epoch: int):
        self.max_epoch = max_epoch

    def __call__(self, epoch, iteration, loss, score=None):
        return epoch >= self.max_epoch


class MaxIteration(Trigger):
    def __init__(self, max_iteration: int):
        self.max_iteration = max_iteration

    def __call__(self, epoch, iteration, loss, score=None):
        return iteration >= self.max_iteration


class MinLoss(Trigger):
    def __init__(self, min_loss: float):
        self.min_loss = min_loss

    def __call__(self, epoch, iteration, loss, score=None):
        return loss is not None and loss < self.min_loss


# validation metrics where LOWER is better — feeding one of these to
# MaxScore's higher-is-better comparison silently inverts the trigger
ERROR_STYLE_METRICS = frozenset(
    {"loss", "mse", "mae", "rmse", "mape", "smape"})


class MaxScore(Trigger):
    """Fires when the validation score exceeds ``max`` (ref
    util/triggers.py:111 MaxScore — accuracy-style metrics where higher
    is better).

    ``metric`` names which validation metric to watch (e.g.
    ``MaxScore(0.9, metric="accuracy")``); without it the estimator's
    first non-loss validation metric feeds the trigger, with a warning
    when that metric is error-style (lower-is-better), where this
    comparison would never fire."""

    def __init__(self, max: float, metric: "str | None" = None):
        self.max = float(max)
        self.metric = metric
        self._warned = False
        if metric in ERROR_STYLE_METRICS:
            import warnings
            warnings.warn(
                f"MaxScore(metric={metric!r}) watches an error-style "
                "(lower-is-better) metric with a higher-is-better "
                "comparison — it would fire on the WORST epochs; use an "
                "accuracy-style metric")

    def __call__(self, epoch, iteration, loss, score=None):
        if isinstance(score, dict):
            if self.metric is not None:
                score = score.get(self.metric)
            else:
                name, score = next(
                    ((k, v) for k, v in score.items() if k != "loss"),
                    (None, None))
                if name in ERROR_STYLE_METRICS and not self._warned:
                    import warnings
                    warnings.warn(
                        f"MaxScore is watching {name!r}, an error-style "
                        "(lower-is-better) metric — the trigger can never "
                        "fire; name an accuracy-style metric with "
                        "MaxScore(..., metric=...)")
                    self._warned = True
        return score is not None and score > self.max


class TriggerAnd(Trigger):
    def __init__(self, *triggers):
        self.triggers = triggers

    def __call__(self, epoch, iteration, loss, score=None):
        # fire() inspects each sub-trigger so legacy 3-arg user triggers
        # work nested, same as at the top level
        return all(fire(t, epoch, iteration, loss, score)
                   for t in self.triggers)


class TriggerOr(Trigger):
    def __init__(self, *triggers):
        self.triggers = triggers

    def __call__(self, epoch, iteration, loss, score=None):
        return any(fire(t, epoch, iteration, loss, score)
                   for t in self.triggers)
