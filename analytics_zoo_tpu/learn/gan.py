"""GANEstimator — adversarial training as one jitted step.

Parity with the reference's TFGAN-style estimator
(pyzoo/zoo/tfpark/gan/gan_estimator.py:28: generator_fn/discriminator_fn,
separate G/D losses and optimizers, alternating optimization driven through
TFOptimizer). Here the generator and discriminator are flax modules; one
pjit-compiled step samples noise, updates D on real+fake, then updates G
through D — both updates in a single compiled program so the whole
adversarial iteration stays on-device (the reference round-trips through
the JVM per sub-step).

Losses: non-saturating GAN ("minimax") or least-squares ("lsgan")
(ref gan_estimator loss_fns).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np


class GANEstimator:
    def __init__(self, generator, discriminator, noise_dim: int,
                 generator_optimizer="adam", discriminator_optimizer="adam",
                 loss: str = "minimax", seed: int = 0):
        from analytics_zoo_tpu.learn.optimizers import Optimizer
        if loss not in ("minimax", "lsgan"):
            raise ValueError("loss must be 'minimax' or 'lsgan'")
        self.generator = generator
        self.discriminator = discriminator
        self.noise_dim = int(noise_dim)
        self.g_tx = Optimizer.get(generator_optimizer).to_optax()
        self.d_tx = Optimizer.get(discriminator_optimizer).to_optax()
        self.loss = loss
        self.seed = seed
        self._state = None
        self._step_fn = None

    # ------------------------------------------------------------- build
    def _init_state(self, sample_batch):
        import jax
        if self._state is not None:
            return
        rng = jax.random.PRNGKey(self.seed)
        g_rng, d_rng = jax.random.split(rng)
        z = np.zeros((sample_batch.shape[0], self.noise_dim), np.float32)
        g_params = self.generator.init(g_rng, z)
        fake = self.generator.apply(g_params, z)
        d_params = self.discriminator.init(d_rng, fake)
        self._state = {
            "step": np.zeros((), np.int32),
            "g_params": g_params, "d_params": d_params,
            "g_opt": self.g_tx.init(g_params),
            "d_opt": self.d_tx.init(d_params),
        }

    def _build_step(self):
        import jax
        import jax.numpy as jnp
        import optax

        if self._step_fn is not None:
            return
        gen, disc = self.generator, self.discriminator
        g_tx, d_tx = self.g_tx, self.d_tx
        base_rng_seed = self.seed + 101
        lsgan = self.loss == "lsgan"

        def d_loss_fn(d_params, g_params, x, z):
            fake = gen.apply(g_params, z)
            real_logit = disc.apply(d_params, x)
            fake_logit = disc.apply(d_params, fake)
            if lsgan:
                return (jnp.mean((real_logit - 1.0) ** 2)
                        + jnp.mean(fake_logit ** 2)) / 2
            return -(jnp.mean(jax.nn.log_sigmoid(real_logit))
                     + jnp.mean(jax.nn.log_sigmoid(-fake_logit)))

        def g_loss_fn(g_params, d_params, z):
            fake_logit = disc.apply(d_params, gen.apply(g_params, z))
            if lsgan:
                return jnp.mean((fake_logit - 1.0) ** 2)
            return -jnp.mean(jax.nn.log_sigmoid(fake_logit))  # non-saturating

        def step(state, x):
            rng = jax.random.fold_in(
                jax.random.PRNGKey(base_rng_seed), state["step"])
            z = jax.random.normal(rng, (x.shape[0], self.noise_dim),
                                  dtype=jnp.float32)
            d_loss, d_grads = jax.value_and_grad(d_loss_fn)(
                state["d_params"], state["g_params"], x, z)
            d_upd, d_opt = d_tx.update(d_grads, state["d_opt"],
                                       state["d_params"])
            d_params = optax.apply_updates(state["d_params"], d_upd)
            g_loss, g_grads = jax.value_and_grad(g_loss_fn)(
                state["g_params"], d_params, z)
            g_upd, g_opt = g_tx.update(g_grads, state["g_opt"],
                                       state["g_params"])
            g_params = optax.apply_updates(state["g_params"], g_upd)
            new_state = {"step": state["step"] + 1,
                         "g_params": g_params, "d_params": d_params,
                         "g_opt": g_opt, "d_opt": d_opt}
            return new_state, {"d_loss": d_loss, "g_loss": g_loss}

        self._step_fn = jax.jit(step, donate_argnums=0)

    # ------------------------------------------------------------- api
    def fit(self, x, epochs: int = 1, batch_size: int = 32,
            shuffle: bool = True) -> Dict[str, list]:
        """(ref GANEstimator.train)"""
        import jax
        x = np.asarray(x, np.float32)
        if len(x) < batch_size:
            raise ValueError(
                f"dataset size {len(x)} < batch_size {batch_size}: no full "
                "batch can be formed (the trailing partial batch is always "
                "dropped to keep one compiled shape)")
        self._init_state(x[:batch_size])
        self._build_step()
        history = {"d_loss": [], "g_loss": []}
        rng = np.random.default_rng(self.seed)
        for ep in range(epochs):
            idx = rng.permutation(len(x)) if shuffle else np.arange(len(x))
            d_losses, g_losses = [], []
            for lo in range(0, len(x) - batch_size + 1, batch_size):
                batch = x[idx[lo:lo + batch_size]]
                self._state, logs = self._step_fn(self._state, batch)
                d_losses.append(logs["d_loss"])
                g_losses.append(logs["g_loss"])
            history["d_loss"].append(
                float(np.mean(jax.device_get(d_losses))))
            history["g_loss"].append(
                float(np.mean(jax.device_get(g_losses))))
        return history

    def generate(self, n: int, seed: Optional[int] = None) -> np.ndarray:
        """Sample n outputs from the generator (ref gan predict path)."""
        import jax
        if self._state is None:
            raise RuntimeError("fit (or _init_state) before generate")
        rng = jax.random.PRNGKey(self.seed + 7 if seed is None else seed)
        z = jax.random.normal(rng, (n, self.noise_dim), dtype=np.float32)
        return np.asarray(jax.device_get(
            self.generator.apply(self._state["g_params"], z)))
