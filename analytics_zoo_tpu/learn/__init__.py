from analytics_zoo_tpu.learn.estimator import Estimator  # noqa: F401
