"""Optimizers + LR schedules (ref ``pyzoo/zoo/orca/learn/optimizers_impl.py``
327 LoC: SGD/Adam/AdamWeightDecay/LBFGS/... and ``schedule.py`` 218 LoC).

The reference lowers these to BigDL ``OptimMethod`` objects updated
per-partition on the JVM after the allreduce; here each wrapper builds an
``optax`` gradient transformation that runs sharded on-device inside the
jitted train step (optimizer state inherits the parameter sharding, so FSDP
shards it for free).
"""

from __future__ import annotations

from typing import Optional, Union

import optax

Schedule = Union[float, "LRSchedule"]


# ---------------- schedules (ref orca/learn/schedule.py) ----------------

class LRSchedule:
    def to_optax(self, base_lr: float):
        raise NotImplementedError


class Default(LRSchedule):
    def to_optax(self, base_lr):
        return base_lr


class Poly(LRSchedule):
    """(ref schedule.py Poly: lr * (1 - iter/max)^power)"""

    def __init__(self, power: float, max_iteration: int):
        self.power, self.max_iteration = power, max_iteration

    def to_optax(self, base_lr):
        return optax.polynomial_schedule(
            init_value=base_lr, end_value=0.0, power=self.power,
            transition_steps=self.max_iteration)


class Exponential(LRSchedule):
    def __init__(self, decay_step: int, decay_rate: float, stair_case: bool = False):
        self.decay_step, self.decay_rate, self.stair_case = decay_step, decay_rate, stair_case

    def to_optax(self, base_lr):
        return optax.exponential_decay(
            base_lr, transition_steps=self.decay_step,
            decay_rate=self.decay_rate, staircase=self.stair_case)


class Step(LRSchedule):
    def __init__(self, step_size: int, gamma: float):
        self.step_size, self.gamma = step_size, gamma

    def to_optax(self, base_lr):
        return optax.exponential_decay(
            base_lr, transition_steps=self.step_size,
            decay_rate=self.gamma, staircase=True)


class Warmup(LRSchedule):
    """Linear warmup then constant (ref schedule.py Warmup delta)."""

    def __init__(self, warmup_steps: int):
        self.warmup_steps = warmup_steps

    def to_optax(self, base_lr):
        return optax.linear_schedule(0.0, base_lr, self.warmup_steps)


class WarmupCosine(LRSchedule):
    def __init__(self, warmup_steps: int, total_steps: int, end_value: float = 0.0):
        self.warmup_steps, self.total_steps, self.end_value = warmup_steps, total_steps, end_value

    def to_optax(self, base_lr):
        return optax.warmup_cosine_decay_schedule(
            0.0, base_lr, self.warmup_steps, self.total_steps, self.end_value)


def _lr(learning_rate, schedule: Optional[LRSchedule]):
    if schedule is None or isinstance(schedule, Default):
        return learning_rate
    return schedule.to_optax(learning_rate)


# ---------------- optimizers (ref orca/learn/optimizers_impl.py) --------

class Optimizer:
    def to_optax(self) -> optax.GradientTransformation:
        raise NotImplementedError

    @staticmethod
    def get(opt) -> "Optimizer":
        if isinstance(opt, Optimizer):
            return opt
        if isinstance(opt, optax.GradientTransformation):
            return _Raw(opt)
        if isinstance(opt, str):
            name = opt.lower()
            table = {"sgd": SGD, "adam": Adam, "adamw": AdamWeightDecay,
                     "rmsprop": RMSprop, "adagrad": Adagrad,
                     "adadelta": Adadelta, "adamax": Adamax, "nadam": Nadam,
                     "lars": LARS, "lamb": LAMB, "lbfgs": LBFGS}
            if name not in table:
                raise ValueError(f"unknown optimizer {opt!r}")
            return table[name]()
        raise TypeError(f"cannot build optimizer from {type(opt)}")


class _Raw(Optimizer):
    def __init__(self, tx):
        self.tx = tx

    def to_optax(self):
        return self.tx


class SGD(Optimizer):
    """(ref optimizers_impl.py SGD: momentum/dampening/nesterov/wd + schedule)"""

    def __init__(self, learningrate: float = 1e-3, momentum: float = 0.0,
                 nesterov: bool = False, weightdecay: float = 0.0,
                 leaningrate_schedule: Optional[LRSchedule] = None):
        self.lr, self.momentum, self.nesterov = learningrate, momentum, nesterov
        self.weightdecay, self.schedule = weightdecay, leaningrate_schedule

    def to_optax(self):
        parts = []
        if self.weightdecay:
            parts.append(optax.add_decayed_weights(self.weightdecay))
        parts.append(optax.sgd(_lr(self.lr, self.schedule),
                               momentum=self.momentum or None,
                               nesterov=self.nesterov))
        return optax.chain(*parts)


class Adam(Optimizer):
    def __init__(self, learningrate: float = 1e-3, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8,
                 leaningrate_schedule: Optional[LRSchedule] = None):
        self.lr, self.b1, self.b2, self.eps = learningrate, beta1, beta2, epsilon
        self.schedule = leaningrate_schedule

    def to_optax(self):
        return optax.adam(_lr(self.lr, self.schedule), b1=self.b1, b2=self.b2,
                          eps=self.eps)


class AdamWeightDecay(Optimizer):
    """(ref optimizers_impl.py AdamWeightDecay — the BERT optimizer)"""

    def __init__(self, learningrate: float = 1e-3, weight_decay: float = 0.01,
                 beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-6,
                 total: int = -1, warmup_portion: float = -1.0):
        self.lr, self.wd = learningrate, weight_decay
        self.b1, self.b2, self.eps = beta1, beta2, epsilon
        self.total, self.warmup_portion = total, warmup_portion

    def to_optax(self):
        lr = self.lr
        if self.total > 0 and self.warmup_portion > 0:
            lr = optax.warmup_cosine_decay_schedule(
                0.0, self.lr, int(self.total * self.warmup_portion), self.total)
        return optax.adamw(lr, b1=self.b1, b2=self.b2, eps=self.eps,
                           weight_decay=self.wd)


class RMSprop(Optimizer):
    def __init__(self, learningrate: float = 1e-2, decayrate: float = 0.9,
                 epsilon: float = 1e-8):
        self.lr, self.decay, self.eps = learningrate, decayrate, epsilon

    def to_optax(self):
        return optax.rmsprop(self.lr, decay=self.decay, eps=self.eps)


class Adagrad(Optimizer):
    def __init__(self, learningrate: float = 1e-2):
        self.lr = learningrate

    def to_optax(self):
        return optax.adagrad(self.lr)


class Adadelta(Optimizer):
    def __init__(self, learningrate: float = 1.0, decayrate: float = 0.9,
                 epsilon: float = 1e-6):
        self.lr, self.rho, self.eps = learningrate, decayrate, epsilon

    def to_optax(self):
        return optax.adadelta(self.lr, rho=self.rho, eps=self.eps)


class Adamax(Optimizer):
    def __init__(self, learningrate: float = 2e-3, beta1: float = 0.9,
                 beta2: float = 0.999):
        self.lr, self.b1, self.b2 = learningrate, beta1, beta2

    def to_optax(self):
        return optax.adamax(self.lr, b1=self.b1, b2=self.b2)


class Nadam(Optimizer):
    def __init__(self, learningrate: float = 2e-3):
        self.lr = learningrate

    def to_optax(self):
        return optax.nadam(self.lr)


class LARS(Optimizer):
    """Layer-wise adaptive rate scaling — large-batch TPU training."""

    def __init__(self, learningrate: float = 1e-1, momentum: float = 0.9,
                 weight_decay: float = 1e-4):
        self.lr, self.momentum, self.wd = learningrate, momentum, weight_decay

    def to_optax(self):
        return optax.lars(self.lr, weight_decay=self.wd, momentum=self.momentum)


class LAMB(Optimizer):
    def __init__(self, learningrate: float = 1e-3, weight_decay: float = 0.0):
        self.lr, self.wd = learningrate, weight_decay

    def to_optax(self):
        return optax.lamb(self.lr, weight_decay=self.wd)


class LBFGS(Optimizer):
    """Memory-limited BFGS (ref optimizers_impl.py:99 LBFGS, BigDL's
    torch-style implementation). The reference's default path — no line
    search, fixed ``learningrate``-scaled steps along the two-loop
    direction — is exactly ``optax.lbfgs(linesearch=None)``, and that is
    what runs inside the jitted train step here. ``ncorrection`` is the
    history length. The reference's driver-loop knobs (``max_iter``,
    ``max_eval``, ``tolfun``, ``tolx``) govern BigDL's inner convergence
    loop, which has no analog in a per-minibatch SPMD step; they are
    accepted for signature parity and ignored."""

    def __init__(self, max_iter: int = 20, max_eval=None,
                 tolfun: float = 1e-5, tolx: float = 1e-9,
                 ncorrection: int = 100, learningrate: float = 1.0,
                 verbose: bool = False, linesearch=None,
                 linesearch_options=None):
        if linesearch is not None:
            raise ValueError("custom line-search functions are not "
                             "supported inside the jitted step; use the "
                             "default fixed-step mode")
        self.lr = learningrate
        self.ncorrection = int(ncorrection)

    def to_optax(self):
        return optax.lbfgs(self.lr, memory_size=self.ncorrection,
                           linesearch=None)
