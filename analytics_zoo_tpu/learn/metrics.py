"""Framework-neutral metrics (ref ``pyzoo/zoo/orca/learn/metrics.py:19-340``).

The reference lowers metric names to BigDL ``ValidationMethod`` objects
executed on the JVM; here each metric is a pure-functional accumulator —
``init_state() → state``, ``update(state, y_true, y_pred, mask) → state``
(jit-safe, runs on device inside the eval step, so metric math is fused into
the forward pass and only O(1) state returns to host), ``result(state)``.

Surface parity: Accuracy, SparseCategoricalAccuracy, CategoricalAccuracy,
BinaryAccuracy, Top5Accuracy, AUC, MAE, MSE, RMSE, BinaryCrossentropy,
CategoricalCrossentropy, SparseCategoricalCrossentropy, KLDivergence,
Poisson.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np


def _align(y_true, y_pred):
    """Flatten both to [batch, features] so (n,) labels vs (n,1) predictions
    don't broadcast into an (n,n) matrix."""
    y_true = jnp.asarray(y_true)
    y_pred = jnp.asarray(y_pred)
    b = y_pred.shape[0]
    return y_true.reshape(b, -1), y_pred.reshape(b, -1)


def _masked(values, mask):
    """Reduce per-sample values with an optional {0,1} validity mask."""
    values = values.astype(jnp.float32)
    if values.ndim > 1:
        values = values.reshape(values.shape[0], -1).mean(axis=-1)
    if mask is None:
        return values.sum(), jnp.asarray(values.shape[0], jnp.float32)
    return (values * mask).sum(), mask.sum()


class Metric:
    name = "metric"

    def init_state(self):
        return {"total": jnp.zeros((), jnp.float32),
                "count": jnp.zeros((), jnp.float32)}

    def update(self, state, y_true, y_pred, mask=None):
        total, count = _masked(self._per_sample(y_true, y_pred), mask)
        return {"total": state["total"] + total, "count": state["count"] + count}

    def _per_sample(self, y_true, y_pred):
        raise NotImplementedError

    def result(self, state) -> float:
        return float(state["total"] / jnp.maximum(state["count"], 1.0))

    def __repr__(self):
        return f"{type(self).__name__}()"


class Accuracy(Metric):
    """Auto-dispatching accuracy (ref metrics.py Accuracy: zero-based labels).

    binary if y_pred has 1 output, sparse-categorical if labels are integer
    class ids, categorical if labels are one-hot.
    """
    name = "accuracy"

    def _per_sample(self, y_true, y_pred):
        y_pred = jnp.asarray(y_pred)
        y_true = jnp.asarray(y_true)
        if y_pred.ndim <= 1 or y_pred.shape[-1] == 1:
            p = y_pred.reshape(y_pred.shape[0], -1)[:, 0]
            t = y_true.reshape(y_true.shape[0], -1)[:, 0]
            return ((p > 0.5) == (t > 0.5)).astype(jnp.float32)
        pred_cls = jnp.argmax(y_pred, axis=-1)
        if y_true.ndim == y_pred.ndim:
            true_cls = jnp.argmax(y_true, axis=-1)
        else:
            true_cls = y_true.astype(jnp.int32)
        return (pred_cls == true_cls).astype(jnp.float32)


class SparseCategoricalAccuracy(Accuracy):
    name = "sparse_categorical_accuracy"

    def _per_sample(self, y_true, y_pred):
        return (jnp.argmax(y_pred, -1) == jnp.asarray(y_true).astype(jnp.int32)
                ).astype(jnp.float32)


class CategoricalAccuracy(Metric):
    name = "categorical_accuracy"

    def _per_sample(self, y_true, y_pred):
        return (jnp.argmax(y_pred, -1) == jnp.argmax(y_true, -1)).astype(jnp.float32)


class BinaryAccuracy(Metric):
    name = "binary_accuracy"

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold

    def _per_sample(self, y_true, y_pred):
        t, p = _align(y_true, y_pred)
        return ((p > self.threshold) == (t > 0.5)).astype(jnp.float32)


class Top5Accuracy(Metric):
    """(ref metrics.py Top5Accuracy)"""
    name = "top5_accuracy"

    def _per_sample(self, y_true, y_pred):
        y_true = jnp.asarray(y_true)
        if y_true.ndim == jnp.asarray(y_pred).ndim:
            y_true = jnp.argmax(y_true, -1)
        top5 = jnp.argsort(y_pred, axis=-1)[..., -5:]
        return jnp.any(top5 == y_true[..., None], axis=-1).astype(jnp.float32)


class MAE(Metric):
    name = "mae"

    def _per_sample(self, y_true, y_pred):
        t, p = _align(y_true, y_pred)
        return jnp.abs(p - t)


class MSE(Metric):
    name = "mse"

    def _per_sample(self, y_true, y_pred):
        t, p = _align(y_true, y_pred)
        return jnp.square(p - t)


class RMSE(MSE):
    name = "rmse"

    def result(self, state):
        return float(jnp.sqrt(state["total"] / jnp.maximum(state["count"], 1.0)))


class BinaryCrossentropy(Metric):
    name = "binary_crossentropy"

    def _per_sample(self, y_true, y_pred):
        eps = 1e-7
        t, p = _align(y_true, y_pred)
        p = jnp.clip(p, eps, 1 - eps)
        return -(t * jnp.log(p) + (1 - t) * jnp.log1p(-p))


class CategoricalCrossentropy(Metric):
    name = "categorical_crossentropy"

    def _per_sample(self, y_true, y_pred):
        eps = 1e-7
        p = jnp.clip(y_pred, eps, 1.0)
        return -(y_true * jnp.log(p)).sum(-1)


class SparseCategoricalCrossentropy(Metric):
    name = "sparse_categorical_crossentropy"

    def _per_sample(self, y_true, y_pred):
        eps = 1e-7
        p = jnp.clip(y_pred, eps, 1.0)
        idx = jnp.asarray(y_true).astype(jnp.int32)
        return -jnp.log(jnp.take_along_axis(p, idx[..., None], axis=-1))[..., 0]


class KLDivergence(Metric):
    name = "kld"

    def _per_sample(self, y_true, y_pred):
        eps = 1e-7
        t = jnp.clip(y_true, eps, 1.0)
        p = jnp.clip(y_pred, eps, 1.0)
        return (t * jnp.log(t / p)).sum(-1)


class Poisson(Metric):
    name = "poisson"

    def _per_sample(self, y_true, y_pred):
        t, p = _align(y_true, y_pred)
        return p - t * jnp.log(p + 1e-7)


class AUC(Metric):
    """Streaming ROC-AUC over ``num_thresholds`` buckets
    (ref metrics.py AUC → BigDL AUC(20 thresholds); default raised to 200)."""
    name = "auc"

    def __init__(self, num_thresholds: int = 200):
        self.k = num_thresholds

    def init_state(self):
        z = jnp.zeros((self.k,), jnp.float32)
        return {"tp": z, "fp": z, "pos": jnp.zeros((), jnp.float32),
                "neg": jnp.zeros((), jnp.float32)}

    def update(self, state, y_true, y_pred, mask=None):
        y_pred = jnp.asarray(y_pred).reshape(-1)
        y_true = (jnp.asarray(y_true).reshape(-1) > 0.5).astype(jnp.float32)
        m = jnp.ones_like(y_true) if mask is None else jnp.asarray(mask).reshape(-1)
        thresholds = jnp.linspace(0.0, 1.0, self.k)
        pred_ge = (y_pred[None, :] >= thresholds[:, None]).astype(jnp.float32)
        tp = (pred_ge * (y_true * m)[None, :]).sum(-1)
        fp = (pred_ge * ((1 - y_true) * m)[None, :]).sum(-1)
        return {"tp": state["tp"] + tp, "fp": state["fp"] + fp,
                "pos": state["pos"] + (y_true * m).sum(),
                "neg": state["neg"] + ((1 - y_true) * m).sum()}

    def result(self, state):
        tpr = np.asarray(state["tp"]) / max(float(state["pos"]), 1.0)
        fpr = np.asarray(state["fp"]) / max(float(state["neg"]), 1.0)
        # thresholds ascending → fpr descending; integrate |dx| * mean(y)
        trapezoid = getattr(np, "trapezoid", None) or np.trapz  # numpy<2 compat
        return float(np.abs(trapezoid(tpr, fpr)))


_REGISTRY: Dict[str, type] = {
    "accuracy": Accuracy, "acc": Accuracy,
    "sparse_categorical_accuracy": SparseCategoricalAccuracy,
    "categorical_accuracy": CategoricalAccuracy,
    "binary_accuracy": BinaryAccuracy,
    "top5": Top5Accuracy, "top5_accuracy": Top5Accuracy,
    "mae": MAE, "mean_absolute_error": MAE,
    "mse": MSE, "mean_squared_error": MSE,
    "rmse": RMSE,
    "auc": AUC,
    "binary_crossentropy": BinaryCrossentropy,
    "categorical_crossentropy": CategoricalCrossentropy,
    "sparse_categorical_crossentropy": SparseCategoricalCrossentropy,
    "kld": KLDivergence, "kullback_leibler_divergence": KLDivergence,
    "poisson": Poisson,
}


def get(metric) -> Metric:
    """Resolve a metric name or instance (ref metrics.py Metric.get)."""
    if isinstance(metric, Metric):
        return metric
    if isinstance(metric, str):
        key = metric.lower()
        if key not in _REGISTRY:
            raise ValueError(f"unknown metric {metric!r}; known: {sorted(_REGISTRY)}")
        return _REGISTRY[key]()
    raise TypeError(f"metric must be str or Metric, got {type(metric)}")
