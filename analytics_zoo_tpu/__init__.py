"""analytics_zoo_tpu — a TPU-native Big Data AI framework.

A ground-up rebuild of the capability surface of Analytics Zoo
(reference: songhappy/analytics-zoo) on JAX/XLA: where the reference layered
Spark + BigDL + Ray + JNI (reference zoo/pom.xml, pyzoo/zoo/__init__.py), this
framework lowers everything to XLA on a `jax.sharding.Mesh` — data / tensor /
sequence parallelism via sharding specs and pallas kernels, with host-parallel
sharded data loading.

Top-level subpackages (mirroring the reference layer map, SURVEY.md §1):

- ``common``   — context bootstrap + config singleton (ref: pyzoo/zoo/orca/common.py)
- ``data``     — XShards sharded data layer (ref: pyzoo/zoo/orca/data/shard.py)
- ``parallel`` — mesh / sharding strategies / collectives (new capability; ref had
  data-parallel only, see reference Topology.scala:1145-1550)
- ``ops``      — pallas TPU kernels + parallel attention (flash, ring, Ulysses, MoE)
- ``learn``    — Orca-style Estimators: fit/predict/evaluate (ref:
  pyzoo/zoo/orca/learn/)
- ``keras``    — Keras-style layer/model API (ref: pyzoo/zoo/pipeline/api/keras/)
- ``keras2``   — Keras-2 argument spellings (ref: pyzoo/zoo/pipeline/api/keras2/)
- ``net``      — model import: torch fx translation, ONNX protobuf parser
- ``inference``— InferenceModel + int8 weight quantization
- ``models``   — model zoo (ref: pyzoo/zoo/models/, zoo/.../models/)
- ``automl``   — hyperparameter search (ref: pyzoo/zoo/automl/)
- ``zouwu``    — time series: forecasters, AutoTS, anomaly (ref: pyzoo/zoo/zouwu/)
- ``friesian`` — recsys tabular feature engineering (ref: pyzoo/zoo/friesian/)
- ``feature``  — image (2D/3D) + text pipelines incl. QA relations (ref:
  pyzoo/zoo/feature/)
- ``text``     — BERT encoder + task estimators (ref: pyzoo/zoo/tfpark/text/)
- ``nnframes`` — ML-pipeline stages over DataFrames (ref: pyzoo/zoo/pipeline/nnframes/)
- ``serving``  — streaming + batch inference serving (ref: zoo serving/)
"""

from analytics_zoo_tpu.version import __version__  # noqa: F401
from analytics_zoo_tpu.common.context import (  # noqa: F401
    init_orca_context,
    stop_orca_context,
    OrcaContext,
)
