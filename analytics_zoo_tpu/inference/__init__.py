from analytics_zoo_tpu.inference.inference_model import InferenceModel

__all__ = ["InferenceModel"]
