from analytics_zoo_tpu.inference.decode_scheduler import (
    DecodeScheduler, PagedKVAllocator, PagedKVCache, PagePoolExhausted)
from analytics_zoo_tpu.inference.inference_model import InferenceModel

__all__ = ["InferenceModel", "DecodeScheduler", "PagedKVAllocator",
           "PagedKVCache", "PagePoolExhausted"]
