"""Step-level continuous batching for autoregressive decode (ISSUE 16).

PR 14 served generation as whole batches: a ``_GenBatch`` ran prefill
plus its entire decode loop before the engine got the executor back, so
one long generation parked every interactive encode batch behind it.
Here decode is a persistent **step-level scheduler**: a
:class:`DecodeScheduler` holds the set of live sequences and advances
them ONE wide model step at a time — between steps it admits
newly-assembled generate records (their prefill chunked across
iterations), retires finished sequences, and returns to the caller so
encode work interleaves at step granularity.

Underneath, the per-batch ``BucketedKVCache`` buffer is replaced by a
**paged KV allocator**: the decode feedback buffer lives in fixed-size
seq-axis pages drawn from one shared :class:`PagedKVAllocator` pool
sized off the ladder rungs, so rung memory is shared across concurrent
sequences — pages freed by a finishing short generation immediately
back the next admission. Page alloc/free pairing is machine-checked on
every path by the ``kv-page-leak`` zoolint lifecycle rule
(analysis/rules_lifecycle.py).

Speculative decoding rides the same step loop: a small draft model
proposes ``spec_k`` tokens which the (sharded) target model verifies in
one wide step. The acceptance rule — take draft tokens while they match
the target's greedy argmax, then the target's own token at the first
mismatch — makes greedy output **bitwise identical** to step-by-step
decode (the causal rung-padding parity of generation.py applies
unchanged), so the existing parity harness gates it directly. With no
draft model configured every sequence takes the plain one-token step.

Correctness story for interleaving: the decoder is strictly causal in
time and row-independent across the batch, so a sequence's step output
depends only on its OWN live positions — which other sequences share
the wide step, what rung the buffer padded to, and when the scheduler
paused are all invisible bitwise (tests/test_decode_scheduler.py pins
interleaved-vs-isolated equality across admission mid-flight,
preemption boundaries, and page recycling).
"""

from __future__ import annotations

from time import perf_counter
from typing import Callable, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.common import compile_ahead, telemetry
from analytics_zoo_tpu.inference import generation

# metric handles are re-resolved from the live registry on every write
# (registering an existing family is an idempotent dict hit) — a handle
# captured at import time would go stale when telemetry.reset_for_tests
# swaps the registry singleton under a long-lived process

def _m_pages_in_use():
    return telemetry.get_registry().gauge(
        "zoo_kv_pages_in_use",
        "KV pages currently allocated to live decode sequences out of "
        "the shared pool")


def _m_pages_free():
    return telemetry.get_registry().gauge(
        "zoo_kv_pages_free",
        "KV pages currently free in the shared pool — what admission "
        "control checks before accepting a new generate sequence")


def _m_spec_proposed():
    return telemetry.get_registry().counter(
        "zoo_spec_proposed_total",
        "Draft tokens proposed by the speculative-decode draft model")


def _m_spec_accepted():
    return telemetry.get_registry().counter(
        "zoo_spec_accepted_total",
        "Draft tokens accepted by the target model's greedy verification")


def _m_spec_ratio():
    return telemetry.get_registry().gauge(
        "zoo_spec_accept_ratio",
        "Running accepted/proposed ratio of speculative decode — 1.0 "
        "means every draft token survived verification")


def _m_paged_steps():
    return telemetry.get_registry().counter(
        "zoo_paged_attn_steps_total",
        "Wide decode steps dispatched through the paged seam — the page "
        "pool consumed on device via the scalar-prefetched page table "
        "instead of a host-side gather")


def _m_paged_fallback():
    return telemetry.get_registry().counter(
        "zoo_paged_attn_fallback_total",
        "Wide decode steps that took the host gather_into fallback on a "
        "paged-capable scheduler (paged off, no verdict yet, or the "
        "autotune verdict favored gather)")


def _m_zeros_skipped():
    return telemetry.get_registry().counter(
        "zoo_kv_page_zeros_skipped_total",
        "Recycled-page memsets skipped because the paged kernel's length "
        "masking makes stale positions unreadable")


def _m_kv_requants():
    return telemetry.get_registry().counter(
        "zoo_kv_quant_requants_total",
        "int8 KV page requantizations — a later append raised a page's "
        "running amax, so its existing rows were rescaled to the grown "
        "per-page scale")


def _m_kv_pool_bytes():
    return telemetry.get_registry().gauge(
        "zoo_kv_quant_pool_bytes",
        "Resident bytes of the shared KV page pool including per-page "
        "scales — ZOO_KV_DTYPE=int8 shows up here as a ~4x drop at a "
        "fixed page count")


class PagePoolExhausted(RuntimeError):
    """The shared KV page pool cannot hold another sequence right now —
    admission should defer until a live sequence retires its pages."""


def default_pool_pages(max_batch: int, max_seq: int, spec_k: int = 4,
                       page_size: int = generation.DEFAULT_SEQ_RUNGS[0]
                       ) -> int:
    """Page count a scheduler's lazily-built allocator uses for this
    config (``admit``'s ``for_grid`` sizing: worst case per sequence is
    max_seq generated positions + the speculative draft window + one).
    ``InferenceModel.warm_decode`` sizes the paged executables' pool aval
    with it so the first live paged dispatch hits a warmed shape."""
    positions = max(1, int(max_seq) + max(0, int(spec_k)) + 1)
    per_seq = -(-positions // int(page_size))
    return max(1, int(max_batch)) * per_seq


class PagedKVAllocator:
    """Fixed-size seq-axis pages from one shared pool.

    The pool is a single ``[n_pages, page_size, dim]`` block sized off
    the ladder rungs (``for_grid``): enough pages for ``max_batch``
    concurrent worst-case sequences. Sequences own disjoint page lists,
    so a short generation finishing early returns its pages for the next
    admission regardless of what lengths are still in flight — rung
    memory is shared, never per-batch.

    Storage dtype (``kv_dtype``, default from ``ZOO_KV_DTYPE``) may be
    ``int8``: pages then hold symmetric-quantized rows with one float32
    scale per page stored alongside the pool (inference/quantize.py), a
    4x byte drop per page — at a fixed pool byte budget that multiplies
    the admissible concurrent-sequence count. ``dtype`` stays the
    LOGICAL float dtype every reader sees (gathers dequantize).

    Not thread-safe: an allocator belongs to the one scheduler (and so
    the one driving thread) that created it.
    """

    def __init__(self, n_pages: int, page_size: int, dim: int,
                 dtype=np.float32, kv_dtype=None, lazy_zero: bool = False,
                 sync_gauges: bool = True):
        from analytics_zoo_tpu.inference import quantize
        if int(n_pages) < 1 or int(page_size) < 1:
            raise ValueError("need n_pages >= 1 and page_size >= 1")
        self.page_size = int(page_size)
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.kv_dtype = quantize.resolve_kv_dtype(kv_dtype)
        self.quantized = self.kv_dtype == np.dtype(np.int8)
        self._pool = np.zeros((int(n_pages), self.page_size, self.dim),
                              self.kv_dtype if self.quantized
                              else self.dtype)
        # per-page symmetric scale + the running |x|max it derives from;
        # allocated (tiny) for float pools too so pool_view keeps one
        # signature — x * 1.0 is bitwise x
        self._scales = np.ones((int(n_pages),), np.float32)
        self._amax = np.zeros((int(n_pages),), np.float32)
        self._free: List[int] = list(range(int(n_pages)))[::-1]
        self.lazy_zero = bool(lazy_zero)
        self.zeros_skipped = 0
        self._gauges_on = bool(sync_gauges)
        self._sync_gauges()

    @classmethod
    def for_grid(cls, max_batch: int, max_positions: int, dim: int,
                 page_size: int = generation.DEFAULT_SEQ_RUNGS[0],
                 dtype=np.float32, kv_dtype=None) -> "PagedKVAllocator":
        """Pool sized for ``max_batch`` concurrent sequences of up to
        ``max_positions`` each — the (batch rung × seq rung) grid's
        worst case, shared instead of per-batch."""
        per_seq = -(-max(1, int(max_positions)) // int(page_size))
        return cls(max(1, int(max_batch)) * per_seq, page_size, dim,
                   dtype, kv_dtype=kv_dtype)

    @classmethod
    def for_pool_bytes(cls, budget_bytes: int, page_size: int, dim: int,
                       dtype=np.float32, kv_dtype=None
                       ) -> "PagedKVAllocator":
        """Pool sized from a byte budget — the admission-capacity lever
        int8 KV moves: at fixed bytes, int8 pages cost ~4x less than
        float32, so the same budget admits ~4x the sequences."""
        from analytics_zoo_tpu.inference import quantize
        kv = quantize.resolve_kv_dtype(kv_dtype)
        per_page = int(page_size) * int(dim) * kv.itemsize
        if kv == np.dtype(np.int8):
            per_page += 8            # per-page scale + running amax
        n_pages = max(1, int(budget_bytes) // per_page)
        return cls(n_pages, page_size, dim, dtype, kv_dtype=kv)

    # ------------------------------------------------------------ sizing
    @property
    def n_pages(self) -> int:
        return int(self._pool.shape[0])

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_in_use(self) -> int:
        return self.n_pages - self.n_free

    def pages_for(self, positions: int) -> int:
        """Pages needed to hold ``positions`` sequence positions."""
        return -(-max(0, int(positions)) // self.page_size)

    @property
    def page_nbytes(self) -> int:
        """Bytes one page pins in the pool (row storage plus its per-page
        scale/amax entries when quantized) — what
        ``decode_kv_bytes_per_seq`` multiplies out."""
        per = int(self._pool[0].nbytes)
        if self.quantized:
            per += int(self._scales.itemsize + self._amax.itemsize)
        return per

    @property
    def pool_nbytes(self) -> int:
        return int(self._pool.nbytes + self._scales.nbytes
                   + self._amax.nbytes)

    def _sync_gauges(self):
        if not self._gauges_on:
            return
        _m_pages_in_use().set(self.n_in_use)
        _m_pages_free().set(self.n_free)
        _m_kv_pool_bytes().set(self.pool_nbytes)

    def _grow(self, extra: int):
        """Extend the pool (a single request larger than the whole pool
        must still be servable — mirrors the pre-paging behavior where
        the buffer simply grew)."""
        base = self.n_pages
        self._pool = np.concatenate(
            [self._pool,
             np.zeros((int(extra), self.page_size, self.dim),
                      self._pool.dtype)])
        self._scales = np.concatenate(
            [self._scales, np.ones((int(extra),), np.float32)])
        self._amax = np.concatenate(
            [self._amax, np.zeros((int(extra),), np.float32)])
        self._free.extend(range(base + int(extra) - 1, base - 1, -1))
        self._sync_gauges()

    # ------------------------------------------------------- alloc/free
    def alloc_pages(self, n: int) -> List[int]:
        """Take ``n`` zeroed pages from the pool. Raises
        :class:`PagePoolExhausted` when other live sequences hold too
        many pages (the caller defers admission); a single request
        bigger than the entire pool grows it instead — that is capacity
        planning, not contention."""
        n = int(n)
        if n > self.n_pages:
            self._grow(n - self.n_pages)
        if n > len(self._free):
            raise PagePoolExhausted(
                f"need {n} KV pages, {len(self._free)} free of "
                f"{self.n_pages} — waiting for a sequence to retire")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            # quant state always resets (O(1) per page): a recycled
            # page's scale must not dequantize the new owner's rows
            self._scales[p] = 1.0
            self._amax[p] = 0.0
        if self.lazy_zero:
            # the paged kernel's length masking makes stale positions
            # unreadable, so the recycle memset is pure overhead; the
            # gather fallback stays safe too (gather_into copies only
            # positions < length and the step buffer is pre-zeroed)
            self.zeros_skipped += len(pages)
            _m_zeros_skipped().inc(len(pages))
        else:
            # zero on alloc: a recycled page must not leak a previous
            # sequence's positions into the causal zero tail
            for p in pages:
                self._pool[p].fill(0)
        self._sync_gauges()
        return pages

    def free_pages(self, pages: Sequence[int]) -> None:
        """Return pages to the pool — immediately reusable by the next
        admission."""
        self._free.extend(int(p) for p in pages)
        self._sync_gauges()

    # -------------------------------------------------------- row access
    def write_row(self, page: int, off: int, vec: np.ndarray) -> None:
        """Write one position in place (the paged append seam). int8
        pools quantize under the page's symmetric scale, growing it —
        and requantizing the page's existing rows — when this row raises
        the page's running |x|max."""
        from analytics_zoo_tpu.inference import quantize
        if not self.quantized:
            self._pool[page, off, :] = vec
            return
        vec = np.asarray(vec, np.float32)
        amax = float(np.max(np.abs(vec))) if vec.size else 0.0
        if amax > self._amax[page]:
            new_scale = quantize.page_scale(amax)
            if self._amax[page] > 0.0:
                self._pool[page] = quantize.requantize_rows(
                    self._pool[page], self._scales[page], new_scale)
                _m_kv_requants().inc()
            self._scales[page] = new_scale
            self._amax[page] = amax
        self._pool[page, off, :] = quantize.quantize_rows(
            vec, self._scales[page])

    def read_row(self, page: int, off: int) -> np.ndarray:
        """One position as the logical float dtype (dequantized)."""
        from analytics_zoo_tpu.inference import quantize
        if self.quantized:
            return quantize.dequantize_rows(self._pool[page, off, :],
                                            self._scales[page])
        return self._pool[page, off, :].copy()

    def read_page(self, page: int, upto: int) -> np.ndarray:
        """The first ``upto`` rows of a page, dequantized — the SAME
        ``q * scale`` expression the paged kernel fuses, so the gather
        fallback is bitwise the kernel's view of the pool."""
        from analytics_zoo_tpu.inference import quantize
        rows = self._pool[page, :upto, :]
        if self.quantized:
            return quantize.dequantize_rows(rows, self._scales[page])
        return rows

    def pool_view(self):
        """The device-facing view ``(pool, scales)`` — the same backing
        arrays appends write in place, handed to the paged step whole
        (one upload instead of a python loop of page copies). ``scales``
        is all-ones for float pools so the paged seam keeps one
        signature; ``x * 1.0`` is bitwise ``x``."""
        return self._pool, self._scales


class PagedKVCache:
    """One sequence's decode feedback buffer, stored in allocator pages.

    Replaces the sequence's slice of the per-batch ``BucketedKVCache``:
    positions live in fixed-size pages instead of one contiguous
    ``[batch, rung, dim]`` block, so concurrent sequences of different
    lengths share pool memory. ``gather_into`` materializes the live
    positions into one row of the wide step buffer (zeros past
    :attr:`length` — the causal tail the parity claim rests on).

    Not thread-safe: a cache is owned by the one sequence holding it.
    """

    def __init__(self, alloc: PagedKVAllocator, pages: Sequence[int]):
        self._alloc = alloc
        self._pages = list(pages)
        self.length = 0

    @property
    def capacity(self) -> int:
        return len(self._pages) * self._alloc.page_size

    @property
    def n_pages(self) -> int:
        return len(self._pages)

    def _slot(self, pos: int):
        page, off = divmod(int(pos), self._alloc.page_size)
        return self._pages[page], off

    def append(self, vec: np.ndarray) -> None:
        if self.length >= self.capacity:
            # growth beyond the admission reservation: hand fresh pages
            # straight to the owned list (alloc/free stays paired — the
            # pages escape into self._pages in the same expression)
            self._pages.extend(self._alloc.alloc_pages(1))
        p, off = self._slot(self.length)
        self._alloc.write_row(p, off, vec)
        self.length += 1

    def append_block(self, mat: np.ndarray) -> None:
        """Write a chunk of positions (chunked prefill)."""
        for row in np.asarray(mat, self._alloc.dtype):
            self.append(row)

    def set(self, pos: int, vec: np.ndarray) -> None:
        p, off = self._slot(pos)
        self._alloc.write_row(p, off, vec)

    def token_id(self, pos: int) -> int:
        p, off = self._slot(pos)
        # argmax over raw storage is argmax over the dequantized row: the
        # per-page scale is one positive scalar
        return int(np.argmax(self._alloc._pool[p, off, :]))

    def row(self, pos: int) -> np.ndarray:
        p, off = self._slot(pos)
        return self._alloc.read_row(p, off)

    def truncate(self, n: int) -> None:
        """Drop positions ``>= n`` (rejected speculative drafts), zeroing
        them so later gathers see the causal zero tail again (int8 zero
        dequantizes to exact 0.0 under any scale)."""
        n = max(0, int(n))
        for pos in range(n, self.length):
            p, off = self._slot(pos)
            self._alloc._pool[p, off, :] = 0
        self.length = min(self.length, n)

    def gather_into(self, dst: np.ndarray) -> None:
        """Copy live positions into ``dst`` (``[rung, dim]``, pre-zeroed
        by the caller), dequantizing int8 pages with the same per-page
        expression the paged kernel fuses — the fallback and the kernel
        see identical bits."""
        ps = self._alloc.page_size
        pos = 0
        for page in self._pages:
            if pos >= self.length:
                break
            take = min(ps, self.length - pos)
            dst[pos:pos + take, :] = self._alloc.read_page(page, take)
            pos += take

    def page_table(self, width: int) -> np.ndarray:
        """This sequence's device-facing page-table row, padded to
        ``width`` entries with page 0 — a real page the pipelined DMA may
        prefetch, whose contents the kernel's length mask keeps out of
        the result."""
        table = np.zeros((int(width),), np.int32)
        own = self._pages[:int(width)]
        table[:len(own)] = own
        return table

    def close(self) -> None:
        """Free every page back to the pool (idempotent)."""
        pages, self._pages = self._pages, []
        self.length = 0
        self._alloc.free_pages(pages)


class DecodeSequence:
    """One live generation: its encoder row, paged cache, decode params,
    per-sequence rng stream, and the generated output buffer.
    Not thread-safe — owned by one scheduler."""

    __slots__ = ("enc", "cache", "prefill", "max_new_tokens", "mode",
                 "temperature", "rng", "gen", "generated", "tag", "lane",
                 "trace_uri", "error", "_prefill_pos", "_drafts",
                 "t_admit", "device_s", "pages_held")

    def __init__(self, enc, prefill, max_new_tokens, mode, temperature,
                 seed, cache, tag, lane, trace_uri):
        self.enc = enc
        self.prefill = prefill                  # [S, dim] teacher-forced
        self.max_new_tokens = int(max_new_tokens)
        self.mode = mode
        self.temperature = float(temperature)
        self.rng = np.random.default_rng(seed) if mode == "sample" \
            else None
        self.cache = cache
        dim = int(prefill.shape[-1])
        self.gen = np.zeros((self.max_new_tokens, dim), np.float32)
        self.generated = 0
        self.tag = tag
        self.lane = lane
        self.trace_uri = trace_uri
        self.error: Optional[BaseException] = None
        self._prefill_pos = 0
        self._drafts = 0
        self.t_admit = perf_counter()
        # cost attribution, settled by the engine when the sequence
        # finishes: device_s accumulates this sequence's share of every
        # wide step's wall time; pages_held tracks the cache's page high
        # water (captured just before close frees them)
        self.device_s = 0.0
        self.pages_held = int(cache.n_pages)

    @property
    def prefilled(self) -> bool:
        return self._prefill_pos >= self.prefill.shape[0]

    @property
    def done(self) -> bool:
        return self.generated >= self.max_new_tokens

    @property
    def result(self) -> np.ndarray:
        return self.gen

    def _feed(self, row: np.ndarray) -> np.ndarray:
        """One step's raw prediction row -> the vector fed back, via the
        same per-row feedback rule as generation.decode_loop. The rng
        stream is PER SEQUENCE, so sample output is independent of which
        other sequences shared the wide step."""
        fed = generation.feedback_rows(row[None], self.mode,
                                       self.temperature, self.rng)[0]
        self.cache.append(fed)
        self.gen[self.generated, :] = fed
        self.generated += 1
        return fed


class DecodeScheduler:
    """The persistent step-level decode loop.

    ``step_fn(enc, dec) -> [batch, t_dec, dim]`` is the full-sequence
    decoder (the model's AOT dispatch seam — e.g.
    ``InferenceModel.decode_step_fn()``). ``draft_fn`` is the same
    signature on a small draft model; with ``spec_k > 0`` greedy
    sequences decode speculatively and everything else takes the plain
    one-token step (clean fallback).

    One ``step()`` = advance chunked prefill, run ONE wide target step
    over every live sequence (padded to the batch/seq rungs the
    compile-ahead grid warmed), feed each sequence at its own position,
    and retire the finished ones. The caller owns the cadence — the
    serving engine interleaves encode batches between calls and counts
    a preemption each time it defers a step to interactive work.

    Not thread-safe: each scheduler instance is confined to its driving
    thread — the engine's serve loop owns its scheduler outright, and a
    direct ``InferenceModel.generate`` call owns a private one for the
    duration of the call. Nothing ever shares an instance across
    threads, so admit/step/drain need no internal lock.
    """

    def __init__(self, step_fn: Callable, *,
                 max_batch: int = 8,
                 max_seq: int = generation.DEFAULT_SEQ_RUNGS[1],
                 page_size: int = generation.DEFAULT_SEQ_RUNGS[0],
                 batch_ladder: Optional[compile_ahead.BucketLadder] = None,
                 allocator: Optional[PagedKVAllocator] = None,
                 draft_fn: Optional[Callable] = None, spec_k: int = 4,
                 prefill_chunk: int = 32,
                 paged_step_fn: Optional[Callable] = None,
                 paged: str = "auto"):
        if paged not in ("auto", "force", "off"):
            raise ValueError(
                f"paged must be auto|force|off, got {paged!r}")
        self._step_fn = step_fn
        # paged seam: ``(enc, pool, scales, table, lengths) ->
        # [rung, width*page_size, dim]`` — the wide TARGET step consuming
        # the page pool directly (InferenceModel.paged_decode_step_fn).
        # "auto" dispatches it per shape when the autotune verdict wins
        # (gather stays the numerics reference — never slower by
        # construction); "force"/"off" pin the path for parity tests.
        self._paged_step_fn = paged_step_fn
        self._paged = paged
        self._draft_fn = draft_fn
        self.spec_k = max(0, int(spec_k))
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.max_batch = max(1, int(max_batch))
        self.max_seq = max(2, int(max_seq))
        self.page_size = max(1, int(page_size))
        self._batch_ladder = batch_ladder or compile_ahead.BucketLadder(
            1, self.max_batch)
        self._seq_ladder = generation.seq_ladder(
            self.max_seq + self.spec_k + 1, min_rung=self.page_size)
        self._alloc = allocator
        self._prefilling: List[DecodeSequence] = []
        self._decoding: List[DecodeSequence] = []
        self._tracer = telemetry.get_tracer()
        self._spec_proposed = 0
        self._spec_accepted = 0
        self.steps_run = 0

    # ---------------------------------------------------------- admission
    @property
    def allocator(self) -> Optional[PagedKVAllocator]:
        return self._alloc

    @property
    def live(self) -> int:
        """Sequences currently admitted (prefilling + decoding)."""
        return len(self._prefilling) + len(self._decoding)

    def admit(self, enc, start, max_new_tokens: int, *,
              mode: str = "greedy", temperature: float = 1.0,
              seed: Optional[int] = None, tag=None,
              lane: str = "default",
              trace_uri: Optional[str] = None) -> DecodeSequence:
        """Admit one generation: reserve its worst-case pages up front
        (admission control — a sequence the pool cannot hold right now
        raises :class:`PagePoolExhausted` instead of stalling mid-decode)
        and queue its prefill, chunked across the next steps."""
        if mode not in generation.MODES:
            raise ValueError(
                f"mode must be one of {generation.MODES}, got {mode!r}")
        steps = int(max_new_tokens)
        if steps < 1:
            raise ValueError("max_new_tokens must be >= 1")
        enc = np.asarray(enc)
        prefill = np.asarray(start, np.float32)
        if prefill.ndim == 1:
            prefill = prefill[None, :]
        if prefill.ndim != 2:
            raise ValueError("start must be [dim] or [prefill_len, dim]")
        if self._alloc is None:
            self._alloc = PagedKVAllocator(
                default_pool_pages(self.max_batch, self.max_seq,
                                   self.spec_k, self.page_size),
                self.page_size, int(prefill.shape[-1]))
        # worst case: prefill + every generated position + a transient
        # speculative draft window past the live length
        need = self._alloc.pages_for(
            prefill.shape[0] + steps + self.spec_k)
        pages = self._alloc.alloc_pages(need)
        try:
            seq = DecodeSequence(enc, prefill, steps, mode, temperature,
                                 seed, PagedKVCache(self._alloc, pages),
                                 tag, lane, trace_uri)
        except Exception:
            self._alloc.free_pages(pages)
            raise
        self._prefilling.append(seq)
        return seq

    def abort_all(self) -> List[DecodeSequence]:
        """Drop every live sequence and free its pages (broker reconnect:
        the entries will redeliver — at-least-once, never double-ack)."""
        dropped = self._prefilling + self._decoding
        self._prefilling, self._decoding = [], []
        for seq in dropped:
            seq.cache.close()
        return dropped

    # -------------------------------------------------------------- steps
    def _advance_prefill(self):
        """Chunked prefill slotted into the decode cadence: each step
        copies at most ``prefill_chunk`` positions per sequence, so one
        long prompt cannot stall the step cadence of live decodes."""
        still = []
        for seq in self._prefilling:
            lo = seq._prefill_pos
            hi = min(lo + self.prefill_chunk, seq.prefill.shape[0])
            if hi > lo:
                seq.cache.append_block(seq.prefill[lo:hi])
                seq._prefill_pos = hi
            if seq.prefilled:
                self._decoding.append(seq)
            else:
                still.append(seq)
        self._prefilling = still

    def step(self) -> List[DecodeSequence]:
        """Advance every live sequence by one wide target step (greedy
        sequences by up to ``spec_k + 1`` tokens when a draft model is
        configured). Returns the sequences that finished this step,
        their pages already back in the pool."""
        self._advance_prefill()
        if not self._decoding:
            return []
        finished: List[DecodeSequence] = []
        # one wide call per encoder shape — heterogeneous generate kinds
        # (different params, different shapes) share the scheduler
        groups = {}
        for seq in self._decoding:
            groups.setdefault(tuple(seq.enc.shape), []).append(seq)
        for seqs in groups.values():
            for lo in range(0, len(seqs), self.max_batch):
                finished.extend(self._step_group(
                    seqs[lo:lo + self.max_batch]))
        self._decoding = [s for s in self._decoding
                          if s not in finished]
        self.steps_run += 1
        return finished

    def drain(self) -> List[DecodeSequence]:
        """Step until no sequence is live — the batch-mode cadence
        (InferenceModel.generate with a draft model rides this)."""
        out: List[DecodeSequence] = []
        while self.live:
            out.extend(self.step())
        return out

    def _materialize(self, seqs: List[DecodeSequence], seq_rung: int):
        """Stack encoder rows and gather paged caches into the wide
        ``[batch_rung, seq_rung, dim]`` step buffer the compile-ahead
        grid warmed — pad rows repeat the last sequence (pad_to_rung),
        their outputs are never read."""
        rung = self._batch_rung(len(seqs))
        enc = np.stack([s.enc for s in seqs])
        dec = np.zeros((len(seqs), seq_rung, self._alloc.dim),
                       self._alloc.dtype)
        for i, s in enumerate(seqs):
            s.cache.gather_into(dec[i])
        enc, dec = compile_ahead.pad_to_rung((enc, dec), rung,
                                             site="decode")
        return enc, dec

    def _batch_rung(self, n: int) -> int:
        rung = min(self._batch_ladder.rung_for(n), self.max_batch)
        return max(rung, n)

    def _use_paged_step(self, seqs: List[DecodeSequence],
                        seq_rung: int) -> bool:
        """Per-shape paged-vs-gather dispatch decision. ``force``/``off``
        pin the path; ``auto`` consults the autotune verdict for the
        step shape — a miss tunes on the spot in sync mode, else
        enqueues a synthetic measurement for the warmup worker and takes
        the gather reference this time (never-slower by construction)."""
        if self._paged_step_fn is None or self._paged == "off":
            return False
        if self._paged == "force":
            return True
        from analytics_zoo_tpu.ops import autotune, paged_attention
        if autotune._mode() == "off":
            return False
        rung = self._batch_rung(len(seqs))
        enc_shape = tuple(seqs[0].enc.shape)
        key = paged_attention.step_key(
            rung, seq_rung, self.page_size, self._alloc.dim,
            self._alloc.n_pages, self._alloc.kv_dtype, enc_shape)
        rec = autotune.get_tuner().lookup(key, "paged_step")
        if rec is None:
            thunk = self._paged_tune_thunk(rung, seq_rung, enc_shape, key)
            if autotune._mode() == "sync":
                rec = thunk()
            else:
                autotune.enqueue_tune(key, thunk)
                return False
        return bool(rec.get("use_kernel"))

    def _paged_tune_thunk(self, rung: int, seq_rung: int, enc_shape,
                          key: str) -> Callable[[], dict]:
        """Closure measuring one wide step via host gather vs via the
        paged seam, end to end (``Autotuner.tune_thunks`` — host thunks,
        because the gather fallback's cost is host-side python a jit
        harness cannot see). Runs on SYNTHETIC state at the live shapes:
        its own private allocator, never the serving pool."""
        step_fn, paged_fn = self._step_fn, self._paged_step_fn
        page_size, dim = self.page_size, self._alloc.dim
        n_pages, kv_dtype = self._alloc.n_pages, self._alloc.kv_dtype

        def thunk() -> dict:
            from analytics_zoo_tpu.ops import autotune
            rng = np.random.default_rng(0)
            alloc = PagedKVAllocator(n_pages, page_size, dim,
                                     kv_dtype=kv_dtype, sync_gauges=False)
            width = alloc.pages_for(seq_rung)
            fill = max(1, seq_rung - 1)
            caches = []
            for _ in range(rung):
                cache = PagedKVCache(alloc, alloc.alloc_pages(width))
                cache.append_block(
                    rng.standard_normal((fill, dim)).astype(np.float32))
                caches.append(cache)
            enc = rng.standard_normal(
                (rung,) + tuple(enc_shape)).astype(np.float32)
            table = np.stack([c.page_table(width) for c in caches])
            lengths = np.array([c.length for c in caches], np.int32)
            pool, scales = alloc.pool_view()

            def gather():
                dec = np.zeros((rung, seq_rung, dim), np.float32)
                for i, c in enumerate(caches):
                    c.gather_into(dec[i])
                return np.asarray(step_fn(enc, dec))

            def paged():
                return np.asarray(
                    paged_fn(enc, pool, scales, table, lengths))

            return autotune.get_tuner().tune_thunks(
                "paged_step", key, {"paged": paged}, gather)

        return thunk

    def tune_paged(self, batch_rung: Optional[int] = None,
                   seq_rung: Optional[int] = None,
                   enc_shape=None) -> Optional[dict]:
        """Synchronously measure gather-vs-paged for one step shape and
        persist the verdict ``paged="auto"`` dispatch consults (what
        bench.py and tests call; the serve path tunes in the background
        instead). Shape arguments default to the live sequences'.
        Returns None when no paged seam or allocator exists yet."""
        if self._paged_step_fn is None or self._alloc is None:
            return None
        live = self._prefilling + self._decoding
        if batch_rung is None:
            batch_rung = self._batch_rung(max(1, len(live)))
        if seq_rung is None:
            want = max((s.cache.length + 1 for s in live), default=2)
            seq_rung = self._seq_ladder.rung_for(want)
        if enc_shape is None:
            if not live:
                raise ValueError(
                    "enc_shape is required when no sequence is live")
            enc_shape = tuple(live[0].enc.shape)
        from analytics_zoo_tpu.ops import paged_attention
        key = paged_attention.step_key(
            int(batch_rung), int(seq_rung), self.page_size,
            self._alloc.dim, self._alloc.n_pages, self._alloc.kv_dtype,
            tuple(enc_shape))
        return self._paged_tune_thunk(int(batch_rung), int(seq_rung),
                                      tuple(enc_shape), key)()

    def _paged_step(self, seqs: List[DecodeSequence],
                    seq_rung: int) -> np.ndarray:
        """The paged analog of ``_materialize`` + step: hand the step the
        pool itself plus each sequence's page table and live length — the
        gather happens on device, driven by the scalar-prefetched table.
        Pad rows repeat the last sequence's table and length (the
        pad_to_rung convention: their outputs are never read, and
        repeating keeps the dispatch identical to the gather path's)."""
        rung = self._batch_rung(len(seqs))
        width = self._alloc.pages_for(seq_rung)
        enc = np.stack([s.enc for s in seqs])
        (enc,) = compile_ahead.pad_to_rung((enc,), rung, site="decode")
        table = np.stack([s.cache.page_table(width) for s in seqs])
        lengths = np.array([s.cache.length for s in seqs], np.int32)
        if len(seqs) < rung:
            pad = rung - len(seqs)
            table = np.concatenate(
                [table, np.repeat(table[-1:], pad, axis=0)])
            lengths = np.concatenate(
                [lengths, np.repeat(lengths[-1:], pad)])
        pool, scales = self._alloc.pool_view()
        out = np.asarray(
            self._paged_step_fn(enc, pool, scales, table, lengths))
        # kernel length masking is live from here on: recycled pages stop
        # paying the memset (the gather fallback stays safe — it only
        # ever copies positions < length into a pre-zeroed buffer)
        self._alloc.lazy_zero = True
        _m_paged_steps().inc()
        return out

    def _step_group(self, seqs: List[DecodeSequence]
                    ) -> List[DecodeSequence]:
        t0 = perf_counter()
        spec = [s for s in seqs
                if self._draft_fn is not None and self.spec_k > 0
                and s.mode == "greedy"]
        if spec:
            self._propose(spec)
        seq_rung = self._seq_ladder.rung_for(
            max(s.cache.length + 1 for s in seqs))
        if self._use_paged_step(seqs, seq_rung):
            # the wide TARGET step goes paged; outputs agree bitwise with
            # the gather path because the on-device gather materializes
            # the identical (dequantized, causally zero-tailed) buffer
            out = self._paged_step(seqs, seq_rung)
        else:
            enc, dec = self._materialize(seqs, seq_rung)
            out = np.asarray(self._step_fn(enc, dec))
            if self._paged_step_fn is not None and self._paged != "off":
                _m_paged_fallback().inc()
        finished = []
        for i, s in enumerate(seqs):
            before = s.generated
            if s._drafts:
                self._verify(s, out[i])
            else:
                s._feed(out[i, s.cache.length - 1, :])
            generation.count_decode_steps(s.generated - before)
            t1 = perf_counter()
            if s.trace_uri is not None:
                for g in range(before + 1, s.generated + 1):
                    self._tracer.record(s.trace_uri, f"decode_step_{g}",
                                        t0, t1, parent="device")
            if s.done:
                s.pages_held = max(s.pages_held, s.cache.n_pages)
                s.cache.close()
                finished.append(s)
        # bill every participant an equal share of the wide step's wall
        # time — the per-request device-seconds the engine settles into
        # zoo_request_cost_device_seconds when the sequence finishes
        share = (perf_counter() - t0) / max(1, len(seqs))
        for s in seqs:
            s.device_s += share
        return finished

    # ------------------------------------------------- speculative decode
    @property
    def spec_accept_ratio(self) -> float:
        if self._spec_proposed == 0:
            return 0.0
        return self._spec_accepted / self._spec_proposed

    def _propose(self, seqs: List[DecodeSequence]):
        """Draft phase: the small model proposes up to ``spec_k`` greedy
        tokens per sequence, written into the paged cache past the live
        length (rejected ones are truncated back to zeros)."""
        want = {s: min(self.spec_k, s.max_new_tokens - s.generated)
                for s in seqs}
        for j in range(max(want.values())):
            live = [s for s in seqs if want[s] > j]
            if not live:
                break
            seq_rung = self._seq_ladder.rung_for(
                max(s.cache.length + 1 for s in live))
            enc, dec = self._materialize(live, seq_rung)
            out = np.asarray(self._draft_fn(enc, dec))
            for i, s in enumerate(live):
                row = out[i, s.cache.length - 1, :]
                fed = generation.feedback_rows(row[None], "greedy",
                                               1.0, None)[0]
                s.cache.append(fed)
                s._drafts += 1

    def _verify(self, s: DecodeSequence, out_row: np.ndarray):
        """Acceptance: walk the drafts against the target's own greedy
        argmax at each position — identical prefixes mean identical
        causal outputs, so every accepted token is bitwise the token
        step-by-step greedy would have produced; the first mismatch is
        replaced by the target's token and the rest are truncated. All
        drafts accepted earns the bonus token the wide step already
        computed."""
        k = s._drafts
        t0 = s.cache.length - k                # live length before drafts
        accepted = 0
        mismatched = False
        for j in range(k):
            if s.done:
                break
            # accepted drafts are exactly the step-by-step greedy tokens,
            # so by causality out_row[t0+j-1] is bitwise the output the
            # sequential loop would have computed at this position
            tgt = int(np.argmax(out_row[t0 + j - 1, :]))
            if tgt == s.cache.token_id(t0 + j):
                accepted += 1
                s.gen[s.generated, :] = s.cache.row(t0 + j)
                s.generated += 1
            else:
                fed = np.zeros(self._alloc.dim, np.float32)
                fed[tgt] = 1.0
                s.cache.truncate(t0 + j)       # drop this + later drafts
                s.cache.append(fed)            # target's own token instead
                s.gen[s.generated, :] = fed
                s.generated += 1
                mismatched = True
                break
        if not mismatched:
            s.cache.truncate(t0 + accepted)    # drop unconsumed drafts
            if accepted == k and not s.done:
                # every draft survived: the wide step's last position is
                # the free extra token of standard speculative decoding
                s._feed(out_row[t0 + k - 1, :])
        self._spec_proposed += k
        self._spec_accepted += accepted
        s._drafts = 0
        _m_spec_proposed().inc(k)
        _m_spec_accepted().inc(accepted)
        if self._spec_proposed:
            _m_spec_ratio().set(self._spec_accepted / self._spec_proposed)
