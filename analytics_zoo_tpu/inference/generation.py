"""Bucketed autoregressive decode — seq-length rungs, never a
per-request recompile.

The reference's ``Seq2Seq.infer`` zero-pads the decoder buffer to one
fixed ``max_seq_len`` so XLA compiles once — every request pays the
longest generation's compute. Here the decode buffer lives in a
:class:`BucketedKVCache`: it is padded to the current **seq-length rung**
of a :class:`~analytics_zoo_tpu.common.compile_ahead.BucketLadder` and
grows rung→rung as generation proceeds, so short generations run short
shapes and the whole length range compiles to a handful of executables —
all AOT-warmable through the same compile-ahead ladder the batch axis
already uses.

Correctness leans on causality, not luck: the decoder is a
strictly-causal scan over time, so step ``t``'s output depends only on
positions ``<= t`` — zero padding past the live positions cannot change
it, and rung-padded decode is **bitwise identical** to an unpadded
reference (asserted by tests/test_generation.py, tail lengths and
rung-growth boundaries included).
"""

from __future__ import annotations

from time import perf_counter
from typing import Callable, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.common import compile_ahead, telemetry

#: generation modes: ``raw`` feeds the predicted vector straight back
#: (the reference ``Seq2Seq.infer`` semantics); ``greedy`` feeds the
#: one-hot argmax; ``sample`` feeds a one-hot temperature sample.
MODES = ("raw", "greedy", "sample")

#: default seq-length ladder bounds for generate requests
DEFAULT_SEQ_RUNGS = (8, 128)

# metric handles are re-resolved from the live registry on every write
# (registering an existing family is an idempotent dict hit) — a handle
# captured at import time would go stale when telemetry.reset_for_tests
# swaps the registry singleton under a long-lived process


def _m_decode_steps():
    return telemetry.get_registry().counter(
        "zoo_decode_steps_total",
        "Autoregressive decode steps executed (one per generated position "
        "per batch dispatch)")


def _m_kv_rung():
    return telemetry.get_registry().gauge(
        "zoo_kv_cache_rung",
        "Current seq-length rung of the bucketed decode/KV cache — climbs "
        "power-of-two rungs as generation proceeds, never per-step shapes")


def seq_ladder(max_seq_len: int,
               min_rung: int = DEFAULT_SEQ_RUNGS[0]):
    """The seq-length rung ladder for generations up to ``max_seq_len``."""
    lo = max(2, min(int(min_rung), int(max_seq_len)))
    return compile_ahead.BucketLadder(lo, max(lo, int(max_seq_len)))


class BucketedKVCache:
    """The decoder feedback buffer, padded to the live seq-length rung.

    For the RNN seq2seq zoo the "KV cache" *is* the teacher-forcing
    buffer the model re-consumes each step; attention models slot their
    key/value blocks behind the same rung discipline. ``view()`` is
    always ``[batch, rung, dim]`` with zeros past :attr:`length`, so the
    shapes XLA sees are exactly the ladder's rungs.
    """

    def __init__(self, batch: int, dim: int, ladder=None,
                 start: Optional[np.ndarray] = None,
                 dtype=np.float32):
        self.ladder = ladder
        self.length = 0
        self.dim = int(dim)
        rung = ladder.rung_for(1) if ladder is not None else 1
        self._buf = np.zeros((int(batch), int(rung), self.dim), dtype)
        if start is not None:
            self.append(np.asarray(start, dtype))
        _m_kv_rung().set(self.rung)

    @property
    def rung(self) -> int:
        return int(self._buf.shape[1])

    def append(self, vec: np.ndarray) -> None:
        """Write one position; grow buffer to the next rung when full.
        Growth re-pads with zeros — never a per-step shape."""
        if self.length == self._buf.shape[1]:
            new_rung = (self.ladder.rung_for(self.length + 1)
                        if self.ladder is not None else self.length + 1)
            grown = np.zeros((self._buf.shape[0], new_rung, self.dim),
                             self._buf.dtype)
            grown[:, :self.length, :] = self._buf
            self._buf = grown
            _m_kv_rung().set(self.rung)
        self._buf[:, self.length, :] = vec
        self.length += 1

    def view(self) -> np.ndarray:
        return self._buf


def sample_token_ids(vec: np.ndarray, temperature: float,
                     rng: np.random.Generator) -> np.ndarray:
    """Vectorized Gumbel-max temperature sampling: one token id per row.

    Distributionally identical to softmax(``vec/t``) sampling but with no
    per-row Python loop on the host hot path. The rng stream contract —
    pinned by tests/test_generation.py — is exactly ONE uniform draw of
    ``vec.shape`` per call (``rng.random(vec.shape)``), so a sequence
    sampling alone consumes the same stream as the same sequence inside
    a wider batch row-for-row only when it owns its own generator (the
    step scheduler gives every sequence a private seeded rng for this
    reason).
    """
    t = max(float(temperature), 1e-6)
    u = rng.random(vec.shape)
    # guard the (measure-zero) u == 0.0 draw; log(-log(u)) must be finite
    u = np.maximum(u, np.finfo(np.float64).tiny)
    gumbel = -np.log(-np.log(u))
    return np.argmax(vec / t + gumbel, axis=-1)


def feedback_rows(vec: np.ndarray, mode: str, temperature: float,
                  rng: Optional[np.random.Generator]) -> np.ndarray:
    """Turn one step's raw prediction rows into the vectors fed back."""
    if mode == "raw":
        return vec
    if mode == "greedy":
        ids = np.argmax(vec, axis=-1)
    else:                                   # sample
        ids = sample_token_ids(vec, temperature, rng)
    out = np.zeros_like(vec)
    out[np.arange(vec.shape[0]), ids] = 1.0
    return out


def count_decode_steps(n: int) -> None:
    """Bump the decode-steps counter by ``n`` generated positions — the
    step scheduler's wide steps account here alongside decode_loop."""
    if n > 0:
        _m_decode_steps().inc(int(n))


def decode_loop(predict_fn: Callable[[np.ndarray, np.ndarray], np.ndarray],
                input_seq: np.ndarray, start_sign: np.ndarray,
                max_new_tokens: int, *, ladder=None, mode: str = "raw",
                temperature: float = 1.0, seed: Optional[int] = None,
                trace_ids: Sequence[str] = ()) -> np.ndarray:
    """Run the autoregressive loop: prefill + ``max_new_tokens`` steps
    through the bucketed cache.

    ``predict_fn(enc, dec) -> [batch, t_dec, dim]`` is the full-sequence
    decoder (the jitted/AOT model apply); step ``t`` reads position
    ``t-1`` of its output, exactly the reference ``infer`` recurrence.
    ``ladder=None`` runs the exact-length unpadded reference (one shape
    per step — the parity baseline, not a serving path). Returns the
    generated ``[batch, max_new_tokens, dim]`` sequence (raw vectors, or
    one-hot rows for greedy/sample).
    """
    if mode not in MODES:
        raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
    input_seq = np.asarray(input_seq)
    start = np.asarray(start_sign, np.float32)
    batch, dim = input_seq.shape[0], start.shape[-1]
    steps = int(max_new_tokens)
    if steps < 1:
        raise ValueError("max_new_tokens must be >= 1")
    rng = np.random.default_rng(seed) if mode == "sample" else None
    tracer = telemetry.get_tracer()

    cache = BucketedKVCache(batch, dim, ladder, start)
    gen = np.zeros((batch, steps, dim), np.float32)
    for t in range(1, steps + 1):
        t0 = perf_counter()
        # the buffer holds positions [0, t) — output t-1 is causal in
        # them, so the rung's zero tail cannot change it
        out = np.asarray(predict_fn(input_seq, cache.view()))
        fed = feedback_rows(out[:, t - 1, :], mode, temperature, rng)
        cache.append(fed)
        gen[:, t - 1, :] = fed
        _m_decode_steps().inc(batch)
        t1 = perf_counter()
        for uri in trace_ids:
            tracer.record(uri, f"decode_step_{t}", t0, t1, parent="device")
    return gen
