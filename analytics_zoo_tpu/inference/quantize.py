"""Post-training int8 weight quantization for inference.

Ref capability: BigDL/zoo model quantization — "up to 2× inference
speedup, 4× model-size reduction, <0.1% accuracy drop"
(SURVEY.md §6 baseline table; the reference exposes it as
``model.quantize()`` / InferenceModel int8 paths backed by MKL int8
kernels). TPU-native version: symmetric per-output-channel weight-only
int8 — weights live in HBM as int8 (4× smaller), the dequantize
multiply fuses into the consuming matmul under jit, and on int8-capable
MXUs XLA can keep the mac in low precision. Activations stay float:
weight-only is the accuracy-safe default for the model-zoo scale.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np


class QuantizedLeaf(NamedTuple):
    """int8 values + per-output-channel float scales (pytree node: jit
    treats both as ordinary traced arrays)."""

    q: Any          # int8, original shape
    scale: Any      # float32, broadcastable to the original shape


def _quantize_array(w: np.ndarray) -> QuantizedLeaf:
    import jax.numpy as jnp

    w = np.asarray(w)
    # per-output-channel (last axis) symmetric scales
    amax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)), keepdims=True)
    scale = (amax / 127.0).astype(np.float32)
    scale = np.where(scale == 0.0, 1.0, scale)
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return QuantizedLeaf(jnp.asarray(q), jnp.asarray(scale))


def quantize_tree(params, min_elems: int = 1024):
    """Quantize float leaves with >= ``min_elems`` elements and ndim >= 2
    (matmul/conv kernels — where the bytes are); small leaves (biases,
    norms) stay float for accuracy."""
    import jax

    import jax.numpy as jnp

    def maybe(leaf):
        if isinstance(leaf, QuantizedLeaf):
            # idempotent: already quantized (re-put on device — the
            # device_get above pulled the fields to host)
            return QuantizedLeaf(jnp.asarray(leaf.q),
                                 jnp.asarray(leaf.scale))
        a = np.asarray(leaf)
        if a.ndim >= 2 and a.size >= min_elems and \
                np.issubdtype(a.dtype, np.floating):
            return _quantize_array(a)
        # keep skipped leaves device-resident: host arrays here would be
        # re-uploaded on every jitted call
        return jnp.asarray(a)

    return jax.tree_util.tree_map(
        maybe, jax.device_get(params),
        is_leaf=lambda x: isinstance(x, QuantizedLeaf))


def dequantize_tree(qparams):
    """Inverse of quantize_tree — runs INSIDE jit so int8→float happens
    on-device and fuses into the consumers."""
    import jax

    def restore(leaf):
        if isinstance(leaf, QuantizedLeaf):
            return leaf.q.astype(np.float32) * leaf.scale
        return leaf

    return jax.tree_util.tree_map(restore, qparams,
                                  is_leaf=lambda x: isinstance(
                                      x, QuantizedLeaf))


def tree_nbytes(params) -> int:
    import jax

    return int(sum(np.asarray(leaf).nbytes
                   for leaf in jax.tree_util.tree_leaves(params)))
