"""Post-training int8 weight quantization for inference.

Ref capability: BigDL/zoo model quantization — "up to 2× inference
speedup, 4× model-size reduction, <0.1% accuracy drop"
(SURVEY.md §6 baseline table; the reference exposes it as
``model.quantize()`` / InferenceModel int8 paths backed by MKL int8
kernels). TPU-native version: symmetric per-output-channel weight-only
int8 — weights live in HBM as int8 (4× smaller), the dequantize
multiply fuses into the consuming matmul under jit, and on int8-capable
MXUs XLA can keep the mac in low precision. Activations stay float:
weight-only is the accuracy-safe default for the model-zoo scale.
"""

from __future__ import annotations

import os
from typing import Any, NamedTuple

import numpy as np


class QuantizedLeaf(NamedTuple):
    """int8 values + per-output-channel float scales (pytree node: jit
    treats both as ordinary traced arrays)."""

    q: Any          # int8, original shape
    scale: Any      # float32, broadcastable to the original shape


def _quantize_array(w: np.ndarray) -> QuantizedLeaf:
    import jax.numpy as jnp

    w = np.asarray(w)
    # per-output-channel (last axis) symmetric scales
    amax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)), keepdims=True)
    scale = (amax / 127.0).astype(np.float32)
    scale = np.where(scale == 0.0, 1.0, scale)
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return QuantizedLeaf(jnp.asarray(q), jnp.asarray(scale))


def quantize_tree(params, min_elems: int = 1024):
    """Quantize float leaves with >= ``min_elems`` elements and ndim >= 2
    (matmul/conv kernels — where the bytes are); small leaves (biases,
    norms) stay float for accuracy."""
    import jax

    import jax.numpy as jnp

    def maybe(leaf):
        if isinstance(leaf, QuantizedLeaf):
            # idempotent: already quantized (re-put on device — the
            # device_get above pulled the fields to host)
            return QuantizedLeaf(jnp.asarray(leaf.q),
                                 jnp.asarray(leaf.scale))
        a = np.asarray(leaf)
        if a.ndim >= 2 and a.size >= min_elems and \
                np.issubdtype(a.dtype, np.floating):
            return _quantize_array(a)
        # keep skipped leaves device-resident: host arrays here would be
        # re-uploaded on every jitted call
        return jnp.asarray(a)

    return jax.tree_util.tree_map(
        maybe, jax.device_get(params),
        is_leaf=lambda x: isinstance(x, QuantizedLeaf))


def dequantize_tree(qparams):
    """Inverse of quantize_tree — runs INSIDE jit so int8→float happens
    on-device and fuses into the consumers."""
    import jax

    def restore(leaf):
        if isinstance(leaf, QuantizedLeaf):
            return leaf.q.astype(np.float32) * leaf.scale
        return leaf

    return jax.tree_util.tree_map(restore, qparams,
                                  is_leaf=lambda x: isinstance(
                                      x, QuantizedLeaf))


def tree_nbytes(params) -> int:
    import jax

    return int(sum(np.asarray(leaf).nbytes
                   for leaf in jax.tree_util.tree_leaves(params)))


# ---------------------------------------------------------------------------
# Static int8 ACTIVATION quantization (calibrated) — the int8-MXU compute
# path on top of the storage-side weight quantization above. The reference's
# MKL int8 inference quantizes activations with calibrated ranges; here a
# flax method interceptor (nn.intercept_methods) swaps every nn.Dense
# __call__ for an int8×int8→int32 dot_general with per-tensor activation
# scale and per-output-channel weight scales — no model rewrite needed, and
# the interception happens at TRACE time so the whole int8 graph jits.
# ---------------------------------------------------------------------------

def _module_path(mod) -> str:
    return "/".join(str(p) for p in mod.path)


def calibrate_activations(apply_fn, state, batches) -> dict:
    """Run calibration batches EAGERLY, recording each nn.Dense input's
    max |x| (per-tensor symmetric range — the reference's calibration
    pass over sample data). ``batches``: iterable of model inputs
    (ndarray or tuple for multi-input)."""
    import flax.linen as nn
    import jax.numpy as jnp

    amax: dict = {}

    def observer(next_fun, args, kwargs, context):
        mod = context.module
        supported = (isinstance(mod, nn.Dense)
                     or (isinstance(mod, nn.Conv)
                         and args and hasattr(args[0], "ndim")
                         and _conv_int8_plan(mod, args[0].ndim) is not None))
        if supported and args and hasattr(args[0], "shape"):
            path = _module_path(mod)
            amax[path] = max(amax.get(path, 0.0),
                             float(jnp.max(jnp.abs(args[0]))))
        return next_fun(*args, **kwargs)

    for b in batches:
        xs = b if isinstance(b, tuple) else (b,)
        with nn.intercept_methods(observer):
            apply_fn(state, *xs)
    if not amax:
        raise ValueError(
            "calibration saw no flax nn.Dense/nn.Conv layers — activation "
            "int8 covers flax/zoo-keras models (torch-translated graphs "
            "run weight-only quantization instead)")
    return amax


def _lookup_quantized_kernel(qparams, path_parts):
    """Resolve the STORED int8 kernel (QuantizedLeaf) for a module path in
    the weight-quantized state tree, or None. The tree may nest the flax
    variables dict one level deeper depending on the loader."""
    bases, cur = [], qparams
    for _ in range(3):  # unwrap up to two "params" nesting levels
        if not isinstance(cur, dict):
            break
        bases.append(cur)
        cur = cur.get("params")
    for base in bases:
        node = base
        for part in path_parts:
            if not isinstance(node, dict) or part not in node:
                node = None
                break
            node = node[part]
        if isinstance(node, dict) and isinstance(node.get("kernel"),
                                                 QuantizedLeaf):
            return node["kernel"]
    return None


_CONV_DIMS = {1: ("NWC", "WIO", "NWC"),
              2: ("NHWC", "HWIO", "NHWC"),
              3: ("NDHWC", "DHWIO", "NDHWC")}


def _conv_tuple(v, rank, default=1):
    """Normalize a flax Conv stride/dilation attr to a rank-length tuple."""
    if v is None:
        v = default
    if isinstance(v, int):
        return (v,) * rank
    return tuple(v)


def _conv_padding(padding, rank):
    """Canonicalize a flax Conv padding attr the way flax itself does
    (flax keeps the raw user value on the module: int, pair, sequence of
    ints/pairs, or string). Returns a lax-compatible value or None for
    anything unsupported (→ caller falls back to float)."""
    if isinstance(padding, str):
        p = padding.upper()
        return p if p in ("SAME", "VALID") else None
    if isinstance(padding, int):
        return ((padding, padding),) * rank
    if isinstance(padding, (tuple, list)):
        out = []
        for e in padding:
            if isinstance(e, int):
                out.append((e, e))
            elif isinstance(e, (tuple, list)) and len(e) == 2:
                out.append(tuple(e))
            else:
                return None
        return tuple(out) if len(out) == rank else None
    return None


def _conv_int8_plan(mod, x_ndim):
    """Return (rank, lax padding) when this nn.Conv call can run int8,
    else None (exotic options — circular/causal padding, masked kernel,
    >3 spatial dims, unbatched input — run float). Shared by the
    calibration observer and the executing interceptor so a model whose
    every conv is unsupported fails calibration LOUDLY instead of
    silently running float."""
    ks = mod.kernel_size
    ks = (ks,) if isinstance(ks, int) else tuple(ks)
    rank = len(ks)
    padding = _conv_padding(mod.padding, rank)
    if (rank not in _CONV_DIMS or x_ndim != rank + 2
            or padding is None or mod.mask is not None):
        return None
    return rank, padding


def int8_interceptor(act_amax: dict, qparams=None):
    """flax method interceptor executing calibrated nn.Dense layers as
    int8×int8→int32 ``lax.dot_general`` and calibrated nn.Conv layers as
    int8 ``conv_general_dilated`` (both the MXU int8 path — convs lower
    to the systolic array the same way matmuls do), rescaled by
    act_scale · per-channel weight scale. Uncalibrated layers and other
    modules fall through to float.

    ``qparams``: the weight-quantized state tree — when the layer's kernel
    is stored as a QuantizedLeaf there, its int8 values/scales are used
    DIRECTLY (no per-call dequantize→re-quantize round trip); otherwise
    the kernel is quantized in-trace."""
    import jax
    import flax.linen as nn
    import jax.numpy as jnp

    def quantized_kernel(mod, params):
        stored = _lookup_quantized_kernel(qparams, mod.path)
        if stored is not None:
            return stored.q, jnp.reshape(stored.scale, (-1,))    # (out,)
        kernel = params["kernel"]
        # per-output-channel (last axis); no keepdims: a (1, out) scale
        # would add a rank to 1-D (e.g. vmapped) inputs' outputs
        w_amax = jnp.max(jnp.abs(kernel),
                         axis=tuple(range(kernel.ndim - 1)))
        s_w = jnp.where(w_amax == 0, 1.0, w_amax / 127.0)
        wq = jnp.clip(jnp.round(kernel / s_w), -127, 127).astype(jnp.int8)
        return wq, s_w

    def interceptor(next_fun, args, kwargs, context):
        mod = context.module
        is_dense = isinstance(mod, nn.Dense)
        is_conv = isinstance(mod, nn.Conv)
        if not (is_dense or is_conv):
            return next_fun(*args, **kwargs)
        path = _module_path(mod)
        if path not in act_amax or not args or args[0].ndim < 1:
            return next_fun(*args, **kwargs)
        x = args[0]
        if is_conv:
            plan = _conv_int8_plan(mod, x.ndim)
            if plan is None:
                return next_fun(*args, **kwargs)
            rank, padding = plan
        params = mod.variables["params"]
        s_in = jnp.float32(max(act_amax[path], 1e-8) / 127.0)
        xq = jnp.clip(jnp.round(x / s_in), -127, 127).astype(jnp.int8)
        wq, s_w = quantized_kernel(mod, params)
        if is_dense:
            y = jax.lax.dot_general(
                xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
        else:
            dn = jax.lax.conv_dimension_numbers(
                x.shape, wq.shape, _CONV_DIMS[rank])
            y = jax.lax.conv_general_dilated(
                xq, wq,
                window_strides=_conv_tuple(mod.strides, rank),
                padding=padding,
                lhs_dilation=_conv_tuple(mod.input_dilation, rank),
                rhs_dilation=_conv_tuple(mod.kernel_dilation, rank),
                dimension_numbers=dn,
                feature_group_count=mod.feature_group_count,
                preferred_element_type=jnp.int32)
        y = y.astype(jnp.float32) * (s_in * s_w)
        if mod.use_bias:
            y = y + params["bias"]
        return y.astype(x.dtype) if x.dtype != y.dtype else y

    return interceptor


# ---------------------------------------------------------------------------
# Paged-KV int8 storage: per-PAGE symmetric scales for the decode page pool
# (`ZOO_KV_DTYPE=int8`). One float32 scale per page sits alongside the pool;
# the paged kernel fuses the dequantize multiply into its inner loop and the
# host gather fallback uses the *same expression* so both paths see identical
# bits. Storage drops 4x per page vs float32 — at a fixed pool byte budget
# that multiplies the admissible concurrent-sequence count.
# ---------------------------------------------------------------------------

KV_DTYPES = ("float32", "int8")


def resolve_kv_dtype(kv_dtype=None) -> np.dtype:
    """Storage dtype for the decode KV page pool: the explicit argument
    when given, else the ``ZOO_KV_DTYPE`` env knob (``float32`` default;
    ``int8`` stores pages quantized under per-page symmetric scales)."""
    if kv_dtype is None:
        kv_dtype = os.environ.get("ZOO_KV_DTYPE", "").strip().lower() \
            or "float32"
    if isinstance(kv_dtype, str):
        kv_dtype = {"fp32": "float32", "f32": "float32"}.get(
            kv_dtype, kv_dtype)
    dt = np.dtype(kv_dtype)
    if dt not in (np.dtype(np.float32), np.dtype(np.int8)):
        raise ValueError(
            f"ZOO_KV_DTYPE must be one of {KV_DTYPES}, got {kv_dtype!r}")
    return dt


def page_scale(amax: float) -> np.float32:
    """Symmetric per-page scale for a page whose running max |x| is
    ``amax`` (zero-amax pages get scale 1.0 so all-zero pages stay exact)."""
    return np.float32(amax / 127.0) if amax > 0.0 else np.float32(1.0)


def quantize_rows(rows, scale) -> np.ndarray:
    """Float rows → int8 under one shared (per-page) scale."""
    return np.clip(np.round(np.asarray(rows, np.float32)
                            / np.float32(scale)),
                   -127, 127).astype(np.int8)


def dequantize_rows(q, scale) -> np.ndarray:
    """int8 rows → float32 as ``q * scale`` — the exact expression the
    paged kernel fuses into its inner loop, so the host gather fallback
    and the kernel dequant are bitwise identical."""
    return np.asarray(q).astype(np.float32) * np.float32(scale)


def requantize_rows(q, old_scale, new_scale) -> np.ndarray:
    """Rescale already-quantized rows after a later append raised the
    page's amax (so its scale grew). The round-trip costs at most half an
    ulp of the FINAL scale — bounded by the page's eventual amax/254."""
    return quantize_rows(dequantize_rows(q, old_scale), new_scale)


def int8_apply(apply_fn, act_amax: dict):
    """Wrap an ``apply_fn(state, *xs)`` so every calibrated Dense/Conv runs
    int8 (jit-compatible: interception happens while tracing). The
    call-time state feeds the interceptor so stored int8 kernels are
    consumed directly."""
    import flax.linen as nn

    def wrapped(state, *xs):
        qparams = state if isinstance(state, dict) else None
        with nn.intercept_methods(int8_interceptor(act_amax, qparams)):
            return apply_fn(state, *xs)

    return wrapped
