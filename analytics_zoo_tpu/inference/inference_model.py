"""InferenceModel — thread-safe, high-concurrency model inference.

TPU-native analog of the reference's inference engine
(zoo/.../pipeline/inference/InferenceModel.scala:28-62 and
AbstractInferenceModel.java): where the reference keeps a
``LinkedBlockingQueue`` of ``concurrentNum`` deep-copied model instances so
multiple request threads can each take a private copy, here device weights
are immutable jax arrays shared by all callers, and the "copies" become one
**compiled-executable cache** keyed by input shape (an XLA executable is
reusable concurrently; recompiles only happen per new shape bucket). A
semaphore still bounds in-flight predicts at ``concurrent_num`` to provide
the same backpressure semantics as the reference's blocking queue.

Loader parity (ref InferenceModel.scala doLoadBigDL:96 / doLoadTensorflow:121
/ doLoadPyTorch:249 / doLoadOpenVINO:282 — all foreign-runtime loads):

- ``load_zoo(model)`` / ``load(path)``      — zoo keras/ZooModel (≈ doLoadBigDL)
- ``load_flax(module, sample_input, ...)``  — any flax.linen module
- ``load_torch(torch_module, sample_input)``— torch nn.Module converted to a
  jax forward (≈ doLoadPyTorch; see net/torch_net.py)
- ``load_checkpoint(path)``                 — weights from an Estimator
  checkpoint directory into the current model

Batching: predict pads the tail batch up to the bucket size and masks it
off, so every request shape hits one of a small set of executables (the
reference instead re-runs the graph at the raw batch,
TFNet.scala:179-265 — fine for CPU, recompile-per-shape on XLA).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.common import compile_ahead, resilience, telemetry


def _as_tuple(x):
    return tuple(x) if isinstance(x, (list, tuple)) else (x,)


def _warm_many_async(todo):
    """Daemon thread warming ``(cache, avals)`` pairs — warm_decode's
    grid may span the plain and the paged executable caches. Smallest
    first, same as ``ExecutableCache.warm_async``; a failed build must
    not kill the thread (the shape just compiles in-band later)."""
    def size(item):
        _, avals = item
        return int(np.prod(avals[1].shape)) if len(avals) > 1 else 0

    def work():
        for cache, avals in sorted(todo, key=size):
            try:
                cache.warm(*avals)
            except Exception:
                pass

    t = threading.Thread(target=work, name="zoo-warm-decode", daemon=True)
    t.start()
    return t


class InferenceModel:
    """Thread-safe inference holder with a jitted-executable cache."""

    def __init__(self, concurrent_num: int = 1):
        self.concurrent_num = int(concurrent_num)
        self._sem = threading.Semaphore(self.concurrent_num)
        self._lock = threading.Lock()
        self._apply = None          # (params, *inputs) -> outputs
        self._params = None
        self._jitted = None
        self._n_inputs = 1
        # set by quantize(mode="int8"): {dense path: calibrated |x|max}
        self._act_ranges = None
        # compile-ahead state: the batch-bucket ladder predict chunks
        # against, the per-sample input spec warmup builds avals from
        # (captured at load when a sample_input is given, else observed on
        # the first dispatch), the AOT executable cache dispatches run
        # through, and the live warmup threads wait_warm() joins
        self._ladder: Optional[compile_ahead.BucketLadder] = None
        self._sample_spec = None    # ((sample_shape, dtype), ...) per input
        self._exec_cache: Optional[compile_ahead.ExecutableCache] = None
        self._warm_threads: list = []
        # set by shard(): the mesh executable the dispatch seam rides —
        # params partitioned per strategy, avals carrying shardings
        self._sharded = None
        # paged decode seam (built lazily by paged_decode_step_fn): the
        # forward with ops/paged_attention.paged_gather fused under it
        self._paged_jitted = None
        self._paged_cache: Optional[compile_ahead.ExecutableCache] = None

    # ------------------------------------------------------------- loaders
    def load_zoo(self, model) -> "InferenceModel":
        """Load a zoo keras model (KerasNet) or ZooModel instance
        (ref doLoadBigDL, InferenceModel.scala:96)."""
        from analytics_zoo_tpu.keras.models import KerasNet

        import jax
        import jax.numpy as jnp

        net = model.model if hasattr(model, "model") and isinstance(
            getattr(model, "model"), KerasNet) else model
        est = net.estimator
        est._init_state()
        adapter = est.adapter
        # Deep-copy onto fresh device buffers: the estimator's train step
        # donates its state (donate_argnums=0), so aliasing est._state here
        # would leave this model pointing at invalidated TPU buffers after a
        # subsequent est.fit().
        state = jax.tree_util.tree_map(
            jnp.array,
            {"params": est._state["params"],
             "model_state": est._state["model_state"]})

        def apply_fn(state, *xs):
            out, _ = adapter.apply(state["params"], state["model_state"],
                                   xs if len(xs) > 1 else xs[0], False, None)
            return out

        self._install(apply_fn, state, adapter.n_inputs)
        return self

    def load(self, path: str) -> "InferenceModel":
        """Load a saved ZooModel directory (ref doLoadBigDL from file)."""
        from analytics_zoo_tpu.models.common import ZooModel
        return self.load_zoo(ZooModel.load_model(path))

    def load_flax(self, module, sample_input, params=None,
                  rng_seed: int = 0) -> "InferenceModel":
        """Load any flax.linen module; ``sample_input`` initialises params
        when none are given."""
        import jax

        args = _as_tuple(sample_input)
        if params is None:
            params = module.init(jax.random.PRNGKey(rng_seed), *args)

        def apply_fn(state, *xs):
            return module.apply(state["params"], *xs)

        self._install(apply_fn, {"params": params}, len(args))
        self._remember_spec(args, overwrite=True)
        return self

    def load_openvino(self, model_path: str, weight_path: str,
                      batch_size: int = 0) -> "InferenceModel":
        """Load an OpenVINO IR model (ref
        pyzoo/zoo/pipeline/inference/inference_model.py:69 load_openvino
        → native OpenVINO engine; here the IR is parsed and translated to
        a jitted jax function, net/openvino_net.py, so the same published
        artifacts serve on TPU). ``batch_size`` is accepted for API parity
        (batching is dynamic here)."""
        from analytics_zoo_tpu.net.openvino_net import OpenVINONet

        net = OpenVINONet(model_path, weight_path, jit=False)

        def apply_fn(state, *xs):
            return net.apply_fn({"params": state["params"]}, *xs)

        self._install(apply_fn, {"params": net.variables["params"]},
                      net.n_inputs)
        return self

    def load_torch(self, torch_module, sample_input) -> "InferenceModel":
        """Convert a torch nn.Module into a jax forward and load it
        (ref doLoadPyTorch, InferenceModel.scala:249 — there the module runs
        inside an embedded CPython; here it is *translated* so inference runs
        on the TPU)."""
        from analytics_zoo_tpu.net.torch_net import torch_to_jax

        apply_fn, variables = torch_to_jax(torch_module)
        n = len(_as_tuple(sample_input))

        def wrapped(state, *xs):
            return apply_fn({"params": state["params"],
                             "buffers": state["model_state"]}, *xs)

        self._install(wrapped, {"params": variables["params"],
                                "model_state": variables["buffers"]}, n)
        self._remember_spec(_as_tuple(sample_input), overwrite=True)
        return self

    def load_checkpoint(self, path: str) -> "InferenceModel":
        """Restore weights saved by ``Estimator.save``/checkpointing into
        the currently-loaded model (ref doLoadBigDL weight path)."""
        from analytics_zoo_tpu.learn import checkpoint as ckpt_lib
        import jax

        if self._params is None:
            raise RuntimeError("load a model before load_checkpoint")
        found = ckpt_lib.find_latest_checkpoint(path)
        target = path if found is None else found[0]
        host = jax.device_get(self._params)
        # Estimator checkpoints store {step, params, opt_state, model_state};
        # restore against a matching skeleton then keep only what we hold.
        skeleton = {"step": np.zeros((), np.int32),
                    "params": host.get("params"),
                    "opt_state": None,
                    "model_state": host.get("model_state", {})}
        try:
            state, _ = ckpt_lib.load_checkpoint(target, skeleton)
            new = {"params": state["params"]}
            if "model_state" in host:
                new["model_state"] = state.get("model_state",
                                               host["model_state"])
        except Exception:
            state, _ = ckpt_lib.load_checkpoint(target, host)
            new = state
        with self._lock:
            # executables key on shapes, not values — no re-jit needed
            self._params = new
        return self

    def quantize(self, min_elems: int = 1024, mode: str = "weight",
                 calibration_data=None) -> "InferenceModel":
        """Post-training int8 quantization (ref BigDL ``model.quantize()``
        int8 inference — SURVEY §6: "2× speedup, 4× model-size reduction").

        ``mode="weight"`` (default): matmul/conv kernels stored int8 with
        per-channel scales; dequantization runs inside the jitted forward
        so weights stay int8 in HBM (4× smaller).

        ``mode="int8"``: ALSO quantizes activations — a calibration pass
        over ``calibration_data`` (ndarray / tuple, or list of batches)
        records per-Dense input ranges (the reference's MKL int8
        calibration), then every calibrated ``nn.Dense`` executes as an
        int8×int8→int32 ``dot_general`` — the MXU's int8 path. Covers
        flax/zoo-keras models; composes with the weight storage
        quantization (applied first)."""
        from analytics_zoo_tpu.inference.quantize import (
            calibrate_activations, dequantize_tree, int8_apply,
            quantize_tree,
        )

        if mode not in ("weight", "int8"):
            raise ValueError(f"mode must be 'weight' or 'int8', got {mode!r}")
        with self._lock:
            if self._apply is None:
                raise RuntimeError("load a model before quantize")
            orig_apply = self._apply
            qstate = quantize_tree(self._params, min_elems=min_elems)

        def q_apply(state, *xs):
            return orig_apply(dequantize_tree(state), *xs)

        if mode == "int8":
            if calibration_data is None:
                raise ValueError(
                    "mode='int8' needs calibration_data (a batch or list "
                    "of batches) for the activation-range pass")
            batches = calibration_data \
                if isinstance(calibration_data, list) else [calibration_data]
            if not batches:
                raise ValueError(
                    "mode='int8': calibration_data is empty — pass at "
                    "least one batch to calibrate activation ranges")
            act_amax = calibrate_activations(q_apply, qstate, batches)
            # introspection: per-layer calibrated |x|max ranges
            self._act_ranges = act_amax
            self._install(int8_apply(q_apply, act_amax), qstate,
                          self._n_inputs)
            return self

        self._install(q_apply, qstate, self._n_inputs)
        return self

    def _install(self, apply_fn, params, n_inputs):
        with self._lock:
            self._apply = apply_fn
            self._params = params
            self._n_inputs = n_inputs
            # recompile accounting: every new shape bucket shows up in
            # zoo_jit_cache_misses_total{fn="inference_model"}
            self._jitted = telemetry.instrument_jit(
                apply_fn, name="inference_model")
            # warm dispatches bypass jit entirely through the AOT
            # executable cache; a re-install (load_*, quantize) drops the
            # old executables — the new forward needs new ones
            self._exec_cache = compile_ahead.ExecutableCache(
                self._jitted, name="inference_model")
            # a re-install also invalidates any mesh layout: the new
            # forward must be re-sharded explicitly
            self._sharded = None
            # and the paged decode seam: it closes over the old forward
            self._paged_jitted = None
            self._paged_cache = None

    def shard(self, strategy, param_rules=None, mesh=None,
              devices=None) -> "InferenceModel":
        """Repartition the loaded model onto a device mesh: parameters
        placed per the :class:`~analytics_zoo_tpu.parallel.strategy.
        ShardingStrategy` (e.g. ``"tp8"``, ``"fsdp"``, ``"dp2,tp4"``)
        and every subsequent predict/warm dispatch runs the mesh
        executable. The serving seam above (bucket ladder, assembly
        loop, warmup) is unchanged — executables key on batch
        shape/dtype, and warmup walks the ladder with sharded avals so
        bucket growth stays a stall-free swap."""
        from analytics_zoo_tpu.parallel.sharded_executable import (
            ShardedExecutable,
        )

        with self._lock:
            if self._apply is None:
                raise RuntimeError("load a model before shard")
            apply_fn, params = self._apply, self._params
        se = ShardedExecutable(apply_fn, params, strategy,
                               param_rules=param_rules, mesh=mesh,
                               devices=devices, name="inference_model")
        with self._lock:
            self._params = se.params
            self._jitted = se._jitted
            self._exec_cache = se.cache
            self._sharded = se
        return self

    def shard_info(self) -> Optional[Dict[str, Any]]:
        """Per-shard HBM accounting for the mesh executable (None when
        unsharded) — the `/healthz` payload proving no single device
        holds the full model."""
        with self._lock:
            se = self._sharded
        if se is None:
            return None
        hbm = se.shard_hbm_bytes()
        return {"strategy": str(se.strategy), "n_shards": se.n_shards,
                "total_param_bytes": se.total_param_bytes(),
                "shard_hbm_bytes": hbm}

    # ------------------------------------------------------ compile-ahead
    def _remember_spec(self, xs, overwrite: bool = False):
        """Record the per-sample (shape, dtype) of every input — what
        ``warm_up`` builds batched avals from. Loaders with a
        ``sample_input`` overwrite (authoritative); observed dispatch
        shapes only fill an empty spec."""
        try:
            spec = tuple((tuple(a.shape[1:]), np.dtype(a.dtype))
                         for a in xs)
        except Exception:
            return
        with self._lock:
            if overwrite or self._sample_spec is None:
                self._sample_spec = spec

    def has_warm_spec(self) -> bool:
        """True once the input spec needed for AOT warmup is known."""
        with self._lock:
            return self._sample_spec is not None

    def set_ladder(self, ladder, max_batch_size: Optional[int] = None
                   ) -> "InferenceModel":
        """Attach a batch-bucket ladder: ``predict`` pads each tail chunk
        to the nearest rung (instead of the full batch bucket) so tails
        reuse smaller pre-built executables. Pass a
        :class:`~analytics_zoo_tpu.common.compile_ahead.BucketLadder` or
        ``(min_batch_size, max_batch_size)`` ints."""
        if not isinstance(ladder, compile_ahead.BucketLadder):
            ladder = compile_ahead.BucketLadder(int(ladder), max_batch_size)
        with self._lock:
            self._ladder = ladder
        return self

    def _aot_avals(self, params, spec, rung):
        import jax

        with self._lock:
            sharded = self._sharded

        def aval(a):
            # carry the leaf's sharding: an AOT build lowered without it
            # compiles a different executable than the live dispatch
            # needs, so the "warm" rung silently recompiles on first use
            sh = getattr(a, "sharding", None)
            if sh is not None:
                try:
                    return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype,
                                                sharding=sh)
                except TypeError:       # older jax: no sharding kwarg
                    pass
            if hasattr(a, "shape") and hasattr(a, "dtype"):
                return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
            arr = np.asarray(a)
            return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

        p_avals = jax.tree_util.tree_map(aval, params)
        if sharded is not None:
            return (p_avals,) + sharded.batch_avals(spec, rung)
        return (p_avals,) + compile_ahead.batch_avals(spec, rung)

    def warm_up(self, rungs=None, sample_input=None, block: bool = False):
        """AOT-compile executables for the given batch ``rungs`` (default:
        the attached ladder's) on a background daemon thread — the serving
        engine calls this off the serve thread so bucket growth becomes a
        stall-free swap. ``sample_input`` records the input spec when the
        loader didn't capture one. ``block=True`` compiles synchronously.
        Returns the warmup thread (None when there is nothing to warm or
        no spec yet); ``wait_warm`` joins all outstanding ones."""
        if sample_input is not None:
            self._remember_spec(
                tuple(np.asarray(a) for a in _as_tuple(sample_input)),
                overwrite=True)
        with self._lock:
            spec, cache = self._sample_spec, self._exec_cache
            params, ladder = self._params, self._ladder
        if cache is None or spec is None:
            return None
        if rungs is None:
            rungs = ladder.rungs if ladder is not None else ()
        # ZOO_CPU_FALLBACK=1: each rung also gets a CPU executable so a
        # wedged backend fails over to already-compiled code (ISSUE 7)
        want_cpu = resilience.cpu_fallback_enabled()
        todo = []
        for rung in sorted({int(r) for r in rungs}):
            avals = self._aot_avals(params, spec, rung)
            if not cache.ready(*avals) or \
                    (want_cpu and not cache.cpu_ready(*avals)):
                todo.append(avals)
        if not todo:
            return None
        if block:
            for avals in todo:
                cache.warm(*avals)
                if want_cpu:
                    cache.warm_cpu(*avals)
            return None
        t = cache.warm_async(todo, cpu_also=want_cpu)
        with self._lock:
            self._warm_threads = [w for w in self._warm_threads
                                  if w.is_alive()] + [t]
        return t

    def wait_warm(self, timeout: Optional[float] = None
                  ) -> "InferenceModel":
        """Join every outstanding warmup thread (best effort under
        ``timeout`` seconds total)."""
        with self._lock:
            threads = list(self._warm_threads)
        deadline = None if timeout is None else \
            time.monotonic() + float(timeout)
        for t in threads:
            t.join(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))
        return self

    def rung_ready(self, rung: int) -> bool:
        """True when an AOT executable exists for batch size ``rung`` —
        the serving engine's gate for stall-free bucket growth. Unknown
        spec reads as not-ready (growing would compile in-band)."""
        with self._lock:
            spec, cache, params = \
                self._sample_spec, self._exec_cache, self._params
        if cache is None or spec is None:
            return False
        try:
            return cache.ready(*self._aot_avals(params, spec, rung))
        except Exception:
            return False

    # ------------------------------------------------------------ generate
    def warm_decode(self, max_seq_len: int, rungs=None, seq_rungs=None,
                    block: bool = False, verify_k: int = 0,
                    paged_pool=None):
        """AOT-compile the decode grid: every (batch rung × seq-length
        rung) shape a ``generate`` up to ``max_seq_len`` can present, so
        the decode loop never recompiles — the KV cache's rung growth is
        a swap onto an already-built executable. Needs a 2-input
        (encoder, decoder) spec; the decoder's time axis is rewritten per
        seq rung. ``verify_k > 0`` extends the grid top so the
        speculative k-wide verify step (live length + k drafts + bonus)
        lands on a warmed rung too; chunked prefill needs no extra shapes
        — prefill positions fill the same rung buffers the decode steps
        run. ``paged_pool=(n_pages, page_size)`` additionally warms the
        PAGED step executables on the same grid (pool dtype from
        ``ZOO_KV_DTYPE``), so the scheduler's first paged dispatch hits a
        built shape. Returns the warmup thread (None when nothing to
        do)."""
        from analytics_zoo_tpu.inference import generation

        with self._lock:
            spec, cache = self._sample_spec, self._exec_cache
            params, ladder = self._params, self._ladder
        if cache is None or spec is None or len(spec) < 2:
            return None
        if seq_rungs is None:
            seq_rungs = generation.seq_ladder(
                int(max_seq_len) + max(0, int(verify_k))).rungs
        if rungs is None:
            rungs = ladder.rungs if ladder is not None else ()
        todo = [(cache, avals)
                for avals in compile_ahead.decode_grid_specs(
                    spec, rungs, seq_rungs,
                    lambda dspec, rung: self._aot_avals(
                        params, dspec, rung))
                if not cache.ready(*avals)]
        if paged_pool is not None:
            for pcache, avals in self._paged_decode_avals(
                    paged_pool, spec, params, rungs, seq_rungs):
                todo.append((pcache, avals))
        if not todo:
            return None
        if block:
            for c, avals in todo:
                c.warm(*avals)
            return None
        t = _warm_many_async(todo)
        with self._lock:
            self._warm_threads = [w for w in self._warm_threads
                                  if w.is_alive()] + [t]
        return t

    def _paged_decode_avals(self, paged_pool, spec, params, rungs,
                            seq_rungs):
        """Yield (cache, avals) for every unbuilt PAGED step executable
        on the (batch rung × seq rung) grid. The paged seam materializes
        the decoder at ``width * page_size`` positions, so distinct seq
        rungs sharing a page width share one executable."""
        import jax
        from analytics_zoo_tpu.inference import quantize

        n_pages, page_size = (int(v) for v in paged_pool)
        self._ensure_paged()
        with self._lock:
            pcache = self._paged_cache
        if pcache is None:
            return
        kv_dtype = quantize.resolve_kv_dtype(None)
        dim = int(spec[-1][0][-1])
        pool_aval = jax.ShapeDtypeStruct((n_pages, page_size, dim),
                                         kv_dtype)
        scales_aval = jax.ShapeDtypeStruct((n_pages,), np.float32)
        seen = set()
        for rung in sorted({int(r) for r in rungs}):
            for sr in sorted({int(s) for s in seq_rungs}):
                width = -(-sr // page_size)
                if (rung, width) in seen:
                    continue
                seen.add((rung, width))
                avals = self._aot_avals(params, spec[:1], rung) + (
                    pool_aval, scales_aval,
                    jax.ShapeDtypeStruct((rung, width), np.int32),
                    jax.ShapeDtypeStruct((rung,), np.int32))
                if not pcache.ready(*avals):
                    yield pcache, avals

    def decode_step_fn(self):
        """The scheduler-facing step seam: one wide ``(enc, dec) -> out``
        dispatch through the AOT executables (async submit + traced
        fetch). A :class:`~analytics_zoo_tpu.inference.decode_scheduler.
        DecodeScheduler` built on this callable runs every step on the
        same (batch rung × seq rung) grid ``warm_decode`` compiled."""
        with self._lock:
            if self._apply is None:
                raise RuntimeError("load a model before decode_step_fn")
            if self._n_inputs != 2:
                raise ValueError(
                    "decode needs a 2-input (encoder, decoder) model, "
                    f"got {self._n_inputs} inputs")

        def step(enc, dec):
            return np.asarray(self.predict_fetch(
                self.predict_async((enc, dec))))

        return step

    def _ensure_paged(self):
        """Build the paged decode dispatch seam once per installed
        forward: ``paged_apply(state, enc, pool, scales, table, lengths)``
        runs ``ops/paged_attention.paged_gather`` INSIDE the jitted step
        — the per-page host copy of ``gather_into`` becomes an on-device
        gather driven by the scalar-prefetched page table — then feeds
        the gathered buffer to the original forward. Because that buffer
        is bitwise the host-gathered one, outputs match the plain seam
        bit for bit."""
        with self._lock:
            if self._paged_cache is not None:
                return
            orig_apply = self._apply

        def paged_apply(state, enc, pool, scales, table, lengths):
            from analytics_zoo_tpu.ops import paged_attention
            # pinned dispatch, decision by verdict lookup only: this
            # traces under whoever owns the jit (serve loop / warmup
            # thread, possibly holding the model lock), so the path must
            # never reach a tuner measurement
            dec = paged_attention.paged_gather_pinned(
                pool, table, lengths, scales=scales,
                use_kernel=paged_attention.gather_decision(pool, table))
            return orig_apply(state, enc, dec)

        jitted = telemetry.instrument_jit(
            paged_apply, name="inference_model_paged")
        cache = compile_ahead.ExecutableCache(
            jitted, name="inference_model_paged")
        with self._lock:
            if self._paged_cache is None and self._apply is orig_apply:
                self._paged_jitted = jitted
                self._paged_cache = cache

    def paged_decode_step_fn(self):
        """Paged counterpart of :meth:`decode_step_fn`: one wide
        ``(enc, pool, scales, table, lengths) -> out`` dispatch where the
        per-sequence page gather runs inside the jitted forward. The
        decoder buffer materializes at ``table_width * page_size``
        positions — the seq rung rounded up to a page multiple — which is
        output-invisible for live positions (the causal rung-padding
        parity generation.py pins). int8 pools ship with their per-page
        scales; float pools pass all-ones (``x * 1.0`` is bitwise
        ``x``)."""
        with self._lock:
            if self._apply is None:
                raise RuntimeError(
                    "load a model before paged_decode_step_fn")
            if self._n_inputs != 2:
                raise ValueError(
                    "decode needs a 2-input (encoder, decoder) model, "
                    f"got {self._n_inputs} inputs")
        self._ensure_paged()

        def step(enc, pool, scales, table, lengths):
            self._ensure_paged()   # rebuilt lazily after a re-install
            with self._lock:
                params, cache = self._params, self._paged_cache
            pending = cache(params, np.asarray(enc),
                            np.ascontiguousarray(pool),
                            np.asarray(scales, np.float32),
                            np.asarray(table, np.int32),
                            np.asarray(lengths, np.int32))
            return np.asarray(telemetry.traced_device_get(pending))

        return step

    def generate(self, input_seq, start_sign, max_new_tokens: int = 16, *,
                 mode: str = "greedy", temperature: float = 1.0,
                 seed: Optional[int] = None, ladder=None,
                 trace_ids: Sequence[str] = (), draft=None,
                 spec_k: int = 4) -> np.ndarray:
        """Autoregressive generation through the AOT dispatch seam:
        sharded prefill + decode over the bucketed KV rungs, every step
        running the (batch rung × seq rung) executables ``warm_decode``
        built — never a per-request recompile. The loaded model must be a
        2-input encoder/decoder (e.g. the seq2seq zoo via ``load_zoo``).

        ``draft`` (another InferenceModel, or a bare ``(enc, dec)``
        callable) switches to speculative decoding through the step
        scheduler: the draft proposes ``spec_k`` tokens per step and this
        model verifies them in one wide step — greedy output stays
        bitwise identical to plain decode; without ``draft`` the classic
        step-by-step loop runs unchanged. Each row keeps a private rng
        stream under ``draft`` (seeded ``seed + row``), whereas the plain
        loop draws one batch-wide stream. Returns the generated
        ``[batch, max_new_tokens, output_dim]`` sequence."""
        from analytics_zoo_tpu.inference import generation

        with self._lock:
            if self._apply is None:
                raise RuntimeError("load a model before generate")
            if self._n_inputs != 2:
                raise ValueError(
                    "generate needs a 2-input (encoder, decoder) model, "
                    f"got {self._n_inputs} inputs")
        if draft is not None:
            from analytics_zoo_tpu.inference import decode_scheduler

            draft_fn = (draft.decode_step_fn()
                        if hasattr(draft, "decode_step_fn") else draft)
            input_seq = np.asarray(input_seq)
            start = np.asarray(start_sign, np.float32)
            sched = decode_scheduler.DecodeScheduler(
                self.decode_step_fn(),
                max_batch=max(1, int(input_seq.shape[0])),
                max_seq=int(max_new_tokens) + 1,
                draft_fn=draft_fn, spec_k=spec_k)
            seqs = [sched.admit(
                        input_seq[i], start[i], max_new_tokens,
                        mode=mode, temperature=temperature,
                        seed=None if seed is None else int(seed) + i,
                        tag=i,
                        trace_uri=(trace_ids[i]
                                   if i < len(trace_ids) else None))
                    for i in range(input_seq.shape[0])]
            sched.drain()
            return np.stack([s.result for s in seqs])
        if ladder is None:
            ladder = generation.seq_ladder(int(max_new_tokens) + 1)
        return generation.decode_loop(
            self.decode_step_fn(), input_seq, start_sign,
            max_new_tokens, ladder=ladder, mode=mode,
            temperature=temperature, seed=seed, trace_ids=trace_ids)

    # ------------------------------------------------------------- predict
    def _snapshot(self):
        with self._lock:
            # one consistent snapshot: a concurrent load_* or
            # load_checkpoint can't mix model versions across chunks
            if self._apply is None:
                raise RuntimeError("no model loaded")
            return (self._params, self._jitted, self._n_inputs,
                    self._exec_cache, self._ladder)

    @staticmethod
    def _coerce(x, n_inputs) -> Tuple[np.ndarray, ...]:
        xs = _as_tuple(x)
        if len(xs) != n_inputs:
            if n_inputs == 1:
                xs = (np.asarray(x),)
            else:
                raise ValueError(
                    f"model takes {n_inputs} inputs, got {len(xs)}")
        return tuple(np.asarray(a) for a in xs)

    def _chunks(self, x, n_inputs, batch_size, ladder=None):
        """Split one logical batch into compile-bucket chunks, padding the
        tail so every shape hits an already-built executable: yields
        ``(chunk_tuple, n_valid)``. With a bucket ladder attached, the
        tail pads to its **nearest rung** instead of the full bucket —
        less pad waste, and the rung's executable is already warm."""
        xs = self._coerce(x, n_inputs)
        self._remember_spec(xs)
        n = xs[0].shape[0]
        if n == 0:
            raise ValueError("predict called on an empty batch")
        bs = int(batch_size) if batch_size else \
            (ladder.rung_for(n) if ladder is not None else n)
        for lo in range(0, n, bs):
            hi = min(lo + bs, n)
            chunk = tuple(a[lo:hi] for a in xs)
            valid = hi - lo
            rung = bs if ladder is None else \
                min(bs, ladder.rung_for(valid))
            yield compile_ahead.pad_to_rung(chunk, rung,
                                            site="inference"), valid

    def predict(self, x, batch_size: Optional[int] = None,
                pipeline_window: int = 2) -> np.ndarray:
        """Batch predict. ``x``: ndarray, tuple of ndarrays (multi-input),
        or an iterator/generator of such batches — a stream is consumed
        incrementally, one window's worth at a time, instead of being
        materialized up front.

        Chunks flow through a bounded in-flight dispatch window
        (``pipeline_window`` batches deep, common/pipeline_io.py): chunk
        N+1 is sliced/padded and dispatched while chunk N computes, and
        results are fetched only as the window retires them — never inline
        with a dispatch. ``pipeline_window=1`` reproduces the synchronous
        cadence. Outputs are bit-identical either way (same executables,
        same inputs; only the fetch schedule changes).

        Thread-safe; at most ``concurrent_num`` predicts run concurrently
        (ref InferenceModel.doPredict + model-queue take/offer)."""
        import jax
        from analytics_zoo_tpu.common.pipeline_io import DevicePipeline

        params, jitted, n_inputs, cache, ladder = self._snapshot()
        # warm rungs dispatch straight through the AOT executable cache —
        # the jit call path (and its recompile counter) is only the
        # fallback for shapes the cache cannot handle
        run = cache if cache is not None else \
            (lambda p, *c: jitted(p, *c))

        def chunks():
            if hasattr(x, "__next__"):       # stream of batches
                for b in x:
                    yield from self._chunks(b, n_inputs, batch_size,
                                            ladder)
            else:
                yield from self._chunks(x, n_inputs, batch_size, ladder)

        outs = []

        def take(comp):
            if comp.error is not None:
                raise comp.error
            outs.append(jax.tree_util.tree_map(
                lambda a: a[:comp.ctx], comp.result))

        with self._sem:
            pipe = DevicePipeline(lambda c: run(params, *c),
                                  window=max(1, int(pipeline_window)),
                                  trace_id="inference_predict")
            with pipe:
                for chunk, valid in chunks():
                    for comp in pipe.submit(chunk, ctx=valid):
                        take(comp)
                for comp in pipe.drain():
                    take(comp)
        if not outs:
            raise ValueError("predict called on an empty batch")
        leaves = [jax.tree_util.tree_leaves(o) for o in outs]
        treedef = jax.tree_util.tree_structure(outs[0])
        return jax.tree_util.tree_unflatten(
            treedef,
            [np.concatenate([l[i] for l in leaves])
             for i in range(len(leaves[0]))])

    def predict_async(self, x):
        """Dispatch ONE already-batched input (ndarray or multi-input
        tuple) without blocking — the serving engine's staged-dispatch
        hook. Returns an opaque pending value; pass it to
        ``predict_fetch`` for the host result. The caller owns batching
        and padding (the engine pads to its own bucket) and bounds
        in-flight work through its DevicePipeline window, so the
        ``concurrent_num`` semaphore is not taken here."""
        params, jitted, n_inputs, cache, _ = self._snapshot()
        xs = self._coerce(x, n_inputs)
        self._remember_spec(xs)
        if cache is not None:
            return cache(params, *xs)
        return jitted(params, *xs)

    def predict_fetch(self, pending):
        """Blocking host side of ``predict_async``."""
        return telemetry.traced_device_get(pending)

    def predict_cpu(self, x):
        """Synchronously predict ONE already-batched input on the host
        CPU device — the serving engine's failover dispatch while the
        accelerator backend is wedged. Goes through the executable
        cache's CPU rung (pre-built during warmup under
        ``ZOO_CPU_FALLBACK=1``) and deliberately bypasses the accelerator
        dispatch path — and its fault-injection seam — entirely."""
        import jax

        params, jitted, n_inputs, cache, _ = self._snapshot()
        xs = self._coerce(x, n_inputs)
        self._remember_spec(xs)
        if cache is not None:
            return jax.device_get(cache.cpu_call(params, *xs))
        with jax.default_device(jax.devices("cpu")[0]):
            return jax.device_get(jitted(params, *xs))

    def predict_classes(self, x, batch_size: Optional[int] = None,
                        zero_based_label: bool = True) -> np.ndarray:
        probs = np.asarray(self.predict(x, batch_size))
        classes = np.argmax(probs, axis=-1)
        return classes if zero_based_label else classes + 1

    # java-flavoured aliases (ref AbstractInferenceModel.java)
    do_predict = predict
    do_load = load
