from analytics_zoo_tpu.nnframes.nn_classifier import (
    NNClassifier, NNClassifierModel, NNEstimator, NNImageReader, NNModel,
)

__all__ = ["NNEstimator", "NNModel", "NNClassifier", "NNClassifierModel",
           "NNImageReader"]
