"""NNFrames — Estimator/Transformer ML-pipeline integration over DataFrames.

Parity with the reference's Spark-ML integration
(zoo/.../pipeline/nnframes/NNEstimator.scala:202 ``NNEstimator.fit(df) →
NNModel``, ``NNModel:679`` transform adds a prediction column,
``NNClassifier.scala`` argmax variant, ``NNImageReader.scala:182`` reads an
image directory into a DataFrame; python mirror
pyzoo/zoo/pipeline/nnframes/nn_classifier.py:714). The reference rides
Spark DataFrames + Row preprocessing chains; here the frame is a pandas
DataFrame (the single-host view of the sharded data layer) and the
training/inference engine is the pjit Estimator — the pipeline-stage
contract (set params → fit → model.transform) is preserved so sklearn-style
pipelines compose.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.learn.estimator import Estimator, JaxEstimator


def _df_to_xy(df, feature_cols, label_cols=None,
              feature_preprocessing=None):
    """DataFrame columns → (x, y) ndarrays. Array-valued cells (lists /
    ndarrays, e.g. an image column) are stacked; scalar columns are
    column-stacked into one feature matrix (the reference's
    SeqToTensor/ArrayToTensor preprocessing analog)."""
    def col_to_array(col):
        vals = df[col].tolist()
        first = vals[0]
        if isinstance(first, (list, tuple, np.ndarray)):
            return np.stack([np.asarray(v, np.float32) for v in vals])
        return np.asarray(vals, np.float32)

    feats = [col_to_array(c) for c in feature_cols]
    if len(feats) == 1:
        x = feats[0]
    elif all(f.ndim == 1 for f in feats):
        x = np.column_stack(feats)
    else:
        x = tuple(feats)
    if feature_preprocessing is not None:
        x = feature_preprocessing(x)
    if label_cols is None:
        return x, None
    labels = [col_to_array(c) for c in label_cols]
    y = labels[0] if len(labels) == 1 else np.column_stack(labels)
    return x, y


class NNModel:
    """Fitted transformer: ``transform(df)`` appends a prediction column
    (ref NNModel.scala:679 / python NNModel)."""

    def __init__(self, estimator: JaxEstimator,
                 feature_cols: Sequence[str] = ("features",),
                 prediction_col: str = "prediction",
                 feature_preprocessing=None, batch_size: int = 256):
        self.estimator = estimator
        self.feature_cols = list(feature_cols)
        self.prediction_col = prediction_col
        self.feature_preprocessing = feature_preprocessing
        self.batch_size = batch_size

    def set_feature_cols(self, cols) -> "NNModel":
        self.feature_cols = list(cols)
        return self

    def set_prediction_col(self, col: str) -> "NNModel":
        self.prediction_col = col
        return self

    def _predict_array(self, df) -> np.ndarray:
        x, _ = _df_to_xy(df, self.feature_cols,
                         feature_preprocessing=self.feature_preprocessing)
        return np.asarray(self.estimator.predict(
            x, batch_size=self.batch_size))

    def transform(self, df):
        preds = self._predict_array(df)
        out = df.copy()
        out[self.prediction_col] = (
            list(preds) if preds.ndim > 1 else preds)
        return out

    # -- persistence (ref NNModel.save/load) --
    def save(self, path: str):
        self.estimator.save(path)
        return path

    def load(self, path: str) -> "NNModel":
        self.estimator.load(path)
        return self


class NNEstimator:
    """``NNEstimator(model, loss).setBatchSize(...).fit(df) → NNModel``
    (ref NNEstimator.scala:202; python NNEstimator in nn_classifier.py).

    ``model``: a zoo-keras model (KerasNet / ZooModel) or flax module.
    """

    _model_cls = NNModel

    def __init__(self, model, loss, optimizer="adam",
                 feature_preprocessing=None, label_preprocessing=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.feature_preprocessing = feature_preprocessing
        self.label_preprocessing = label_preprocessing
        self.feature_cols: List[str] = ["features"]
        self.label_cols: List[str] = ["label"]
        self.prediction_col = "prediction"
        self.batch_size = 32
        self.max_epoch = 1
        self.caching_sample = True
        self._validation = None
        self._checkpoint_path = None

    # -- param setters (Spark-ML style, ref setFeaturesCol etc.) --
    def set_features_col(self, cols) -> "NNEstimator":
        self.feature_cols = [cols] if isinstance(cols, str) else list(cols)
        return self

    def set_label_col(self, cols) -> "NNEstimator":
        self.label_cols = [cols] if isinstance(cols, str) else list(cols)
        return self

    def set_prediction_col(self, col: str) -> "NNEstimator":
        self.prediction_col = col
        return self

    def set_batch_size(self, bs: int) -> "NNEstimator":
        self.batch_size = int(bs)
        return self

    def set_max_epoch(self, n: int) -> "NNEstimator":
        self.max_epoch = int(n)
        return self

    def set_validation(self, df, trigger=None) -> "NNEstimator":
        self._validation = df
        return self

    def set_checkpoint(self, path: str) -> "NNEstimator":
        self._checkpoint_path = path
        return self

    # camelCase aliases matching the reference python API
    setFeaturesCol = set_features_col
    setLabelCol = set_label_col
    setPredictionCol = set_prediction_col
    setBatchSize = set_batch_size
    setMaxEpoch = set_max_epoch
    setValidation = set_validation
    setCheckpoint = set_checkpoint

    def _build_estimator(self, sample_x) -> JaxEstimator:
        from analytics_zoo_tpu.keras.models import KerasNet
        model = self.model
        if hasattr(model, "model") and isinstance(
                getattr(model, "model", None), KerasNet):
            model = model.model  # ZooModel wrapper
        if isinstance(model, KerasNet):
            model.compile(optimizer=self.optimizer, loss=self.loss)
            est = model._ensure_estimator(for_training=True)
            if self._checkpoint_path:
                est.model_dir = self._checkpoint_path
            return est
        # assume flax module
        return Estimator.from_flax(
            model=model, loss=self.loss, optimizer=self.optimizer,
            sample_input=sample_x[:2] if not isinstance(sample_x, tuple)
            else tuple(a[:2] for a in sample_x),
            model_dir=self._checkpoint_path)

    def fit(self, df) -> NNModel:
        x, y = _df_to_xy(df, self.feature_cols, self.label_cols,
                         self.feature_preprocessing)
        if self.label_preprocessing is not None:
            y = self.label_preprocessing(y)
        est = self._build_estimator(x)
        val = None
        if self._validation is not None:
            vx, vy = _df_to_xy(self._validation, self.feature_cols,
                               self.label_cols, self.feature_preprocessing)
            if self.label_preprocessing is not None:
                vy = self.label_preprocessing(vy)
            val = (vx, vy)
        est.fit((x, y), epochs=self.max_epoch, batch_size=self.batch_size,
                validation_data=val)
        return self._model_cls(
            est, feature_cols=self.feature_cols,
            prediction_col=self.prediction_col,
            feature_preprocessing=self.feature_preprocessing,
            batch_size=max(self.batch_size, 32))


class NNClassifierModel(NNModel):
    """Prediction column holds the argmax class (ref NNClassifierModel)."""

    def transform(self, df):
        preds = self._predict_array(df)
        out = df.copy()
        if preds.ndim > 1 and preds.shape[-1] > 1:
            out[self.prediction_col] = np.argmax(preds, axis=-1).astype(
                np.float64)
        else:
            out[self.prediction_col] = (preds.reshape(-1) > 0.5).astype(
                np.float64)
        return out


class NNClassifier(NNEstimator):
    """NNEstimator whose fitted model emits class labels
    (ref NNClassifier.scala / python NNClassifier)."""

    _model_cls = NNClassifierModel


class NNImageReader:
    """Read an image directory into a DataFrame with ``image`` (HWC float
    array) and ``origin`` (path) columns — the reference reads into a Spark
    DataFrame of image schema rows (ref NNImageReader.scala:182)."""

    @staticmethod
    def read_images(path: str, resize_h: Optional[int] = None,
                    resize_w: Optional[int] = None, with_label: bool = False):
        import pandas as pd
        from analytics_zoo_tpu.feature.image import ImageSet
        from analytics_zoo_tpu.feature.image.transforms import ImageResize

        iset = ImageSet.read(path, with_label=with_label)
        if resize_h:
            iset = iset.transform(ImageResize(resize_h, resize_w or resize_h))
        feats = iset._features()
        data = {"image": [np.asarray(f.image, np.float32) for f in feats],
                "origin": [f.get("uri", "") for f in feats]}
        if with_label:
            data["label"] = [f.label for f in feats]
        return pd.DataFrame(data)
