"""Transformer / BERT encoders as flax modules.

Parity targets: ``zoo/.../keras/layers/TransformerLayer.scala:56`` (GPT-2
style decoder stack: token+position embeddings, causal blocks) and
``BERT.scala:66`` (token/segment/position embeddings, bidirectional encoder
blocks, pooled [CLS] output) plus the python mirror
``pyzoo/zoo/pipeline/api/keras/layers/self_attention.py``. The reference
builds these from ~400 lines of BigDL graph plumbing per layer; here each
is a compact flax module over the fused attention op
(ops/attention.py → pallas flash kernel for long sequences), so the whole
encoder fuses under jit and shards with the standard strategies (tp rules
below).

Weight-compatible layout with the reference's BERT (kernel shapes match
google-research/bert naming at the block level), so checkpoints can be
mapped across.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops.attention import AttentionModule


@dataclass(frozen=True)
class BertConfig:
    """(ref BERT.scala:66 constructor params / bert config.json)."""

    vocab: int = 30522
    hidden_size: int = 768
    n_block: int = 12
    n_head: int = 12
    intermediate_size: int = 3072
    hidden_drop: float = 0.1
    attn_drop: float = 0.1
    max_position_len: int = 512
    type_vocab: int = 2
    initializer_range: float = 0.02
    # exact (erf) gelu — what HF-format BERT checkpoints were trained
    # with (text/hf_import.py); the tanh approximation would put a ~1e-3
    # floor under import parity
    gelu_exact: bool = True
    # computation dtype (params stay fp32); jnp.bfloat16 doubles MXU
    # throughput on TPU — the default for training at scale
    dtype: Optional[object] = None
    # rematerialize each encoder block in the backward pass
    # (jax.checkpoint, keeping matmul outputs): activation memory drops
    # from O(n_block·b·L·hidden) to O(b·L·hidden) at ~⅓ extra forward
    # FLOPs — for LONG sequences / big batches that otherwise don't fit
    # HBM. Off by default: when everything fits, remat only costs MFU.
    remat: bool = False
    # attention backend: None → ops/attention.py auto-select, True → the
    # tuned pallas path (ops/autotune.py auto_flash_attention — engages
    # the kernel only where a measurement beat blockwise; head_dim 64 is
    # covered via lane padding), False → reference einsum attention
    use_flash: Optional[bool] = None

    @property
    def head_dim(self) -> int:
        assert self.hidden_size % self.n_head == 0
        return self.hidden_size // self.n_head


class EncoderBlock(nn.Module):
    """Post-LN transformer block (BERT ordering: attn → add&norm → ffn →
    add&norm; ref TransformerLayer.scala block / BERT.scala)."""

    hidden_size: int
    n_head: int
    intermediate_size: int
    dropout: float = 0.1
    attn_drop: float = 0.1
    causal: bool = False
    # computation dtype for the whole block INCLUDING the layernorms:
    # flax LayerNorm computes mean/var in fp32 internally regardless, so
    # dtype=bf16 only affects the normalized output — keeping the
    # residual stream bf16 instead of letting fp32 LN params promote it
    # (measured +0.06 MFU on BERT-base/v5e)
    dtype: Optional[object] = None
    # erf gelu for BERT-checkpoint fidelity (HF trained with exact);
    # the GPT-style causal stack keeps the canonical tanh approximation
    gelu_exact: bool = False
    # threaded to AttentionModule (see BertConfig.use_flash)
    use_flash: Optional[bool] = None

    @nn.compact
    def __call__(self, x, mask=None, train: bool = False):
        attn = AttentionModule(
            num_heads=self.n_head,
            head_dim=self.hidden_size // self.n_head,
            dropout=self.attn_drop, causal=self.causal, dtype=self.dtype,
            use_flash=self.use_flash,
            name="attention")(x, mask=mask, train=train)
        x = nn.LayerNorm(epsilon=1e-12, dtype=self.dtype,
                         name="attn_norm")(x + attn)
        h = nn.Dense(self.intermediate_size, dtype=self.dtype,
                     name="intermediate")(x)
        h = nn.gelu(h, approximate=not self.gelu_exact)
        h = nn.Dense(self.hidden_size, dtype=self.dtype, name="output")(h)
        if self.dropout > 0:
            h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return nn.LayerNorm(epsilon=1e-12, dtype=self.dtype,
                            name="ffn_norm")(x + h)


class BertModule(nn.Module):
    """BERT encoder (ref BERT.scala:66; outputs = (sequence, pooled) like
    the reference's ``outputAllBlock=false`` mode)."""

    config: BertConfig = BertConfig()

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 train: bool = False):
        cfg = self.config
        ids = jnp.asarray(input_ids).astype(jnp.int32)
        b, L = ids.shape
        if L > cfg.max_position_len:
            # XLA clamps out-of-range gathers, which would silently reuse
            # the last position embedding — fail loudly instead
            raise ValueError(f"sequence length {L} exceeds "
                             f"max_position_len {cfg.max_position_len}")
        emb = nn.Embed(cfg.vocab, cfg.hidden_size,
                       name="word_embeddings")(ids)
        pos = jnp.arange(L)[None, :]
        emb = emb + nn.Embed(cfg.max_position_len, cfg.hidden_size,
                             name="position_embeddings")(pos)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(ids)
        emb = emb + nn.Embed(cfg.type_vocab, cfg.hidden_size,
                             name="token_type_embeddings")(
            jnp.asarray(token_type_ids).astype(jnp.int32))
        x = nn.LayerNorm(epsilon=1e-12, dtype=cfg.dtype,
                         name="embed_norm")(emb)
        if cfg.hidden_drop > 0:
            x = nn.Dropout(cfg.hidden_drop, deterministic=not train)(x)

        mask = None
        if attention_mask is not None:
            # [b, L] 1/0 → [b, 1, 1, L] broadcast over heads and queries
            mask = jnp.asarray(attention_mask)[:, None, None, :]
        block_cls = EncoderBlock
        if cfg.remat:
            # recompute block activations in backward; dot outputs with no
            # batch dims (weight-stationary matmul results) stay saved so
            # the recompute is elementwise+attention only
            block_cls = nn.remat(
                EncoderBlock, static_argnums=(3,),
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        for i in range(cfg.n_block):
            x = block_cls(
                hidden_size=cfg.hidden_size, n_head=cfg.n_head,
                intermediate_size=cfg.intermediate_size,
                dropout=cfg.hidden_drop, attn_drop=cfg.attn_drop,
                dtype=cfg.dtype, gelu_exact=cfg.gelu_exact,
                use_flash=cfg.use_flash,
                name=f"block_{i}")(x, mask, train)
        pooled = nn.tanh(nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                                  name="pooler")(x[:, 0]))
        return x, pooled


class TransformerModule(nn.Module):
    """GPT-style causal decoder stack (ref TransformerLayer.scala:56:
    token+position embeddings, causal self-attention blocks; returns the
    full sequence representation)."""

    vocab: int
    hidden_size: int = 768
    n_block: int = 12
    n_head: int = 12
    intermediate_size: Optional[int] = None
    hidden_drop: float = 0.1
    attn_drop: Optional[float] = None  # None → follow hidden_drop
    max_position_len: int = 512
    dtype: Optional[object] = None     # computation dtype (params fp32)

    @nn.compact
    def __call__(self, input_ids, train: bool = False):
        ids = jnp.asarray(input_ids).astype(jnp.int32)
        b, L = ids.shape
        if L > self.max_position_len:
            raise ValueError(f"sequence length {L} exceeds "
                             f"max_position_len {self.max_position_len}")
        x = nn.Embed(self.vocab, self.hidden_size, name="wte")(ids)
        x = x + nn.Embed(self.max_position_len, self.hidden_size,
                         name="wpe")(jnp.arange(L)[None, :])
        if self.hidden_drop > 0:
            x = nn.Dropout(self.hidden_drop, deterministic=not train)(x)
        inter = self.intermediate_size or 4 * self.hidden_size
        attn_drop = (self.hidden_drop if self.attn_drop is None
                     else self.attn_drop)
        for i in range(self.n_block):
            x = EncoderBlock(
                hidden_size=self.hidden_size, n_head=self.n_head,
                intermediate_size=inter, dropout=self.hidden_drop,
                attn_drop=attn_drop, dtype=self.dtype,
                causal=True, name=f"block_{i}")(x, train=train)
        return x


def bert_tp_rules() -> list:
    """Tensor-parallel partition rules for the encoder: attention heads and
    FFN width shard over the ``model`` axis (Megatron layout: column-
    parallel QKV/intermediate, row-parallel out/output)."""
    return [
        (r"attention/(query|key|value)/kernel", (None, "model", None)),
        (r"attention/out/kernel", ("model", None, None)),
        (r"intermediate/kernel", (None, "model")),
        (r"output/kernel", ("model", None)),
        (r"word_embeddings/embedding", (None, "model")),
    ]
