"""HuggingFace-format BERT checkpoint import.

The reference's BERT estimators initialize from Google's released BERT
checkpoints (ref ``pyzoo/zoo/tfpark/text/estimator/bert_estimator.py``
``bert_config_file``/``init_checkpoint`` — TF1 ckpt format, dead outside
TF1). The living interchange format for the SAME weights is the
HuggingFace ``transformers`` state_dict (``bert-base-uncased`` et al.);
this module maps it onto ``text.bert.BertModule``'s parameter tree:

    clf = BERTClassifier(num_classes=2, config=BertConfig(...))
    clf.load_hf("pytorch_model.bin")     # or a live BertModel / state_dict

Parity is asserted against the REAL ``transformers`` implementation in
``tests/test_hf_bert_import.py`` (transformers ships in this image), so
the mapping is checked against the canonical source, not a hand twin.

Layout conversions:
- embeddings -> Embed tables (no transpose)
- q/k/v Linear [768, 768] -> DenseGeneral kernels [hidden, heads, dim]
- attention output Linear -> DenseGeneral kernel [heads, dim, hidden]
- intermediate/output/pooler Linear [out, in] -> Dense kernel [in, out]
- LayerNorm weight/bias -> scale/bias (eps 1e-12 both sides)
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from analytics_zoo_tpu.text.bert import BertConfig


def _np(t):
    if hasattr(t, "detach"):
        # .float() first: torch bf16 tensors (common in modern
        # checkpoints) have no direct .numpy()
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, np.float32)


def _strip_prefix(sd: Dict[str, Any]) -> Dict[str, Any]:
    """Accept BertModel dicts and BertFor* dicts (keys under 'bert.')."""
    if any(k.startswith("bert.") for k in sd):
        return {k[len("bert."):]: v for k, v in sd.items()
                if k.startswith("bert.")}
    return sd


def _dense(sd, prefix):
    return {"kernel": _np(sd[f"{prefix}.weight"]).T,
            "bias": _np(sd[f"{prefix}.bias"])}


def _norm(sd, prefix):
    return {"scale": _np(sd[f"{prefix}.weight"]),
            "bias": _np(sd[f"{prefix}.bias"])}


def hf_bert_params(state_dict_or_model, config: BertConfig) -> dict:
    """transformers ``BertModel`` weights -> ``BertModule`` params tree."""
    sd = state_dict_or_model
    if hasattr(sd, "state_dict"):
        sd = sd.state_dict()
    sd = _strip_prefix(dict(sd))
    h, d = config.n_head, config.head_dim
    H = config.hidden_size

    def qkv(prefix):
        w = _np(sd[f"{prefix}.weight"])               # [H, H] (out, in)
        b = _np(sd[f"{prefix}.bias"])
        return {"kernel": w.T.reshape(H, h, d), "bias": b.reshape(h, d)}

    params = {
        "word_embeddings": {
            "embedding": _np(sd["embeddings.word_embeddings.weight"])},
        "position_embeddings": {
            "embedding": _np(sd["embeddings.position_embeddings.weight"])},
        "token_type_embeddings": {
            "embedding": _np(
                sd["embeddings.token_type_embeddings.weight"])},
        "embed_norm": _norm(sd, "embeddings.LayerNorm"),
        "pooler": _dense(sd, "pooler.dense"),
    }
    for i in range(config.n_block):
        p = f"encoder.layer.{i}"
        wo = _np(sd[f"{p}.attention.output.dense.weight"])  # [H, H]
        params[f"block_{i}"] = {
            "attention": {
                "query": qkv(f"{p}.attention.self.query"),
                "key": qkv(f"{p}.attention.self.key"),
                "value": qkv(f"{p}.attention.self.value"),
                # DenseGeneral over (heads, dim) -> hidden
                "out": {"kernel": wo.T.reshape(h, d, H),
                        "bias": _np(
                            sd[f"{p}.attention.output.dense.bias"])},
            },
            "attn_norm": _norm(sd, f"{p}.attention.output.LayerNorm"),
            "intermediate": _dense(sd, f"{p}.intermediate.dense"),
            "output": _dense(sd, f"{p}.output.dense"),
            "ffn_norm": _norm(sd, f"{p}.output.LayerNorm"),
        }
    return params


def _validate_like(new: dict, ref: dict, path: str = "bert"):
    for k, v in new.items():
        if k not in ref:
            raise KeyError(f"{path}/{k} not in the model's parameter tree "
                           f"(have {sorted(ref)})")
        if isinstance(v, dict):
            _validate_like(v, ref[k], f"{path}/{k}")
        elif tuple(np.shape(v)) != tuple(np.shape(ref[k])):
            raise ValueError(f"{path}/{k}: checkpoint shape "
                             f"{np.shape(v)} != model {np.shape(ref[k])} "
                             "(config mismatch?)")


def load_hf_bert(estimator, state_dict_or_path,
                 bert_key: str = "bert") -> None:
    """Load HF BERT weights into a ``_BertTaskEstimator``'s encoder
    (task heads keep their current init — the fine-tuning flow)."""
    sd = state_dict_or_path
    if isinstance(sd, str):
        import torch
        sd = torch.load(sd, map_location="cpu", weights_only=True)
    est = estimator.estimator
    # sync live params back only if training already materialized them —
    # calling _init_state() here would build (then immediately discard)
    # the full optimizer state
    if est._state is not None:
        import jax
        est.adapter.params = jax.device_get(est._state["params"])
        est.adapter.model_state = jax.device_get(est._state["model_state"])
    params = dict(est.adapter.params)
    if bert_key not in params:
        raise KeyError(f"{bert_key!r} not in the estimator's parameter "
                       f"tree (have {sorted(params)})")
    new_bert = hf_bert_params(sd, estimator.config)
    _validate_like(new_bert, params[bert_key])
    params[bert_key] = new_bert
    est.adapter.params = params
    est._state = None
    est._predict_fn = None
    # the discarded state restarts the device step at 0 — keep the host
    # mirrors consistent (same convention as load_orca_checkpoint)
    est._py_step = 0
    est._epoch = 0
