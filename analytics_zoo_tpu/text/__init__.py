from analytics_zoo_tpu.text.bert import (
    BertConfig, BertModule, TransformerModule,
)
from analytics_zoo_tpu.text.estimators import (
    BERTClassifier, BERTNER, BERTSQuAD,
)

__all__ = ["BertConfig", "BertModule", "TransformerModule",
           "BERTClassifier", "BERTNER", "BERTSQuAD"]
