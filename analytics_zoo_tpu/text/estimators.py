"""BERT task estimators — classification, NER, SQuAD.

Parity with the reference's TFPark text estimators
(pyzoo/zoo/tfpark/text/estimator/: ``BERTClassifier``, ``BERTNER``,
``BERTSQuAD`` built on ``BERTBaseEstimator`` + TF-Estimator model_fns).
There each wraps a TF1 graph in the TFEstimator clone; here each is a flax
head module over ``BertModule`` driven by the standard JaxEstimator, so
fit/evaluate/predict run the same sharded train step as everything else
(tensor-parallel via ``bert_tp_rules`` when a ``tp`` strategy is set).

Inputs follow the reference's feature dict: ``(input_ids, token_type_ids,
input_mask)`` arrays of shape [b, L].
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.learn.estimator import Estimator, JaxEstimator
from analytics_zoo_tpu.learn.losses import jax_logsumexp
from analytics_zoo_tpu.text.bert import BertConfig, BertModule, bert_tp_rules


class _ClassifierModule(nn.Module):
    config: BertConfig
    n_classes: int

    @nn.compact
    def __call__(self, input_ids, token_type_ids, input_mask,
                 train: bool = False):
        _, pooled = BertModule(self.config, name="bert")(
            input_ids, token_type_ids, input_mask, train=train)
        if self.config.hidden_drop > 0:
            pooled = nn.Dropout(self.config.hidden_drop,
                                deterministic=not train)(pooled)
        return nn.Dense(self.n_classes, name="classifier")(pooled)


class _NERModule(nn.Module):
    config: BertConfig
    n_entities: int

    @nn.compact
    def __call__(self, input_ids, token_type_ids, input_mask,
                 train: bool = False):
        seq, _ = BertModule(self.config, name="bert")(
            input_ids, token_type_ids, input_mask, train=train)
        if self.config.hidden_drop > 0:
            seq = nn.Dropout(self.config.hidden_drop,
                             deterministic=not train)(seq)
        return nn.Dense(self.n_entities, name="ner")(seq)


class _SQuADModule(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids, input_mask,
                 train: bool = False):
        seq, _ = BertModule(self.config, name="bert")(
            input_ids, token_type_ids, input_mask, train=train)
        logits = nn.Dense(2, name="qa")(seq)           # [b, L, 2]
        return logits[..., 0], logits[..., 1]          # start, end


def _ner_loss(y_true, logits):
    """Per-token CE with padding positions excluded: labels < 0 are
    ignored (BERTNER.fit writes -1 at masked positions). Without this,
    short sequences would take most of their gradient from padding
    (ref BERTNER model_fn masks the loss the same way)."""
    y = jnp.asarray(y_true).astype(jnp.int32)
    logp = logits - jax_logsumexp(logits)
    ce = -jnp.take_along_axis(
        logp, jnp.maximum(y, 0)[..., None], axis=-1)[..., 0]
    valid = (y >= 0).astype(ce.dtype)
    return (ce * valid).sum(axis=-1) / jnp.maximum(valid.sum(axis=-1), 1.0)


def _squad_loss(y_true, preds):
    """y_true: [b, 2] (start_pos, end_pos); preds: (start_logits,
    end_logits) each [b, L] (ref BERTSQuAD model_fn loss)."""
    start_logits, end_logits = preds
    y = jnp.asarray(y_true).astype(jnp.int32)

    def ce(logits, idx):
        logp = logits - jax_logsumexp(logits)
        return -jnp.take_along_axis(logp, idx[:, None], axis=-1)[:, 0]

    return 0.5 * (ce(start_logits, y[:, 0]) + ce(end_logits, y[:, 1]))


class _BertTaskEstimator:
    """Shared surface (ref BERTBaseEstimator: fit/evaluate/predict over
    bert feature dicts)."""

    def __init__(self, module, loss, optimizer, metrics, config: BertConfig,
                 seq_len: int, model_dir, strategy, seed):
        from analytics_zoo_tpu.parallel.strategy import ShardingStrategy
        sample = tuple(np.zeros((2, seq_len), np.int32) for _ in range(3))
        rules = (bert_tp_rules()
                 if "tp" in ShardingStrategy.parse(strategy).uses else None)
        self.config = config
        self.seq_len = seq_len
        self.estimator: JaxEstimator = Estimator.from_flax(
            model=module, loss=loss, optimizer=optimizer, metrics=metrics,
            sample_input=sample, model_dir=model_dir, strategy=strategy,
            param_rules=rules, seed=seed)

    @staticmethod
    def _xy(input_ids, token_type_ids=None, input_mask=None, labels=None):
        ids = np.asarray(input_ids)
        seg = (np.zeros_like(ids) if token_type_ids is None
               else np.asarray(token_type_ids))
        msk = (np.ones_like(ids) if input_mask is None
               else np.asarray(input_mask))
        x = (ids, seg, msk)
        return x if labels is None else (x, np.asarray(labels))

    def fit(self, input_ids, labels, token_type_ids=None, input_mask=None,
            epochs: int = 1, batch_size: int = 32, **kw):
        data = self._xy(input_ids, token_type_ids, input_mask, labels)
        return self.estimator.fit(data, epochs=epochs,
                                  batch_size=batch_size, **kw)

    def evaluate(self, input_ids, labels, token_type_ids=None,
                 input_mask=None, batch_size: int = 32):
        data = self._xy(input_ids, token_type_ids, input_mask, labels)
        return self.estimator.evaluate(data, batch_size=batch_size)

    def predict(self, input_ids, token_type_ids=None, input_mask=None,
                batch_size: int = 32):
        x = self._xy(input_ids, token_type_ids, input_mask)
        # JaxEstimator.predict treats a tuple as multi-input features
        return self.estimator.predict(x, batch_size=batch_size)

    def save(self, path: str):
        return self.estimator.save(path)

    def load(self, path: str):
        self.estimator.load(path)
        return self

    def load_hf(self, state_dict_or_path):
        """Initialize the encoder from a HuggingFace-format BERT
        checkpoint (state_dict, live ``transformers`` module, or
        torch.save path) — the living replacement for the reference's
        TF1 ``init_checkpoint`` flow (bert_estimator.py). Task heads
        keep their init; fine-tune as usual afterwards."""
        from analytics_zoo_tpu.text.hf_import import load_hf_bert
        load_hf_bert(self, state_dict_or_path)
        return self


class BERTClassifier(_BertTaskEstimator):
    """Sequence classification on the pooled output
    (ref tfpark/text/estimator BERTClassifier)."""

    def __init__(self, num_classes: int, config: Optional[BertConfig] = None,
                 seq_len: int = 128, optimizer="adam", metrics=None,
                 model_dir=None, strategy="dp", seed: int = 0):
        config = config or BertConfig()
        super().__init__(
            _ClassifierModule(config, num_classes),
            "sparse_categorical_crossentropy_logits", optimizer,
            metrics, config, seq_len, model_dir, strategy, seed)


class BERTNER(_BertTaskEstimator):
    """Token-level entity tagging on the sequence output
    (ref tfpark/text/estimator BERTNER). Padded positions (input_mask 0)
    are excluded from the loss via -1 labels."""

    def __init__(self, num_entities: int, config: Optional[BertConfig] = None,
                 seq_len: int = 128, optimizer="adam", metrics=None,
                 model_dir=None, strategy="dp", seed: int = 0):
        config = config or BertConfig()
        super().__init__(
            _NERModule(config, num_entities), _ner_loss, optimizer,
            metrics, config, seq_len, model_dir, strategy, seed)

    @staticmethod
    def _masked(labels, input_mask):
        if input_mask is None:
            return labels
        return np.where(np.asarray(input_mask) > 0,
                        np.asarray(labels), -1)

    def fit(self, input_ids, labels, token_type_ids=None, input_mask=None,
            epochs: int = 1, batch_size: int = 32, **kw):
        return super().fit(input_ids, self._masked(labels, input_mask),
                           token_type_ids, input_mask,
                           epochs=epochs, batch_size=batch_size, **kw)

    def evaluate(self, input_ids, labels, token_type_ids=None,
                 input_mask=None, batch_size: int = 32):
        return super().evaluate(input_ids, self._masked(labels, input_mask),
                                token_type_ids, input_mask,
                                batch_size=batch_size)


class BERTSQuAD(_BertTaskEstimator):
    """Extractive QA start/end prediction
    (ref tfpark/text/estimator BERTSQuAD)."""

    def __init__(self, config: Optional[BertConfig] = None,
                 seq_len: int = 128, optimizer="adam", metrics=None,
                 model_dir=None, strategy="dp", seed: int = 0):
        config = config or BertConfig()
        super().__init__(
            _SQuADModule(config), _squad_loss, optimizer,
            metrics, config, seq_len, model_dir, strategy, seed)
