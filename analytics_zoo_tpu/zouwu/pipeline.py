"""TimeSequencePipeline — the reference's name for the fitted AutoTS
artifact (ref ``pyzoo/zoo/zouwu/pipeline/time_sequence.py:27``
TimeSequencePipeline + ``:211`` load_ts_pipeline). Here the pipeline
class lives in ``zouwu.autots`` as ``TSPipeline``; this module keeps the
reference import path working."""

from __future__ import annotations

from analytics_zoo_tpu.zouwu.autots.forecast import TSPipeline

__all__ = ["TimeSequencePipeline", "load_ts_pipeline"]

TimeSequencePipeline = TSPipeline


def load_ts_pipeline(file: str) -> TSPipeline:
    """(ref time_sequence.py:211 — restore a saved pipeline directory)"""
    return TSPipeline.load(file)
