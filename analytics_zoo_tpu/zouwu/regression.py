"""TimeSequencePredictor — the AutoTS search entry below AutoTSTrainer.

API parity with ref ``pyzoo/zoo/zouwu/regression/time_sequence_predictor.py:23``
(``TimeSequencePredictor(name, logs_dir, future_seq_len, dt_col,
target_col, extra_features_col).fit(input_df, validation_df, metric,
recipe) -> TimeSequencePipeline``; fit impl inherited from
``automl/regression/base_predictor.py:66``). Here it is a thin facade
over the same search engine that backs ``AutoTSTrainer`` — the Ray Tune
trial machinery collapses into the mesh-packed local engine."""

from __future__ import annotations

import os
from typing import Optional, Sequence, Union

from analytics_zoo_tpu.zouwu.autots.forecast import AutoTSTrainer, TSPipeline
from analytics_zoo_tpu.zouwu.config.recipe import Recipe, SmokeRecipe

__all__ = ["TimeSequencePredictor"]


class TimeSequencePredictor:
    """Trains a forecaster by hyperparameter search over recipes;
    ``fit`` returns a ``TSPipeline`` (the ref's TimeSequencePipeline).

    ``search_alg_params`` and ``scheduler_params`` are accepted for
    signature parity with the reference's Ray Tune configuration and are
    ignored — the local engine's bayes/hyperband implementations are not
    parameterized per-call."""

    def __init__(self, name: str = "automl",
                 logs_dir: str = "~/zoo_automl_logs",
                 future_seq_len: int = 1,
                 dt_col: str = "datetime",
                 target_col: Union[str, Sequence[str]] = "value",
                 extra_features_col: Optional[Sequence[str]] = None,
                 drop_missing: bool = True,
                 search_alg: Optional[str] = None,
                 search_alg_params=None,   # Ray-Tune-ism, parity only
                 scheduler: Optional[str] = None,
                 scheduler_params=None):   # Ray-Tune-ism, parity only
        if not isinstance(target_col, str):
            if len(target_col) != 1:
                raise ValueError("only a single target_col is supported")
            target_col = target_col[0]
        self.name = name
        self.logs_dir = os.path.expanduser(logs_dir)
        self.future_seq_len = int(future_seq_len)
        self.dt_col = dt_col
        self.target_col = target_col
        self.extra_features_col = extra_features_col
        self.drop_missing = drop_missing
        self.search_alg = search_alg
        self.scheduler = scheduler
        self.pipeline: Optional[TSPipeline] = None

    def fit(self, input_df, validation_df=None, metric: str = "mse",
            recipe: Optional[Recipe] = None, mc: bool = False,
            resources_per_trial=None, upload_dir=None) -> TSPipeline:
        """(ref base_predictor.py:66 — mc / resources_per_trial /
        upload_dir are Ray-Tune-isms accepted for signature parity;
        trials pack over the mesh instead)."""
        recipe = recipe or SmokeRecipe()
        if self.search_alg is not None and recipe.search_alg is None:
            # shallow-copy so the caller's recipe object is not mutated
            import copy
            recipe = copy.copy(recipe)
            recipe.search_alg = self.search_alg
        if self.drop_missing:
            input_df = input_df.dropna()
            if validation_df is not None:
                validation_df = validation_df.dropna()
        trainer = AutoTSTrainer(
            dt_col=self.dt_col, target_col=self.target_col,
            horizon=self.future_seq_len,
            extra_features_col=self.extra_features_col,
            logs_dir=self.logs_dir, name=self.name)
        self.pipeline = trainer.fit(input_df, validation_df, recipe=recipe,
                                    metric=metric, scheduler=self.scheduler)
        return self.pipeline

    def evaluate(self, input_df, metric=None):
        """(ref base_predictor.py:125)"""
        if self.pipeline is None:
            raise RuntimeError("call fit first")
        metrics = ([metric] if isinstance(metric, str)
                   else list(metric or ["mse"]))
        return self.pipeline.evaluate(input_df, metrics=metrics)

    def predict(self, input_df):
        """(ref base_predictor.py:142)"""
        if self.pipeline is None:
            raise RuntimeError("call fit first")
        return self.pipeline.predict(input_df)
