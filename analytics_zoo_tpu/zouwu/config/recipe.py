"""Recipes — named search-space presets for AutoTS.

API-parity with ``zoo.zouwu.config.recipe`` (ref
pyzoo/zoo/zouwu/config/recipe.py, 724 LoC: SmokeRecipe, GridRandomRecipe,
LSTMGridRandomRecipe, Seq2SeqRandomRecipe, TCNGridRandomRecipe,
MTNetGridRandomRecipe — each a ``search_space()`` + trial-count/stop
settings consumed by the search engine).
"""

from __future__ import annotations

from analytics_zoo_tpu.automl import hp


class Recipe:
    """A search space + trial budget."""

    num_samples: int = 1
    epochs: int = 1

    def search_space(self, all_available_features=None) -> dict:
        raise NotImplementedError

    def runtime_params(self) -> dict:
        return {"n_sampling": self.num_samples, "epochs": self.epochs}


class SmokeRecipe(Recipe):
    """One tiny config — CI smoke (ref recipe.py SmokeRecipe)."""

    num_samples = 1
    epochs = 2

    def search_space(self, all_available_features=None):
        return {
            "model": "VanillaLSTM",
            "past_seq_len": 12,
            "lstm_units": (16, 16),
            "dropouts": (0.1, 0.1),
            "lr": 1e-2,
            "batch_size": 32,
        }


class GridRandomRecipe(Recipe):
    """Grid over model family x random draws of its hyperparameters
    (ref recipe.py GridRandomRecipe)."""

    def __init__(self, num_rand_samples: int = 1, epochs: int = 2,
                 look_back: "int | tuple" = 24):
        self.num_samples = num_rand_samples
        self.epochs = epochs
        self.look_back = look_back

    def _past_seq(self):
        if isinstance(self.look_back, (tuple, list)):
            return hp.randint(self.look_back[0], self.look_back[1] + 1)
        return self.look_back

    def search_space(self, all_available_features=None):
        return {
            "model": hp.grid_search(["VanillaLSTM", "TCN"]),
            "past_seq_len": self._past_seq(),
            "lstm_units": hp.choice([(16, 16), (32, 32)]),
            "dropouts": (0.2, 0.2),
            "num_channels": hp.choice([(16, 16), (30, 30, 30)]),
            "kernel_size": hp.choice([2, 3]),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": hp.choice([32, 64]),
        }


class LSTMGridRandomRecipe(GridRandomRecipe):
    """(ref recipe.py LSTMGridRandomRecipe)"""

    def search_space(self, all_available_features=None):
        return {
            "model": "VanillaLSTM",
            "past_seq_len": self._past_seq(),
            "lstm_units": hp.choice([(16, 16), (32, 32), (64, 64)]),
            "dropouts": hp.choice([(0.1, 0.1), (0.2, 0.2)]),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": hp.choice([32, 64]),
        }


class TCNGridRandomRecipe(GridRandomRecipe):
    """(ref recipe.py TCNGridRandomRecipe)"""

    def search_space(self, all_available_features=None):
        return {
            "model": "TCN",
            "past_seq_len": self._past_seq(),
            "num_channels": hp.choice([(16, 16), (30, 30, 30)]),
            "kernel_size": hp.grid_search([2, 3]),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": hp.choice([32, 64]),
        }


class Seq2SeqRandomRecipe(GridRandomRecipe):
    """(ref recipe.py Seq2SeqRandomRecipe)"""

    def search_space(self, all_available_features=None):
        return {
            "model": "Seq2Seq",
            "past_seq_len": self._past_seq(),
            "latent_dim": hp.choice([32, 64, 128]),
            "dropout": hp.uniform(0.0, 0.3),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": hp.choice([32, 64]),
        }


class MTNetGridRandomRecipe(GridRandomRecipe):
    """(ref recipe.py MTNetGridRandomRecipe)"""

    def search_space(self, all_available_features=None):
        # MTNet's window is (long_series_num + 1) * series_length, so the
        # lookback is spelled by those two — no past_seq_len axis here
        return {
            "model": "MTNet",
            "long_series_num": hp.choice([2, 4]),
            "series_length": hp.choice([4, 8]),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": hp.choice([32, 64]),
        }
