"""Recipes — named search-space presets for AutoTS.

API-parity with ``zoo.zouwu.config.recipe`` (ref
pyzoo/zoo/zouwu/config/recipe.py, 724 LoC: SmokeRecipe, GridRandomRecipe,
LSTMGridRandomRecipe, Seq2SeqRandomRecipe, TCNGridRandomRecipe,
MTNetGridRandomRecipe — each a ``search_space()`` + trial-count/stop
settings consumed by the search engine).
"""

from __future__ import annotations

from analytics_zoo_tpu.automl import hp


class Recipe:
    """A search space + trial budget."""

    num_samples: int = 1
    epochs: int = 1
    search_alg: "str | None" = None

    def search_space(self, all_available_features=None) -> dict:
        raise NotImplementedError

    def runtime_params(self) -> dict:
        return {"n_sampling": self.num_samples, "epochs": self.epochs,
                "search_alg": self.search_alg}


class SmokeRecipe(Recipe):
    """One tiny config — CI smoke (ref recipe.py SmokeRecipe)."""

    num_samples = 1
    epochs = 2

    def search_space(self, all_available_features=None):
        return {
            "model": "VanillaLSTM",
            "past_seq_len": 12,
            "lstm_units": (16, 16),
            "dropouts": (0.1, 0.1),
            "lr": 1e-2,
            "batch_size": 32,
        }


class MTNetSmokeRecipe(Recipe):
    """One tiny MTNet config — CI smoke (ref recipe.py MTNetSmokeRecipe)."""

    num_samples = 1
    epochs = 2

    def search_space(self, all_available_features=None):
        return {
            "model": "MTNet",
            "long_series_num": 2,
            "series_length": 4,
            "ar_window": 2,
            "lr": 1e-2,
            "batch_size": 32,
        }


class TCNSmokeRecipe(Recipe):
    """One tiny TCN config — CI smoke (ref recipe.py TCNSmokeRecipe)."""

    num_samples = 1
    epochs = 2

    def search_space(self, all_available_features=None):
        return {
            "model": "TCN",
            "past_seq_len": 12,
            "num_channels": (16, 16),
            "kernel_size": 3,
            "lr": 1e-2,
            "batch_size": 32,
        }


class PastSeqParamHandler:
    """Spell a look_back spec as an hp axis (ref recipe.py:93)."""

    @staticmethod
    def get_past_seq_config(look_back):
        if isinstance(look_back, (tuple, list)):
            if len(look_back) != 2 or look_back[1] < look_back[0]:
                raise ValueError(
                    "look_back should be an int or an ordered (min, max) "
                    f"tuple, got {look_back!r}")
            return hp.randint(look_back[0], look_back[1] + 1)
        return look_back


class GridRandomRecipe(Recipe):
    """Grid over model family x random draws of its hyperparameters
    (ref recipe.py GridRandomRecipe)."""

    def __init__(self, num_rand_samples: int = 1, epochs: int = 2,
                 look_back: "int | tuple" = 24):
        self.num_samples = num_rand_samples
        self.epochs = epochs
        self.look_back = look_back

    def _past_seq(self):
        return PastSeqParamHandler.get_past_seq_config(self.look_back)

    @staticmethod
    def _features_axis(space: dict, all_available_features):
        """Add the feature-selection axis when the caller supplies the
        available names (ref recipes: 'selected_features':
        RandomSample(all_available_features))."""
        if all_available_features:
            space["selected_features"] = hp.subset(all_available_features)
        return space

    def search_space(self, all_available_features=None):
        return self._features_axis({
            "model": hp.grid_search(["VanillaLSTM", "TCN"]),
            "past_seq_len": self._past_seq(),
            "lstm_units": hp.choice([(16, 16), (32, 32)]),
            "dropouts": (0.2, 0.2),
            "num_channels": hp.choice([(16, 16), (30, 30, 30)]),
            "kernel_size": hp.choice([2, 3]),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": hp.choice([32, 64]),
        }, all_available_features)


class LSTMGridRandomRecipe(GridRandomRecipe):
    """(ref recipe.py LSTMGridRandomRecipe)"""

    def search_space(self, all_available_features=None):
        return self._features_axis({
            "model": "VanillaLSTM",
            "past_seq_len": self._past_seq(),
            "lstm_units": hp.choice([(16, 16), (32, 32), (64, 64)]),
            "dropouts": hp.choice([(0.1, 0.1), (0.2, 0.2)]),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": hp.choice([32, 64]),
        }, all_available_features)


class TCNGridRandomRecipe(GridRandomRecipe):
    """(ref recipe.py TCNGridRandomRecipe)"""

    def search_space(self, all_available_features=None):
        return self._features_axis({
            "model": "TCN",
            "past_seq_len": self._past_seq(),
            "num_channels": hp.choice([(16, 16), (30, 30, 30)]),
            "kernel_size": hp.grid_search([2, 3]),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": hp.choice([32, 64]),
        }, all_available_features)


class Seq2SeqRandomRecipe(GridRandomRecipe):
    """(ref recipe.py Seq2SeqRandomRecipe)"""

    def search_space(self, all_available_features=None):
        return self._features_axis({
            "model": "Seq2Seq",
            "past_seq_len": self._past_seq(),
            "latent_dim": hp.choice([32, 64, 128]),
            "dropout": hp.uniform(0.0, 0.3),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": hp.choice([32, 64]),
        }, all_available_features)


class LSTMSeq2SeqRandomRecipe(GridRandomRecipe):
    """Random draws across both LSTM and Seq2Seq families
    (ref recipe.py LSTMSeq2SeqRandomRecipe)."""

    def search_space(self, all_available_features=None):
        return self._features_axis({
            "model": hp.grid_search(["VanillaLSTM", "Seq2Seq"]),
            "past_seq_len": self._past_seq(),
            "lstm_units": hp.choice([(16, 16), (32, 32), (64, 64)]),
            "dropouts": hp.choice([(0.1, 0.1), (0.2, 0.2)]),
            "latent_dim": hp.choice([32, 64, 128]),
            "dropout": hp.uniform(0.0, 0.3),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": hp.choice([32, 64]),
        }, all_available_features)


class MTNetGridRandomRecipe(GridRandomRecipe):
    """(ref recipe.py MTNetGridRandomRecipe)"""

    def search_space(self, all_available_features=None):
        # MTNet's window is (long_series_num + 1) * series_length, so the
        # lookback is spelled by those two — no past_seq_len axis here
        return self._features_axis({
            "model": "MTNet",
            "long_series_num": hp.choice([2, 4]),
            "series_length": hp.choice([4, 8]),
            "lr": hp.loguniform(1e-3, 1e-2),
            "batch_size": hp.choice([32, 64]),
        }, all_available_features)


class RandomRecipe(GridRandomRecipe):
    """Pure random search, no grid axes (ref recipe.py RandomRecipe)."""

    def __init__(self, num_rand_samples: int = 1, epochs: int = 5,
                 look_back: "int | tuple" = 24):
        super().__init__(num_rand_samples, epochs, look_back)

    def search_space(self, all_available_features=None):
        return self._features_axis({
            "model": hp.choice(["VanillaLSTM", "TCN"]),
            "past_seq_len": self._past_seq(),
            "lstm_units": hp.choice([(16, 16), (32, 32), (64, 64)]),
            "dropouts": hp.uniform(0.0, 0.3),
            "num_channels": hp.choice([(16, 16), (30, 30, 30)]),
            "kernel_size": hp.choice([2, 3, 5]),
            "lr": hp.loguniform(1e-4, 1e-1),
            "batch_size": hp.qrandint(32, 128, 32),
        }, all_available_features)


class BayesRecipe(Recipe):
    """Search space shaped for the bayes (TPE-style) search alg — continuous
    axes only, consumed with ``search_alg="bayes"``
    (ref recipe.py BayesRecipe, skopt BayesOptSearch there)."""

    search_alg = "bayes"

    def __init__(self, num_samples: int = 1, epochs: int = 5,
                 look_back: "int | tuple" = 24):
        self.num_samples = num_samples
        self.epochs = epochs
        self.look_back = look_back

    def search_space(self, all_available_features=None):
        return {
            "model": "TCN",
            "past_seq_len":
                PastSeqParamHandler.get_past_seq_config(self.look_back),
            "num_channels": hp.choice([(16, 16), (30, 30, 30)]),
            "kernel_size": hp.randint(2, 6),
            "lr": hp.loguniform(1e-4, 1e-1),
            "batch_size": hp.qrandint(32, 128, 32),
        }


class XgbRegressorGridRandomRecipe(Recipe):
    """Search space for AutoXGBRegressor (ref recipe.py
    XgbRegressorGridRandomRecipe)."""

    def __init__(self, num_rand_samples: int = 1):
        self.num_samples = num_rand_samples

    def search_space(self, all_available_features=None):
        return {
            "n_estimators": hp.grid_search([50, 100]),
            "max_depth": hp.grid_search([2, 4]),
            "min_child_weight": hp.choice([1, 2, 3]),
            "learning_rate": hp.loguniform(1e-3, 1e-1),
        }


class XgbRegressorSkOptRecipe(Recipe):
    """Continuous XGB space for the bayes search alg (ref recipe.py
    XgbRegressorSkOptRecipe, skopt there)."""

    search_alg = "bayes"

    def __init__(self, num_rand_samples: int = 10):
        self.num_samples = num_rand_samples

    def search_space(self, all_available_features=None):
        return {
            "n_estimators": hp.randint(5, 10),
            "max_depth": hp.randint(2, 5),
        }
