from analytics_zoo_tpu.zouwu.config.recipe import (
    GridRandomRecipe,
    LSTMGridRandomRecipe,
    MTNetGridRandomRecipe,
    Recipe,
    Seq2SeqRandomRecipe,
    SmokeRecipe,
    TCNGridRandomRecipe,
)

__all__ = [
    "Recipe", "SmokeRecipe", "GridRandomRecipe", "LSTMGridRandomRecipe",
    "Seq2SeqRandomRecipe", "TCNGridRandomRecipe", "MTNetGridRandomRecipe",
]
