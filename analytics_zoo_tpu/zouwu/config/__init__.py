from analytics_zoo_tpu.zouwu.config.recipe import (
    BayesRecipe,
    GridRandomRecipe,
    LSTMGridRandomRecipe,
    LSTMSeq2SeqRandomRecipe,
    MTNetGridRandomRecipe,
    MTNetSmokeRecipe,
    PastSeqParamHandler,
    RandomRecipe,
    Recipe,
    Seq2SeqRandomRecipe,
    SmokeRecipe,
    TCNGridRandomRecipe,
    TCNSmokeRecipe,
    XgbRegressorGridRandomRecipe,
    XgbRegressorSkOptRecipe,
)

__all__ = [
    "Recipe", "SmokeRecipe", "MTNetSmokeRecipe", "TCNSmokeRecipe",
    "PastSeqParamHandler", "GridRandomRecipe", "LSTMGridRandomRecipe",
    "LSTMSeq2SeqRandomRecipe", "Seq2SeqRandomRecipe", "TCNGridRandomRecipe",
    "MTNetGridRandomRecipe", "RandomRecipe", "BayesRecipe",
    "XgbRegressorGridRandomRecipe", "XgbRegressorSkOptRecipe",
]
