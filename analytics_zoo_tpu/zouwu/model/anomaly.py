"""Anomaly detectors (ref ``pyzoo/zoo/zouwu/model/anomaly/anomaly.py``,
171 LoC: ThresholdDetector, AEDetector, DBScanDetector)."""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class ThresholdDetector:
    """Flag |y - y_hat| (or raw y) outside a threshold. ``fit`` derives the
    threshold as mean + ratio·std of the residuals (ref anomaly.py
    ThresholdDetector: absolute threshold or (mode, ratio) estimation)."""

    def __init__(self, mode: str = "default", ratio: float = 3.0,
                 threshold: Optional[float] = None):
        if mode not in ("default", "percentile"):
            raise ValueError(f"mode must be 'default' (mean + ratio·std) or "
                             f"'percentile' (ratio = percentile), got {mode!r}")
        self.mode = mode
        self.ratio = ratio
        self.threshold = threshold

    def fit(self, y: np.ndarray, y_pred: Optional[np.ndarray] = None):
        res = np.abs(y - y_pred) if y_pred is not None else np.abs(y)
        if self.mode == "percentile":
            self.threshold = float(np.percentile(res, self.ratio))
        else:
            self.threshold = float(res.mean() + self.ratio * res.std())
        return self

    def score(self, y: np.ndarray, y_pred: Optional[np.ndarray] = None
              ) -> np.ndarray:
        return np.abs(y - y_pred) if y_pred is not None else np.abs(y)

    def anomaly_indexes(self, y: np.ndarray,
                        y_pred: Optional[np.ndarray] = None) -> np.ndarray:
        if self.threshold is None:
            raise RuntimeError("fit first or pass threshold explicitly")
        return np.nonzero(self.score(y, y_pred) > self.threshold)[0]


class AEDetector:
    """Autoencoder reconstruction-error detector (ref anomaly.py AEDetector).

    Windows the series, trains a small flax MLP autoencoder through the zoo
    Estimator, and flags the top ``anomaly_ratio`` fraction of windows by
    reconstruction error."""

    def __init__(self, roll_len: int = 24, hidden: Tuple[int, ...] = (16, 8),
                 anomaly_ratio: float = 0.05, epochs: int = 5,
                 batch_size: int = 32, seed: int = 0):
        self.roll_len = roll_len
        self.hidden = tuple(hidden)
        self.anomaly_ratio = anomaly_ratio
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self._est = None
        self._mu = self._sigma = None

    def _windows(self, y: np.ndarray) -> np.ndarray:
        n = len(y) - self.roll_len + 1
        if n <= 0:
            raise ValueError(f"series shorter than roll_len={self.roll_len}")
        idx = np.arange(self.roll_len)[None, :] + np.arange(n)[:, None]
        return y[idx].astype(np.float32)

    def fit(self, y: np.ndarray):
        import flax.linen as nn

        from analytics_zoo_tpu.learn.estimator import Estimator

        y = np.asarray(y, np.float32).ravel()
        self._mu, self._sigma = float(y.mean()), float(y.std() or 1.0)
        w = (self._windows(y) - self._mu) / self._sigma

        hidden, roll_len = self.hidden, self.roll_len

        class _AE(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                h = x
                for units in hidden:
                    h = nn.relu(nn.Dense(units)(h))
                for units in reversed(hidden[:-1]):
                    h = nn.relu(nn.Dense(units)(h))
                return nn.Dense(roll_len)(h)

        self._est = Estimator.from_flax(
            model=_AE(), loss=lambda yt, yp: ((yt - yp) ** 2).mean(),
            sample_input=w[:1], seed=self.seed)
        self._est.fit((w, w), epochs=self.epochs,
                      batch_size=min(self.batch_size, len(w)))
        return self

    def score(self, y: np.ndarray) -> np.ndarray:
        """Per-timestep anomaly score = mean reconstruction error of the
        windows covering that step."""
        y = np.asarray(y, np.float32).ravel()
        w = (self._windows(y) - self._mu) / self._sigma
        rec = np.asarray(self._est.predict(w, batch_size=256))
        err = ((rec - w) ** 2).mean(1)                    # per window
        # spread window scores back over timesteps
        score = np.zeros(len(y))
        count = np.zeros(len(y))
        for i, e in enumerate(err):
            score[i:i + self.roll_len] += e
            count[i:i + self.roll_len] += 1
        return score / np.maximum(count, 1)

    def anomaly_indexes(self, y: np.ndarray) -> np.ndarray:
        s = self.score(y)
        k = max(1, int(len(s) * self.anomaly_ratio))
        return np.sort(np.argsort(s)[-k:])


class DBScanDetector:
    """Density-based outlier detection (ref anomaly.py DBScanDetector;
    sklearn DBSCAN labels -1 = anomaly)."""

    def __init__(self, eps: float = 0.5, min_samples: int = 5):
        self.eps, self.min_samples = eps, min_samples

    def anomaly_indexes(self, y: np.ndarray) -> np.ndarray:
        from sklearn.cluster import DBSCAN
        y = np.asarray(y, np.float32).reshape(len(y), -1)
        labels = DBSCAN(eps=self.eps,
                        min_samples=self.min_samples).fit_predict(y)
        return np.nonzero(labels == -1)[0]
