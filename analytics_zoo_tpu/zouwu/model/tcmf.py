"""TCMF: temporal-regularized matrix factorization for high-dimensional
forecasting.

Rebuild of ref ``pyzoo/zoo/zouwu/model/tcmf`` (DeepGLO-style TCMF, 904+705
LoC torch, distributed via XShards/Ray). Capability: factor a panel
Y [n_series, T] into F [n, k] @ X [k, T], forecast the small temporal basis
X forward, and emit per-series forecasts F @ X_future.

TPU-native design: the factorization trains as ONE jitted optax loop (the
whole Y fits on-chip for the scales the reference targets; n is sharded over
the mesh data axis when it doesn't), and the basis forecaster is a linear
AR(p) fitted in closed form — the reference's local TCN refinement is
available by passing ``basis_forecaster='tcn'``."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax


class TCMFForecaster:
    """fit(y) → predict(horizon) (ref tcmf model API: fit/forecast)."""

    def __init__(self, k: int = 8, lam: float = 1e-3, ar_order: int = 8,
                 lr: float = 0.05, basis_forecaster: str = "ar",
                 use_local: bool = False, local_lookback: int = 16,
                 seed: int = 0):
        self.k, self.lam, self.ar_order, self.lr = k, lam, ar_order, lr
        self.basis_forecaster = basis_forecaster
        # DeepGLO hybrid: a local temporal net on the residuals Y - F@X
        # refines the global forecast (ref tcmf: global MF + per-series
        # local TCN combination)
        self.use_local = use_local
        self.local_lookback = int(local_lookback)
        self.seed = seed
        self.F: Optional[np.ndarray] = None
        self.X: Optional[np.ndarray] = None
        self._local = None

    def fit(self, y: np.ndarray, num_steps: int = 300) -> float:
        """y: [n_series, T]. Returns final reconstruction MSE."""
        y = jnp.asarray(y, jnp.float32)
        n, t = y.shape
        rng = jax.random.PRNGKey(self.seed)
        rf, rx = jax.random.split(rng)
        params = {"F": jax.random.normal(rf, (n, self.k)) * 0.1,
                  "X": jax.random.normal(rx, (self.k, t)) * 0.1}
        tx = optax.adam(self.lr)
        opt_state = tx.init(params)
        lam = self.lam

        @jax.jit
        def step(params, opt_state):
            def loss_fn(p):
                recon = p["F"] @ p["X"]
                mse = jnp.mean((recon - y) ** 2)
                # temporal smoothness on the basis + L2 (the reference's
                # temporal regularizer role)
                smooth = jnp.mean(jnp.diff(p["X"], axis=1) ** 2)
                l2 = jnp.mean(p["F"] ** 2) + jnp.mean(p["X"] ** 2)
                return mse + lam * (smooth + l2)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        loss = jnp.inf
        for _ in range(num_steps):
            params, opt_state, loss = step(params, opt_state)
        self.F = np.asarray(params["F"])
        self.X = np.asarray(params["X"])
        if self.use_local:
            self._fit_local(np.asarray(y))
        return float(jnp.mean((params["F"] @ params["X"] - y) ** 2))

    # ---- DeepGLO hybrid local model over residuals ----
    def _fit_local(self, y: np.ndarray, epochs: int = 3):
        """Train a TCN on residual windows pooled across series (ref
        DeepGLO's local network refining the global factorization)."""
        from analytics_zoo_tpu.zouwu.model.forecast import TCNForecaster

        resid = y - self.F @ self.X                       # [n, T]
        p = min(self.local_lookback, resid.shape[1] - 2)
        if p < 2:
            self._local = None
            return
        xs, ys = [], []
        for row in resid:
            # window starts 0..T-p-1 inclusive: the final window targets
            # row[T-1], the freshest residual the TCN must extrapolate
            for s in range(0, len(row) - p, max(1, p // 4)):
                xs.append(row[s:s + p, None])
                ys.append(row[s + p:s + p + 1])
        self._local = TCNForecaster(future_seq_len=1,
                                    num_channels=(16, 16), kernel_size=3)
        self._local.fit(np.asarray(xs, np.float32),
                        np.asarray(ys, np.float32), epochs=epochs,
                        batch_size=min(64, len(xs)))
        self._resid_hist = resid

    def _local_forecast(self, horizon: int) -> np.ndarray:
        """Roll the residual TCN forward per series — [n, horizon]."""
        if self._local is None:
            return 0.0
        p = min(self.local_lookback, self._resid_hist.shape[1] - 2)
        hist = self._resid_hist[:, -p:].astype(np.float32)  # [n, p]
        outs = []
        for _ in range(horizon):
            nxt = self._local.predict(hist[..., None])      # [n, 1]
            nxt = np.asarray(nxt).reshape(-1, 1)
            outs.append(nxt)
            hist = np.concatenate([hist[:, 1:], nxt], axis=1)
        return np.concatenate(outs, axis=1)

    def fit_incremental(self, y_new: np.ndarray) -> None:
        """Extend the temporal basis for new observations with F FIXED:
        each new column solves the ridge system
        ``(FᵀF + λI) x_t = Fᵀ y_t`` in closed form
        (ref TCMF.fit_incremental: update X on incoming data without
        re-factorizing)."""
        if self.F is None:
            raise RuntimeError("call fit first")
        y_new = np.asarray(y_new, np.float32)
        if y_new.ndim != 2 or y_new.shape[0] != self.F.shape[0]:
            raise ValueError(
                f"y_new must be [n_series={self.F.shape[0]}, t_new], "
                f"got {y_new.shape}")
        g = self.F.T @ self.F + self.lam * np.eye(self.k, dtype=np.float32)
        x_new = np.linalg.solve(g, self.F.T @ y_new)      # [k, t_new]
        self.X = np.concatenate([self.X, x_new], axis=1)
        if self.use_local and self._local is not None:
            resid = y_new - self.F @ x_new
            self._resid_hist = np.concatenate([self._resid_hist, resid],
                                              axis=1)

    def _forecast_basis_ar(self, horizon: int) -> np.ndarray:
        """Closed-form AR(p) per factor row, rolled forward ``horizon``."""
        p = min(self.ar_order, self.X.shape[1] - 1)
        futures = []
        for row in self.X:
            # least-squares AR coefficients
            cols = np.stack([row[i:len(row) - p + i] for i in range(p)], 1)
            target = row[p:]
            coef, *_ = np.linalg.lstsq(
                np.column_stack([cols, np.ones(len(target))]),
                target, rcond=None)
            hist = list(row[-p:])
            out = []
            for _ in range(horizon):
                nxt = float(np.dot(coef[:-1], hist[-p:]) + coef[-1])
                out.append(nxt)
                hist.append(nxt)
            futures.append(out)
        return np.asarray(futures, np.float32)          # [k, horizon]

    def _forecast_basis_tcn(self, horizon: int) -> np.ndarray:
        from analytics_zoo_tpu.zouwu.model.forecast import TCNForecaster
        p = min(max(self.ar_order * 2, 8), self.X.shape[1] - horizon)
        if p < 1:
            raise ValueError(
                f"horizon={horizon} too long for the tcn basis forecaster: "
                f"fitted series length is {self.X.shape[1]}; need "
                f"horizon < T (or use basis_forecaster='ar')")
        xs, ys = [], []
        for row in self.X:
            for s in range(len(row) - p - horizon + 1):
                xs.append(row[s:s + p, None])
                ys.append(row[s + p:s + p + horizon])
        f = TCNForecaster(future_seq_len=horizon, num_channels=(16, 16),
                          kernel_size=3)
        f.fit(np.asarray(xs, np.float32), np.asarray(ys, np.float32),
              epochs=3, batch_size=min(32, len(xs)))
        last = np.stack([row[-p:, None] for row in self.X]).astype(np.float32)
        return f.predict(last)                           # [k, horizon]

    def predict(self, horizon: int = 24) -> np.ndarray:
        """[n_series, horizon] forecasts."""
        if self.X is None:
            raise RuntimeError("call fit first")
        if self.basis_forecaster == "tcn":
            xf = self._forecast_basis_tcn(horizon)
        else:
            xf = self._forecast_basis_ar(horizon)
        out = self.F @ xf
        if self.use_local:
            out = out + self._local_forecast(horizon)
        return out

    def evaluate(self, y_true: np.ndarray, metrics=("mse",)) -> dict:
        from analytics_zoo_tpu.automl.metrics import Evaluator
        pred = self.predict(y_true.shape[1])
        return {m: Evaluator.evaluate(m, y_true, pred) for m in metrics}
