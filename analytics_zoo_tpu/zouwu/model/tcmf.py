"""TCMF: temporal-regularized matrix factorization for high-dimensional
forecasting — at reference scale.

Rebuild of ref ``pyzoo/zoo/zouwu/model/forecast/tcmf_forecaster.py`` (API) +
``pyzoo/zoo/zouwu/model/tcmf/DeepGLO.py`` (904 LoC torch) +
``tcmf_model.py`` (525 LoC, XShards/Ray distribution): factor a panel
Y [n_series, T] into F [n, k] @ X [k, T], forecast the small temporal basis
X forward, and emit per-series forecasts F @ X_future, optionally refined by
a local temporal net on the residuals (DeepGLO hybrid).

TPU-native scale design — where the reference distributes the per-series
work over Ray actors on XShards partitions (``tcmf_model.py``), here the
SERIES dimension is sharded over the mesh's data axis and the whole
alternating factorization runs as ONE jitted ``lax.fori_loop`` dispatch:

- Y [n, T] and F [n, k] live sharded ``P("data", None)`` — each device
  owns n/D series and their factor rows; F's gradient update is entirely
  local (no communication).
- X [k, T] is replicated; its gradient is an XLA all-reduce over the data
  axis — the only collective per step, k·T floats riding ICI.
- ``fit(..., num_workers/distributed)`` and XShards inputs map onto this:
  shards concatenate to the global panel, then shard over the mesh —
  10k+ series train in one program instead of one Ray actor per partition.

Covariates/time features (ref ``use_time``/``period``/``covariates``) enter
the basis forecaster as extra AR regressors (seasonal lag + external rows).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax


def _coerce_panel(x):
    """Reference input contract (tcmf_forecaster.py fit: dict of ndarray
    {"id", "y"} or XShards of such dicts) → (y [n,T] float32, ids or None,
    was_xshards)."""
    from analytics_zoo_tpu.data.shard import XShards

    if isinstance(x, XShards):
        parts = x.collect()
        ys, ids = [], []
        for d in parts:
            assert isinstance(d, dict) and "y" in d, \
                "XShards for TCMF must hold {'id': ..., 'y': ...} dicts"
            ys.append(np.asarray(d["y"], np.float32))
            if d.get("id") is not None:
                ids.append(np.asarray(d["id"]))
        y = np.concatenate(ys, axis=0)
        id_arr = np.concatenate(ids) if len(ids) == len(ys) and ids else None
        return y, id_arr, True
    if isinstance(x, dict) and "y" in x:
        return (np.asarray(x["y"], np.float32),
                np.asarray(x["id"]) if x.get("id") is not None else None,
                False)
    return np.asarray(x, np.float32), None, False


def _time_features(idx) -> np.ndarray:
    """[4, T] calendar regressors from a DatetimeIndex, each normalized
    to [-0.5, 0.5] (the ref's use_time path derives hour/weekday/day/
    month features from dti/start_date+freq for the temporal net)."""
    import pandas as pd
    idx = pd.DatetimeIndex(idx)
    return np.stack([
        idx.hour.to_numpy() / 23.0 - 0.5,
        idx.dayofweek.to_numpy() / 6.0 - 0.5,
        (idx.day.to_numpy() - 1) / 30.0 - 0.5,
        (idx.month.to_numpy() - 1) / 11.0 - 0.5,
    ]).astype(np.float32)


class TCMFForecaster:
    """fit(x) → predict(horizon) (ref tcmf_forecaster.py TCMFForecaster).

    Reference argument names are accepted: ``rank`` (=k),
    ``learning_rate`` (=lr), ``normalize``, ``svd``, ``alt_iters`` /
    ``max_FX_epoch`` (together set the optimization step budget).
    """

    def __init__(self, k: int = 8, lam: float = 1e-3, ar_order: int = 8,
                 lr: float = 0.05, basis_forecaster: str = "ar",
                 use_local: bool = False, local_lookback: int = 16,
                 rank: Optional[int] = None,
                 learning_rate: Optional[float] = None,
                 normalize: bool = False, svd: bool = False,
                 period: Optional[int] = None,
                 seed: int = 0):
        self.k = int(rank) if rank is not None else k
        self.lam, self.ar_order = lam, ar_order
        self.lr = learning_rate if learning_rate is not None else lr
        self.basis_forecaster = basis_forecaster
        # DeepGLO hybrid: a local temporal net on the residuals Y - F@X
        # refines the global forecast (ref DeepGLO.py: global MF + local
        # TCN combination)
        self.use_local = use_local
        self.local_lookback = int(local_lookback)
        self.normalize = bool(normalize)       # ref DeepGLO.py:521-528
        self.svd = bool(svd)                   # ref DeepGLO svd init
        self.period = period                   # ref use_time/period
        self.seed = seed
        self.F: Optional[np.ndarray] = None
        self.X: Optional[np.ndarray] = None
        self._local = None
        self._norm = None                      # (mean, std, mini)
        self._covariates = None
        self._time_feats = None                # [4, T] calendar regressors
        self._dti_last = None                  # last training timestamp
        self._dti_freq = None                  # pandas freq string
        self._was_xshards = False
        self.fit_report: dict = {}

    # ------------------------------------------------------------- fit --
    def fit(self, x, num_steps: int = 300, distributed: Optional[bool] = None,
            num_workers: Optional[int] = None, covariates=None,
            val_len: int = 0, **ref_kwargs) -> float:
        """x: [n_series, T] ndarray, {"id","y"} dict, or XShards of dicts
        (ref fit input contract). Returns final reconstruction MSE.

        ``distributed=True`` (implied by XShards input or ``num_workers``)
        shards the series dimension over the mesh. Reference epoch knobs
        map onto ``num_steps`` as the ref's total F/X epoch budget:
        ``init_FX_epoch + alt_iters * max_FX_epoch`` (DeepGLO.py train_all:
        initial joint fit, then ``alt_iters`` alternating rounds of
        ``max_FX_epoch`` each); ``y_iters``/``max_TCN_epoch`` set the local
        residual net's epochs when ``use_local=True``. ``dti`` (or
        ``start_date``+``freq``) derives calendar regressors
        (hour/weekday/day/month) entering the AR basis design; predict
        extends them into the future automatically. Unknown kwargs
        raise.
        """
        known = {"max_FX_epoch", "init_FX_epoch", "alt_iters", "y_iters",
                 "max_TCN_epoch", "start_date", "freq", "dti", "period"}
        unknown = set(ref_kwargs) - known
        if unknown:
            raise TypeError(f"fit() got unexpected kwargs {sorted(unknown)}")
        if {"max_FX_epoch", "init_FX_epoch", "alt_iters"} & set(ref_kwargs):
            num_steps = (ref_kwargs.get("init_FX_epoch", 0)
                         + ref_kwargs.get("alt_iters", 1)
                         * ref_kwargs.get("max_FX_epoch", 0)) or num_steps
        self._local_epochs = ref_kwargs.get(
            "max_TCN_epoch", ref_kwargs.get("y_iters", 3))
        if ref_kwargs.get("period"):
            self.period = ref_kwargs["period"]
        y, ids, was_xshards = _coerce_panel(x)
        assert y.ndim == 2, f"TCMF expects [n_series, T], got {y.shape}"
        self._ids = ids
        self._was_xshards = was_xshards
        if distributed is None:
            distributed = was_xshards or (num_workers or 0) > 1
        self._covariates = (np.asarray(covariates, np.float32)
                            if covariates is not None else None)

        # dti / start_date+freq → calendar regressors entering the AR
        # basis design (ref DeepGLO use_time: datetime features derived
        # from the index become temporal-net covariates). Future values
        # are deterministic, so predict() extends them automatically.
        # Reset first: a refit without dti must not keep the previous
        # fit's calendar state (misaligned with the new X).
        self._time_feats = self._dti_last = self._dti_freq = None
        dti = ref_kwargs.get("dti")
        if dti is None and ref_kwargs.get("start_date") is not None:
            import pandas as pd
            dti = pd.date_range(ref_kwargs["start_date"],
                                periods=y.shape[1],
                                freq=ref_kwargs.get("freq", "D"))
        if dti is not None:
            import pandas as pd
            dti = pd.DatetimeIndex(dti)
            if len(dti) != y.shape[1]:
                raise ValueError(
                    f"dti length {len(dti)} must match T={y.shape[1]}")
            freq = (dti.freqstr or ref_kwargs.get("freq")
                    or pd.infer_freq(dti))
            if freq is None:
                raise ValueError(
                    "dti has no inferable frequency (irregular index); "
                    "pass freq=... so predict() can extend the calendar "
                    "features correctly")
            self._dti_freq = freq
            self._time_feats = _time_features(dti)
            self._dti_last = dti[-1]

        # ref fit(val_len=24): the last val_len columns are a holdout —
        # split BEFORE normalization (no leakage into the scalers) and
        # trim the covariates to the training window so the AR design
        # stays aligned; the held covariates become the validation
        # forecast's known future regressors
        holdout = hold_cov = None
        if val_len:
            if val_len >= y.shape[1] - 2:
                raise ValueError(
                    f"val_len={val_len} leaves too little history "
                    f"(T={y.shape[1]})")
            holdout = y[:, -val_len:]
            y = y[:, :-val_len]
            if self._covariates is not None:
                if self._covariates.shape[1] != y.shape[1] + val_len:
                    raise ValueError(
                        "covariates must span the same T as the input "
                        "(incl. the val_len window)")
                hold_cov = self._covariates[:, -val_len:]
                self._covariates = self._covariates[:, :-val_len]
            if self._time_feats is not None:
                # predict(val_len) re-derives the holdout stamps from
                # _dti_last + freq, so only the training slice is kept
                self._time_feats = self._time_feats[:, :-val_len]
                self._dti_last = dti[y.shape[1] - 1]

        if self.normalize:
            m = y.mean(axis=1)
            s = y.std(axis=1) + 1e-8
            y = (y - m[:, None]) / s[:, None]
            mini = float(np.abs(y.min()))
            y = y + mini
            self._norm = (m, s, mini)

        mesh = self._mesh() if distributed else None
        mse = self._run_factorization(y, num_steps, mesh)
        if self.use_local:
            self._fit_local(y, epochs=min(getattr(self, "_local_epochs", 3),
                                          10))
        if holdout is not None:
            # score through predict(): the SAME forecaster configuration
            # (basis ar/tcn, DeepGLO local residuals, denormalization,
            # known future covariates) the user will run
            val_pred = self.predict(int(val_len), future_covariates=hold_cov)
            self.fit_report["val_mse"] = float(
                np.mean((val_pred - holdout) ** 2))
        return mse

    @staticmethod
    def _mesh():
        from analytics_zoo_tpu.parallel.mesh import build_mesh, get_default_mesh
        mesh = get_default_mesh()
        if mesh is None:
            mesh = build_mesh()
        return mesh

    def _init_factors(self, y: np.ndarray):
        n, t = y.shape
        if self.svd:
            # ref DeepGLO svd=True: seed F/X from the truncated SVD
            u, s, vt = np.linalg.svd(y, full_matrices=False)
            r = min(self.k, s.shape[0])
            f0 = np.zeros((n, self.k), np.float32)
            x0 = np.zeros((self.k, t), np.float32)
            f0[:, :r] = u[:, :r] * np.sqrt(s[:r])
            x0[:r] = np.sqrt(s[:r])[:, None] * vt[:r]
            return f0, x0
        rng = jax.random.PRNGKey(self.seed)
        rf, rx = jax.random.split(rng)
        return (np.asarray(jax.random.normal(rf, (n, self.k)) * 0.1),
                np.asarray(jax.random.normal(rx, (self.k, t)) * 0.1))

    def _run_factorization(self, y: np.ndarray, num_steps: int, mesh) -> float:
        """The whole optimization as one jitted fori_loop dispatch; with a
        mesh, Y/F shard over the data axis (F-update communication-free,
        X-grad one all-reduce)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        n, t = y.shape
        f0, x0 = self._init_factors(y)
        y_dev = jnp.asarray(y)
        params = {"F": jnp.asarray(f0), "X": jnp.asarray(x0)}
        if mesh is not None:
            row = NamedSharding(mesh, P(mesh.axis_names[0], None))
            rep = NamedSharding(mesh, P())
            y_dev = jax.device_put(y_dev, row)
            params = {"F": jax.device_put(params["F"], row),
                      "X": jax.device_put(params["X"], rep)}

        tx = optax.adam(self.lr)
        lam = self.lam

        @jax.jit
        def run(params, y):
            opt_state = tx.init(params)

            def loss_fn(p):
                recon = p["F"] @ p["X"]
                mse = jnp.mean((recon - y) ** 2)
                # temporal smoothness on the basis + L2 — the reference's
                # temporal regularizer role (DeepGLO TCN-regularized X)
                smooth = jnp.mean(jnp.diff(p["X"], axis=1) ** 2)
                l2 = jnp.mean(p["F"] ** 2) + jnp.mean(p["X"] ** 2)
                return mse + lam * (smooth + l2)

            def body(_, carry):
                p, opt = carry
                _, grads = jax.value_and_grad(loss_fn)(p)
                updates, opt = tx.update(grads, opt)
                return optax.apply_updates(p, updates), opt

            p, _ = jax.lax.fori_loop(0, num_steps, body, (params, opt_state))
            final_mse = jnp.mean((p["F"] @ p["X"] - y) ** 2)
            return p, final_mse

        params, mse = run(params, y_dev)
        self.fit_report = {
            "sharded": mesh is not None,
            "devices_used": len(params["F"].sharding.device_set)
            if mesh is not None else 1,
            "n_series": n, "t": t, "num_steps": num_steps,
        }
        self.F = np.asarray(jax.device_get(params["F"]))
        self.X = np.asarray(jax.device_get(params["X"]))
        return float(mse)

    # ---- DeepGLO hybrid local model over residuals ----
    def _fit_local(self, y: np.ndarray, epochs: int = 3):
        """Train a TCN on residual windows pooled across series (ref
        DeepGLO's local network refining the global factorization)."""
        from analytics_zoo_tpu.zouwu.model.forecast import TCNForecaster

        resid = y - self.F @ self.X                       # [n, T]
        p = min(self.local_lookback, resid.shape[1] - 2)
        if p < 2:
            self._local = None
            return
        xs, ys = [], []
        for row in resid:
            # window starts 0..T-p-1 inclusive: the final window targets
            # row[T-1], the freshest residual the TCN must extrapolate
            for s in range(0, len(row) - p, max(1, p // 4)):
                xs.append(row[s:s + p, None])
                ys.append(row[s + p:s + p + 1])
        self._local = TCNForecaster(future_seq_len=1,
                                    num_channels=(16, 16), kernel_size=3)
        self._local.fit(np.asarray(xs, np.float32),
                        np.asarray(ys, np.float32), epochs=epochs,
                        batch_size=min(64, len(xs)))
        self._resid_hist = resid

    def _local_forecast(self, horizon: int) -> np.ndarray:
        """Roll the residual TCN forward per series — [n, horizon]."""
        if self._local is None:
            return 0.0
        p = min(self.local_lookback, self._resid_hist.shape[1] - 2)
        hist = self._resid_hist[:, -p:].astype(np.float32)  # [n, p]
        outs = []
        for _ in range(horizon):
            nxt = self._local.predict(hist[..., None])      # [n, 1]
            nxt = np.asarray(nxt).reshape(-1, 1)
            outs.append(nxt)
            hist = np.concatenate([hist[:, 1:], nxt], axis=1)
        return np.concatenate(outs, axis=1)

    # ----------------------------------------------------- incremental --
    def fit_incremental(self, x_incr, covariates_incr=None) -> None:
        """Extend the temporal basis for new observations with F FIXED:
        each new column solves the ridge system
        ``(FᵀF + λI) x_t = Fᵀ y_t`` in closed form
        (ref tcmf_forecaster.fit_incremental: update X on incoming data
        without re-factorizing). Accepts the same input formats as fit."""
        if self.F is None:
            raise RuntimeError("call fit first")
        y_new, _, _ = _coerce_panel(x_incr)
        if y_new.ndim != 2 or y_new.shape[0] != self.F.shape[0]:
            raise ValueError(
                f"x_incr must be [n_series={self.F.shape[0]}, t_new], "
                f"got {y_new.shape}")
        if self._covariates is not None:
            if covariates_incr is None:
                raise ValueError(
                    "the model was fit with covariates: fit_incremental "
                    "needs covariates_incr [r, t_new] to keep the basis "
                    "design aligned (ref fit_incremental covariates_incr)")
            cov_incr = np.asarray(covariates_incr, np.float32)
            if cov_incr.shape != (self._covariates.shape[0], y_new.shape[1]):
                raise ValueError(
                    f"covariates_incr must be "
                    f"[{self._covariates.shape[0]}, {y_new.shape[1]}], "
                    f"got {cov_incr.shape}")
            self._covariates = np.concatenate(
                [self._covariates, cov_incr], axis=1)
        if self._time_feats is not None:
            import pandas as pd
            new_idx = pd.date_range(self._dti_last,
                                    periods=y_new.shape[1] + 1,
                                    freq=self._dti_freq)[1:]
            self._time_feats = np.concatenate(
                [self._time_feats, _time_features(new_idx)], axis=1)
            self._dti_last = new_idx[-1]
        if self._norm is not None:
            m, s, mini = self._norm
            y_new = (y_new - m[:, None]) / s[:, None] + mini
        g = self.F.T @ self.F + self.lam * np.eye(self.k, dtype=np.float32)
        x_new = np.linalg.solve(g, self.F.T @ y_new)      # [k, t_new]
        self.X = np.concatenate([self.X, x_new], axis=1)
        if self.use_local and self._local is not None:
            resid = y_new - self.F @ x_new
            self._resid_hist = np.concatenate([self._resid_hist, resid],
                                              axis=1)

    # -------------------------------------------------------- forecast --
    def _basis_design(self, row: np.ndarray, p: int, per: Optional[int]):
        """AR design for one factor row: p lags, optional seasonal
        lag-``per`` regressor and external covariate rows (the ref's
        use_time/period/covariates entering the temporal net). Targets
        start at ``max(p, per)`` so every regressor index is in range."""
        t = len(row)
        start = max(p, per or 0)
        cols = [row[start - lag:t - lag] for lag in range(p, 0, -1)]
        if per:
            cols.append(row[start - per:t - per])
        if self._covariates is not None:
            for cov in self._covariates:
                cols.append(cov[start:t])
        if self._time_feats is not None:
            for tf in self._time_feats:
                cols.append(tf[start:t])
        cols.append(np.ones(t - start))
        return np.stack(cols, 1), row[start:]

    def _forecast_basis_ar(self, horizon: int,
                           future_covariates=None) -> np.ndarray:
        """Closed-form AR(p) (+ seasonal/covariate regressors) per factor
        row, rolled forward ``horizon``. ``future_covariates`` [r, horizon]
        supplies the known future regressor values (ref
        predict(future_covariates=...)); without them the last historical
        value is held."""
        t = self.X.shape[1]
        p = min(self.ar_order, t - 1)
        per = self.period if self.period and max(p, self.period) < t - 1 \
            else None
        if future_covariates is not None:
            fc = np.asarray(future_covariates, np.float32)
            if self._covariates is None:
                raise ValueError("future_covariates given but the model "
                                 "was fit without covariates")
            if fc.shape != (self._covariates.shape[0], horizon):
                raise ValueError(
                    f"future_covariates must be "
                    f"[{self._covariates.shape[0]}, {horizon}], "
                    f"got {fc.shape}")
        else:
            fc = None
        ftf = None
        if self._time_feats is not None:
            import pandas as pd
            future_idx = pd.date_range(self._dti_last,
                                       periods=horizon + 1,
                                       freq=self._dti_freq)[1:]
            ftf = _time_features(future_idx)
        futures = []
        for row in self.X:
            design, target = self._basis_design(row, p, per)
            coef, *_ = np.linalg.lstsq(design, target, rcond=None)
            hist = list(row)
            out = []
            for h in range(horizon):
                feats = list(hist[-p:])
                if per:
                    feats.append(hist[-per])
                if self._covariates is not None:
                    if fc is not None:
                        feats.extend(fc[:, h])
                    else:  # future values unknown: hold last observed
                        feats.extend(c[-1] for c in self._covariates)
                if ftf is not None:
                    feats.extend(ftf[:, h])
                feats.append(1.0)
                nxt = float(np.dot(coef, feats))
                out.append(nxt)
                hist.append(nxt)
            futures.append(out)
        return np.asarray(futures, np.float32)          # [k, horizon]

    def _forecast_basis_tcn(self, horizon: int) -> np.ndarray:
        from analytics_zoo_tpu.zouwu.model.forecast import TCNForecaster
        p = min(max(self.ar_order * 2, 8), self.X.shape[1] - horizon)
        if p < 1:
            raise ValueError(
                f"horizon={horizon} too long for the tcn basis forecaster: "
                f"fitted series length is {self.X.shape[1]}; need "
                f"horizon < T (or use basis_forecaster='ar')")
        xs, ys = [], []
        for row in self.X:
            for s in range(len(row) - p - horizon + 1):
                xs.append(row[s:s + p, None])
                ys.append(row[s + p:s + p + horizon])
        f = TCNForecaster(future_seq_len=horizon, num_channels=(16, 16),
                          kernel_size=3)
        f.fit(np.asarray(xs, np.float32), np.asarray(ys, np.float32),
              epochs=3, batch_size=min(32, len(xs)))
        last = np.stack([row[-p:, None] for row in self.X]).astype(np.float32)
        return f.predict(last)                           # [k, horizon]

    def predict(self, horizon: int = 24, future_covariates=None,
                num_workers: Optional[int] = None) -> np.ndarray:
        """[n_series, horizon] forecasts (ref predict(horizon, ...))."""
        if self.X is None:
            raise RuntimeError("call fit first")
        if self.basis_forecaster == "tcn":
            xf = self._forecast_basis_tcn(horizon)
        else:
            xf = self._forecast_basis_ar(horizon, future_covariates)
        out = self.F @ xf
        if self.use_local:
            out = out + self._local_forecast(horizon)
        if self._norm is not None:
            m, s, mini = self._norm
            out = (out - mini) * s[:, None] + m[:, None]
        return out

    # -------------------------------------------------------- evaluate --
    def evaluate(self, y_true: np.ndarray, metrics=("mse",),
                 target_covariates=None,
                 num_workers: Optional[int] = None) -> dict:
        """Forecast ``y_true.shape[1]`` steps and score (ref evaluate:
        target_value's second dim is the horizon; ``target_covariates``
        are the known future regressors for that window)."""
        from analytics_zoo_tpu.automl.metrics import Evaluator
        y_true, _, _ = _coerce_panel(y_true)
        pred = self.predict(y_true.shape[1],
                            future_covariates=target_covariates)
        return {m: Evaluator.evaluate(m, y_true, pred) for m in metrics}

    def rolling_evaluate(self, y_stream: np.ndarray, horizon: int,
                         metrics=("mse",), covariates=None) -> list:
        """Rolling-origin evaluation over a stream of future observations
        (the scale path the reference runs over Ray workers: repeatedly
        forecast ``horizon`` steps, then absorb the actuals via
        ``fit_incremental`` and roll forward). Returns one metrics dict
        per origin, each tagged with its start offset.

        ``covariates`` [r, y_stream.shape[1]]: future regressor values
        aligned with ``y_stream``; required when the model was fitted
        with covariates (each window is sliced for
        ``predict(future_covariates=...)`` and
        ``fit_incremental(covariates_incr=...)``)."""
        from analytics_zoo_tpu.automl.metrics import Evaluator
        y_stream, _, _ = _coerce_panel(y_stream)
        n, total = y_stream.shape
        if self.F is None:
            raise RuntimeError("call fit first")
        assert n == self.F.shape[0], "series count mismatch"
        if self._covariates is not None and covariates is None:
            raise ValueError(
                "model was fitted with covariates; rolling_evaluate needs "
                "covariates [r, y_stream_len] aligned with y_stream")
        cov = None
        if covariates is not None:
            cov = np.asarray(covariates, np.float32)
            if cov.shape[1] != total:
                raise ValueError(
                    f"covariates second dim {cov.shape[1]} must match "
                    f"y_stream length {total}")
        results = []
        for start in range(0, total - horizon + 1, horizon):
            chunk = y_stream[:, start:start + horizon]
            cov_chunk = (cov[:, start:start + horizon]
                         if cov is not None else None)
            pred = self.predict(horizon, future_covariates=cov_chunk)
            scores = {m: Evaluator.evaluate(m, chunk, pred) for m in metrics}
            scores["origin"] = start
            results.append(scores)
            self.fit_incremental(chunk, covariates_incr=cov_chunk)
        return results

    def is_xshards_distributed(self) -> bool:
        """ref tcmf_forecaster.is_xshards_distributed."""
        return self._was_xshards

    # ------------------------------------------------------- save/load --
    def save(self, path: str) -> None:
        """ref tcmf_forecaster.save: persist factors + config."""
        os.makedirs(path, exist_ok=True)
        arrays = {"F": self.F, "X": self.X}
        if self._norm is not None:
            arrays.update(norm_m=self._norm[0], norm_s=self._norm[1],
                          norm_mini=np.float32(self._norm[2]))
        if self._covariates is not None:
            arrays["covariates"] = self._covariates
        if self._time_feats is not None:
            arrays["time_feats"] = self._time_feats
        if self.use_local and self._local is not None:
            arrays["resid_hist"] = self._resid_hist
            self._local.save(os.path.join(path, "local_tcn"))
        np.savez(os.path.join(path, "tcmf_factors.npz"),
                 **{k: v for k, v in arrays.items() if v is not None})
        cfg = dict(k=self.k, lam=self.lam, ar_order=self.ar_order,
                   lr=self.lr, basis_forecaster=self.basis_forecaster,
                   use_local=self.use_local,
                   local_lookback=self.local_lookback,
                   normalize=self.normalize, svd=self.svd,
                   period=self.period, seed=self.seed,
                   was_xshards=self._was_xshards,
                   dti_last=(str(self._dti_last)
                             if self._dti_last is not None else None),
                   dti_freq=self._dti_freq)
        with open(os.path.join(path, "tcmf_config.json"), "w") as f:
            json.dump(cfg, f)

    @classmethod
    def load(cls, path: str, is_xshards_distributed: bool = False
             ) -> "TCMFForecaster":
        with open(os.path.join(path, "tcmf_config.json")) as f:
            cfg = json.load(f)
        was_xshards = cfg.pop("was_xshards", False)
        dti_last = cfg.pop("dti_last", None)
        dti_freq = cfg.pop("dti_freq", None)
        model = cls(**cfg)
        if dti_last is not None:
            import pandas as pd
            model._dti_last = pd.Timestamp(dti_last)
            model._dti_freq = dti_freq
        data = np.load(os.path.join(path, "tcmf_factors.npz"))
        model.F = data["F"]
        model.X = data["X"]
        if "norm_m" in data:
            model._norm = (data["norm_m"], data["norm_s"],
                           float(data["norm_mini"]))
        model._covariates = data["covariates"] if "covariates" in data \
            else None
        model._time_feats = data["time_feats"] if "time_feats" in data \
            else None
        if "resid_hist" in data:
            from analytics_zoo_tpu.zouwu.model.forecast import TCNForecaster
            model._resid_hist = data["resid_hist"]
            p = min(model.local_lookback, model._resid_hist.shape[1] - 2)
            model._local = TCNForecaster(future_seq_len=1,
                                         num_channels=(16, 16),
                                         kernel_size=3)
            model._local.restore(
                os.path.join(path, "local_tcn"),
                sample_x=model._resid_hist[:1, -p:, None].astype(np.float32))
        model._was_xshards = was_xshards or is_xshards_distributed
        return model
