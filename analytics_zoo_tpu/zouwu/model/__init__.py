from analytics_zoo_tpu.zouwu.model.nets import (  # noqa: F401
    VanillaLSTMNet, Seq2SeqNet, TemporalConvNet, MTNetModule,
)
