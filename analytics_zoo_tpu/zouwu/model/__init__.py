from analytics_zoo_tpu.zouwu.model.forecast import (  # noqa: F401
    Forecaster, LSTMForecaster, MTNetForecaster, Seq2SeqForecaster,
    TCNForecaster,
)
from analytics_zoo_tpu.zouwu.model.stats_forecast import (  # noqa: F401
    ARIMAForecaster, ProphetForecaster,
)
