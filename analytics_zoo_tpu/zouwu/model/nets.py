"""Forecasting network modules (flax), trained through the zoo Estimator.

TPU-native rebuilds of the reference's torch/keras forecast models:
- VanillaLSTMNet — ref ``pyzoo/zoo/zouwu/model/VanillaLSTM.py`` (keras
  stacked LSTM + dropout + dense head)
- Seq2SeqNet     — ref ``pyzoo/zoo/zouwu/model/Seq2Seq.py`` (341 LoC, LSTM
  encoder-decoder emitting future_seq_len steps)
- TemporalConvNet — ref ``pyzoo/zoo/zouwu/model/tcn.py:91`` (dilated causal
  conv residual blocks; torch there, ``nn.Conv`` with left-padding here —
  convs lower straight onto the MXU)
- MTNetModule    — ref ``pyzoo/zoo/zouwu/model/MTNet_keras.py`` (614 LoC:
  long-term memory chunks encoded by CNN+attention, short-term CNN encoder,
  autoregressive highway). Same decomposition, flax idiom.

All take [batch, time, features] and emit [batch, horizon]. Every
module accepts ``dtype`` (e.g. ``jnp.bfloat16``) for mixed-precision
compute with fp32 params — keras/policy.py semantics: hidden layers run
in ``dtype``, attention softmaxes, the output heads and the loss stay
fp32 (learn/losses.py upcasts)."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class VanillaLSTMNet(nn.Module):
    output_dim: int = 1
    lstm_units: Tuple[int, ...] = (32, 32)
    dropouts: Tuple[float, ...] = (0.2, 0.2)
    dtype: Optional[object] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i, units in enumerate(self.lstm_units):
            x = nn.RNN(nn.OptimizedLSTMCell(features=units,
                                            dtype=self.dtype))(x)
            drop = self.dropouts[min(i, len(self.dropouts) - 1)]
            if drop:
                x = nn.Dropout(rate=drop, deterministic=not train)(x)
        # output head stays fp32 (keras mixed-precision guidance): bf16
        # forecast values would leak ml_dtypes.bfloat16 into user code
        return nn.Dense(self.output_dim)(x[:, -1, :].astype(jnp.float32))


class Seq2SeqNet(nn.Module):
    future_seq_len: int = 1
    latent_dim: int = 64
    dropout: float = 0.2
    output_dim: int = 1
    dtype: Optional[object] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        b = x.shape[0]
        enc = nn.RNN(nn.OptimizedLSTMCell(features=self.latent_dim,
                                          dtype=self.dtype))(x)
        ctx = enc[:, -1, :]                                   # [b, latent]
        if self.dropout:
            ctx = nn.Dropout(rate=self.dropout,
                             deterministic=not train)(ctx)
        # decoder: feed the context at every future step (teacher-forcing-free
        # inference graph, matching the reference's inference decoder)
        dec_in = jnp.broadcast_to(ctx[:, None, :],
                                  (b, self.future_seq_len, self.latent_dim))
        dec = nn.RNN(nn.OptimizedLSTMCell(features=self.latent_dim,
                                          dtype=self.dtype))(dec_in)
        out = nn.Dense(self.output_dim)(
            dec.astype(jnp.float32))                          # [b, f, od]
        return out[..., 0] if self.output_dim == 1 else out


class _TemporalBlock(nn.Module):
    channels: int
    kernel_size: int
    dilation: int
    dropout: float
    dtype: Optional[object] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        # causal: left-pad so output[t] only sees inputs <= t
        pad = (self.kernel_size - 1) * self.dilation
        y = x
        for _ in range(2):
            y = jnp.pad(y, ((0, 0), (pad, 0), (0, 0)))
            y = nn.Conv(self.channels, (self.kernel_size,),
                        kernel_dilation=(self.dilation,), padding="VALID",
                        dtype=self.dtype)(y)
            y = nn.relu(y)
            y = nn.Dropout(rate=self.dropout, deterministic=not train)(y)
        res = x if x.shape[-1] == self.channels \
            else nn.Dense(self.channels, dtype=self.dtype)(x)
        return nn.relu(y + res.astype(y.dtype))


class TemporalConvNet(nn.Module):
    """Dilated causal conv stack + linear head (ref tcn.py:91
    TemporalConvNet; dilation doubles per level)."""
    future_seq_len: int = 1
    num_channels: Tuple[int, ...] = (30, 30, 30)
    kernel_size: int = 7
    dropout: float = 0.2
    dtype: Optional[object] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i, ch in enumerate(self.num_channels):
            x = _TemporalBlock(ch, self.kernel_size, 2 ** i,
                               self.dropout, self.dtype)(x, train)
        return nn.Dense(self.future_seq_len)(
            x[:, -1, :].astype(jnp.float32))


class _AttentionGRU(nn.Module):
    """The reference's ``AttentionRNNWrapper`` around stacked GRU cells
    (ref MTNet_keras.py:51-231): at every RNN step, additive attention —
    conditioned on the top cell's state — over ALL input timesteps picks a
    weighted input summary that is concatenated with the current input and
    projected before entering the (stacked) GRU.

    Per step t (ref step(), MTNet_keras.py:128-147):
        e   = tanh(X·W1 + b2 + (h·W2)[:, None]) · V      # [b, T, 1]
        a   = softmax_T(e)
        x~  = Σ_t a_t · X_t                               # [b, D]
        x'  = [x_t ; x~] · W3 + b3                        # [b, D]
        h, states = stacked_GRU(x', states)
    Implemented as one ``lax.scan`` over time with X·W1+b2 precomputed
    (the ref caches the same product in get_constants)."""

    hidden_sizes: Sequence[int]
    dtype: Optional[object] = None

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        init = nn.initializers.truncated_normal(stddev=0.1)
        w1 = self.param("W1", init, (d, d))
        b2 = self.param("b2", init, (d,))
        if self.dtype is not None:
            x = x.astype(self.dtype)
            w1, b2 = w1.astype(self.dtype), b2.astype(self.dtype)
        states = tuple(jnp.zeros((b, int(h)), x.dtype)
                       for h in self.hidden_sizes)
        xw1 = x @ w1 + b2                                   # [b, t, d]
        # carry = recurrent states only; X and X·W1+b2 are loop-invariant
        # and broadcast; the step owns the attention weights (shared
        # across steps via variable_broadcast)
        scan = nn.scan(
            _AttentionGRUStep, variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=(1, nn.broadcast, nn.broadcast), out_axes=1)
        _, ys = scan(hidden_sizes=tuple(self.hidden_sizes),
                     dtype=self.dtype,
                     name="steps")(states, x, x, xw1)
        return ys[:, -1, :]                                 # last output


class _AttentionGRUStep(nn.Module):
    """One attention+stacked-GRU step, scanned over time by
    ``_AttentionGRU``; params (attention weights + cells) are broadcast so
    every step shares them."""

    hidden_sizes: Tuple[int, ...]
    dtype: Optional[object] = None

    @nn.compact
    def __call__(self, states, x_t, x_all, xw1):
        d = x_all.shape[-1]
        h_out = int(self.hidden_sizes[-1])
        init = nn.initializers.truncated_normal(stddev=0.1)
        w2 = self.param("W2", init, (h_out, d))
        w3 = self.param("W3", init, (2 * d, d))
        b3 = self.param("b3", init, (d,))
        v = self.param("V", init, (d, 1))
        if self.dtype is not None:
            w2, w3, b3, v = (p.astype(self.dtype)
                             for p in (w2, w3, b3, v))
        h_top = states[-1]
        e = jnp.tanh(xw1 + (h_top @ w2)[:, None, :]) @ v    # [b, T, 1]
        # softmax over T stays fp32 (stability), result back in compute
        # dtype
        a = jax.nn.softmax(e.astype(jnp.float32),
                           axis=1).astype(e.dtype)
        x_weighted = jnp.sum(a * x_all, axis=1)             # [b, D]
        x_in = jnp.concatenate([x_t, x_weighted], axis=-1) @ w3 + b3
        new_states = []
        h = x_in
        for i, (hsize, st) in enumerate(zip(self.hidden_sizes, states)):
            st2, h = nn.GRUCell(features=int(hsize), dtype=self.dtype,
                                name=f"gru_{i}")(st, h)
            new_states.append(st2)
        return tuple(new_states), h


class MTNetModule(nn.Module):
    """Memory time-series network — the full reference architecture
    (ref MTNet_keras.py:234-446 MTNetKeras.build/__encoder, 614 LoC):

    - input is the long series [b, (long_num+1)·time_step, F]; the first
      ``long_num`` chunks of length ``time_step`` are long-term memory,
      the last chunk is the short-term query (the ref's two inputs,
      concatenated — ``MTNetForecaster`` feeds this layout);
    - THREE separate encoders (ref builds memory/context/query encoders
      with distinct weights): encoder = valid-padding CNN over time with
      full feature width (Conv2D kernel (cnn_height, F) there ≡ Conv1D
      kernel cnn_height VALID here) → relu → dropout → attention-GRU
      stack (``rnn_hid_sizes``); chunks fold into the batch dim so one
      batched conv/GRU feeds the MXU instead of a per-chunk loop;
    - attention: prob = memory·queryᵀ softmaxed over the ``long_num``
      memories, out = context ⊙ prob (the ref code's Softmax(axis=-1)
      acts on the singleton axis of [b, n, 1] — a no-op that weights all
      memories equally; we normalize over the memories per the MTNet
      paper, which subsumes the ref behavior up to a constant);
    - head: flatten [out ; query] → Dense(output_dim), truncated-normal
      0.1 / constant 0.1 init (ref build());
    - AR highway on ALL features of the last ``ar_window`` short-term
      steps (ref reshape_ar), disabled when ``ar_window == 0``.

    Reference hyperparameter names are the module fields: ``time_step``,
    ``long_num``, ``cnn_height``, ``cnn_hid_size``, ``rnn_hid_sizes``,
    ``cnn_dropout``, ``rnn_dropout`` (the ref's rnn_dropout applies inside
    GRUCell input gates; here it applies to the encoder sequence before
    the GRU — same regularization role), ``ar_window``, ``output_dim``.
    """

    output_dim: int = 1               # = future_seq_len
    long_num: int = 4                 # ref long_num (memory chunks)
    time_step: int = 8                # ref time_step (chunk length)
    cnn_hid_size: int = 32
    rnn_hid_sizes: Tuple[int, ...] = (16, 32)
    cnn_height: int = 3               # conv window over time
    ar_window: int = 4
    cnn_dropout: float = 0.1
    rnn_dropout: float = 0.1
    dtype: Optional[object] = None

    def _encoder(self, chunks, name, train):
        """[b·num, T, F] → [b·num, last_rnn_size] (ref __encoder)."""
        init = nn.initializers.truncated_normal(stddev=0.1)
        y = nn.Conv(self.cnn_hid_size, (self.cnn_height,), padding="VALID",
                    kernel_init=init,
                    bias_init=nn.initializers.constant(0.1),
                    dtype=self.dtype,
                    name=f"{name}_conv")(chunks)
        y = nn.relu(y)
        y = nn.Dropout(rate=self.cnn_dropout, deterministic=not train,
                       name=f"{name}_cnn_drop")(y)
        if self.rnn_dropout:
            y = nn.Dropout(rate=self.rnn_dropout, deterministic=not train,
                           name=f"{name}_rnn_drop")(y)
        return _AttentionGRU(hidden_sizes=self.rnn_hid_sizes,
                             dtype=self.dtype,
                             name=f"{name}_attgru")(y)

    @nn.compact
    def __call__(self, x, train: bool = False):
        n, t = self.long_num, self.time_step
        assert t >= self.ar_window, "ar_window must not exceed time_step"
        assert t >= self.cnn_height, "cnn_height must not exceed time_step"
        b = x.shape[0]
        assert x.shape[1] == (n + 1) * t, \
            f"expected seq len {(n + 1) * t}, got {x.shape[1]}"
        h_last = int(self.rnn_hid_sizes[-1])
        long_chunks = x[:, :n * t, :].reshape(b * n, t, x.shape[-1])
        short = x[:, n * t:, :]                              # [b, T, F]

        memory = self._encoder(long_chunks, "memory",
                               train).reshape(b, n, h_last)
        context = self._encoder(long_chunks, "context",
                                train).reshape(b, n, h_last)
        query = self._encoder(short, "query", train)         # [b, h]

        memory, context, query = (z.astype(jnp.float32)
                                  for z in (memory, context, query))
        prob = jnp.einsum("bnh,bh->bn", memory, query)
        prob = jax.nn.softmax(prob, axis=-1)                 # over memories
        out = context * prob[..., None]                      # [b, n, h]
        pred_x = jnp.concatenate([out, query[:, None, :]],
                                 axis=1).reshape(b, (n + 1) * h_last)
        init = nn.initializers.truncated_normal(stddev=0.1)
        nonlinear = nn.Dense(self.output_dim, kernel_init=init,
                             bias_init=nn.initializers.constant(0.1),
                             name="head")(pred_x)
        if self.ar_window > 0:
            ar_in = short[:, -self.ar_window:, :].reshape(
                b, -1).astype(jnp.float32)
            linear = nn.Dense(self.output_dim, kernel_init=init,
                              bias_init=nn.initializers.constant(0.1),
                              name="ar")(ar_in)
            return nonlinear + linear
        return nonlinear
