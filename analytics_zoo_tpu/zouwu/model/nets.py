"""Forecasting network modules (flax), trained through the zoo Estimator.

TPU-native rebuilds of the reference's torch/keras forecast models:
- VanillaLSTMNet — ref ``pyzoo/zoo/zouwu/model/VanillaLSTM.py`` (keras
  stacked LSTM + dropout + dense head)
- Seq2SeqNet     — ref ``pyzoo/zoo/zouwu/model/Seq2Seq.py`` (341 LoC, LSTM
  encoder-decoder emitting future_seq_len steps)
- TemporalConvNet — ref ``pyzoo/zoo/zouwu/model/tcn.py:91`` (dilated causal
  conv residual blocks; torch there, ``nn.Conv`` with left-padding here —
  convs lower straight onto the MXU)
- MTNetModule    — ref ``pyzoo/zoo/zouwu/model/MTNet_keras.py`` (614 LoC:
  long-term memory chunks encoded by CNN+attention, short-term CNN encoder,
  autoregressive highway). Same decomposition, flax idiom.

All take [batch, time, features] and emit [batch, horizon]."""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class VanillaLSTMNet(nn.Module):
    output_dim: int = 1
    lstm_units: Tuple[int, ...] = (32, 32)
    dropouts: Tuple[float, ...] = (0.2, 0.2)

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i, units in enumerate(self.lstm_units):
            x = nn.RNN(nn.OptimizedLSTMCell(features=units))(x)
            drop = self.dropouts[min(i, len(self.dropouts) - 1)]
            if drop:
                x = nn.Dropout(rate=drop, deterministic=not train)(x)
        return nn.Dense(self.output_dim)(x[:, -1, :])


class Seq2SeqNet(nn.Module):
    future_seq_len: int = 1
    latent_dim: int = 64
    dropout: float = 0.2
    output_dim: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        b = x.shape[0]
        enc = nn.RNN(nn.OptimizedLSTMCell(features=self.latent_dim))(x)
        ctx = enc[:, -1, :]                                   # [b, latent]
        if self.dropout:
            ctx = nn.Dropout(rate=self.dropout,
                             deterministic=not train)(ctx)
        # decoder: feed the context at every future step (teacher-forcing-free
        # inference graph, matching the reference's inference decoder)
        dec_in = jnp.broadcast_to(ctx[:, None, :],
                                  (b, self.future_seq_len, self.latent_dim))
        dec = nn.RNN(nn.OptimizedLSTMCell(features=self.latent_dim))(dec_in)
        out = nn.Dense(self.output_dim)(dec)                  # [b, f, od]
        return out[..., 0] if self.output_dim == 1 else out


class _TemporalBlock(nn.Module):
    channels: int
    kernel_size: int
    dilation: int
    dropout: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        # causal: left-pad so output[t] only sees inputs <= t
        pad = (self.kernel_size - 1) * self.dilation
        y = x
        for _ in range(2):
            y = jnp.pad(y, ((0, 0), (pad, 0), (0, 0)))
            y = nn.Conv(self.channels, (self.kernel_size,),
                        kernel_dilation=(self.dilation,), padding="VALID")(y)
            y = nn.relu(y)
            y = nn.Dropout(rate=self.dropout, deterministic=not train)(y)
        res = x if x.shape[-1] == self.channels else nn.Dense(self.channels)(x)
        return nn.relu(y + res)


class TemporalConvNet(nn.Module):
    """Dilated causal conv stack + linear head (ref tcn.py:91
    TemporalConvNet; dilation doubles per level)."""
    future_seq_len: int = 1
    num_channels: Tuple[int, ...] = (30, 30, 30)
    kernel_size: int = 7
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i, ch in enumerate(self.num_channels):
            x = _TemporalBlock(ch, self.kernel_size, 2 ** i,
                               self.dropout)(x, train)
        return nn.Dense(self.future_seq_len)(x[:, -1, :])


class MTNetModule(nn.Module):
    """Memory time-series network (ref MTNet_keras.py): input is the long
    series [b, (n+1)*T, F]; the first n chunks of length T form the memory,
    the last chunk is the short-term query.

    enc(chunk) = GRU(CNN(chunk)) → [b, hid]; attention of query encoding
    over memory encodings; plus an autoregressive highway on the raw target
    (feature 0) of the last ``ar_window`` steps."""
    future_seq_len: int = 1
    long_series_num: int = 4          # n
    series_length: int = 8            # T
    cnn_hid_size: int = 32
    rnn_hid_size: int = 32
    cnn_kernel_size: int = 3
    ar_window: int = 4
    dropout: float = 0.1

    def _encode(self, chunk, train):
        y = nn.Conv(self.cnn_hid_size, (self.cnn_kernel_size,),
                    padding="SAME", name="enc_conv")(chunk)
        y = nn.relu(y)
        y = nn.Dropout(rate=self.dropout, deterministic=not train,
                       name="enc_drop")(y)
        y = nn.RNN(nn.GRUCell(features=self.rnn_hid_size), name="enc_gru")(y)
        return y[:, -1, :]                                    # [b, hid]

    @nn.compact
    def __call__(self, x, train: bool = False):
        n, t = self.long_series_num, self.series_length
        b = x.shape[0]
        assert x.shape[1] == (n + 1) * t, \
            f"expected seq len {(n + 1) * t}, got {x.shape[1]}"
        # shared encoder over memory chunks + query: fold chunks into the
        # batch dim (one big batched conv/GRU feeds the MXU better than a
        # per-chunk loop)
        chunks = x.reshape(b * (n + 1), t, x.shape[-1])
        enc = self._encode(chunks, train).reshape(b, n + 1, self.rnn_hid_size)
        mem, query = enc[:, :n, :], enc[:, n, :]
        att = jnp.einsum("bnh,bh->bn", mem, query) / jnp.sqrt(self.rnn_hid_size)
        att = jax.nn.softmax(att, axis=-1)
        ctx = jnp.einsum("bn,bnh->bh", att, mem)
        hidden = jnp.concatenate([ctx, query], axis=-1)
        pred = nn.Dense(self.future_seq_len, name="head")(hidden)
        # autoregressive highway on the raw target channel
        ar_in = x[:, -self.ar_window:, 0]
        ar = nn.Dense(self.future_seq_len, name="ar")(ar_in)
        return pred + ar
