"""Statistical forecasters — ARIMA and a Prophet-class seasonal model.

The reference wraps external native libraries: ``pmdarima``/statsmodels for
ARIMA (pyzoo/zoo/zouwu/model/arima.py) and ``fbprophet`` (Stan) for Prophet
(pyzoo/zoo/zouwu/model/prophet.py). Neither is in the baked TPU image, and
both are per-series CPU solvers — so these are re-implemented natively on
numpy least squares (closed-form, no iterative MLE):

- ``ARIMAForecaster(p, d, q)``: d-fold differencing + Hannan–Rissanen
  two-stage ARMA estimation (long-AR residual proxy, then lstsq on AR+MA
  lags), recursive forecasting, inverse differencing. Matches the
  reference's fit(series) → predict(horizon) usage.
- ``ProphetForecaster``: additive model = piecewise-linear trend
  (changepoints at quantiles, ridge-penalized slope deltas — Prophet's
  core construction) + Fourier seasonality blocks (yearly/weekly/daily)
  solved in ONE lstsq. fit takes the same ``(ds, y)`` DataFrame as the
  reference; predict returns a ``yhat`` DataFrame.

Same Forecaster surface (fit/predict/evaluate/save/restore) as the neural
forecasters in zouwu/model/forecast.py.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import numpy as np


class ARIMAForecaster:
    """ARIMA(p, d, q) via CSS/Hannan–Rissanen (ref zouwu arima.py wrapper).

    fit on a 1-D series; predict rolls the model ``horizon`` steps ahead.
    """

    def __init__(self, p: int = 2, d: int = 0, q: int = 2, seed: int = 0):
        if min(p, d, q) < 0 or p + q == 0:
            raise ValueError("need p,d,q >= 0 and p+q > 0")
        self.p, self.d, self.q = int(p), int(d), int(q)
        self._coef = None       # [mu, phi_1..p, theta_1..q]
        self._resid_tail: Optional[np.ndarray] = None
        self._series_tail: Optional[np.ndarray] = None
        self._last_values: Optional[np.ndarray] = None

    # ------------------------------------------------------------ internals
    @staticmethod
    def _difference(y: np.ndarray, d: int):
        """Returns (d-times differenced series, tails) where tails[k] is
        the LAST value of the k-times differenced series — exactly the
        anchors inverse differencing needs (y_k[t+1] = y_k[t] +
        y_{k+1}[t+1])."""
        tails: List[float] = []
        for _ in range(d):
            tails.append(float(y[-1]))
            y = np.diff(y)
        return y, tails

    def _design(self, z: np.ndarray, resid: np.ndarray):
        p, q = self.p, self.q
        m = max(p, q)
        n = len(z) - m
        cols = [np.ones(n)]
        for i in range(1, p + 1):
            cols.append(z[m - i:m - i + n])
        for j in range(1, q + 1):
            cols.append(resid[m - j:m - j + n])
        return np.stack(cols, 1), z[m:m + n]

    def fit(self, y: np.ndarray, validation_data=None, **kwargs):
        y = np.asarray(y, np.float64).reshape(-1)
        if len(y) < max(self.p, self.q) + self.d + 10:
            raise ValueError(
                f"series too short ({len(y)}) for ARIMA"
                f"({self.p},{self.d},{self.q})")
        z, self._tails = self._difference(y, self.d)

        # stage 1: long AR to proxy the innovations
        k = min(max(self.p + self.q + 5, 10), len(z) // 2)
        Xar = np.stack([np.ones(len(z) - k)]
                       + [z[k - i:len(z) - i] for i in range(1, k + 1)], 1)
        beta, *_ = np.linalg.lstsq(Xar, z[k:], rcond=None)
        resid_long = z[k:] - Xar @ beta
        resid = np.concatenate([np.zeros(k), resid_long])

        # stage 2: regression on p AR lags + q MA (residual) lags
        X, target = self._design(z, resid)
        coef, *_ = np.linalg.lstsq(X, target, rcond=None)
        self._coef = coef
        fitted = X @ coef
        final_resid = np.concatenate(
            [np.zeros(max(self.p, self.q)), target - fitted])
        m = max(self.p, self.q, 1)
        self._resid_tail = final_resid[-m:]
        self._series_tail = z[-m:]
        self._last_values = y.copy()
        return self

    def predict(self, horizon: int = 1, **kwargs) -> np.ndarray:
        if self._coef is None:
            raise RuntimeError("fit before predict")
        p, q = self.p, self.q
        z = list(self._series_tail)
        resid = list(self._resid_tail)
        mu = self._coef[0]
        phi = self._coef[1:1 + p]
        theta = self._coef[1 + p:1 + p + q]
        out = []
        for _ in range(horizon):
            val = mu
            for i in range(p):
                val += phi[i] * z[-1 - i]
            for j in range(q):
                val += theta[j] * resid[-1 - j]
            z.append(val)
            resid.append(0.0)  # expected future innovation
            out.append(val)
        out = np.asarray(out)
        # invert the d differencings, innermost level first: the forecast
        # of the k-times-differenced series is cumsum of level k+1 anchored
        # on that level's last observed value
        for tail in reversed(self._tails):
            out = np.cumsum(out) + tail
        return out

    def evaluate(self, y_true: np.ndarray, metrics=("mse",)) -> Dict:
        from analytics_zoo_tpu.automl.metrics import Evaluator
        pred = self.predict(len(np.asarray(y_true).reshape(-1)))
        return {m: Evaluator.evaluate(m, np.asarray(y_true).reshape(-1),
                                      pred) for m in metrics}

    def save(self, path: str):
        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, "arima.npz"),
                 coef=self._coef, resid_tail=self._resid_tail,
                 series_tail=self._series_tail,
                 last_values=self._last_values,
                 tails=np.asarray(self._tails),
                 pdq=np.array([self.p, self.d, self.q]))
        return path

    def restore(self, path: str):
        blob = np.load(os.path.join(path, "arima.npz"))
        self.p, self.d, self.q = (int(v) for v in blob["pdq"])
        self._coef = blob["coef"]
        self._resid_tail = blob["resid_tail"]
        self._series_tail = blob["series_tail"]
        self._last_values = blob["last_values"]
        self._tails = list(blob["tails"])
        return self


class ProphetForecaster:
    """Additive trend+seasonality model (ref zouwu prophet.py wrapper).

    ``fit(df)`` takes the Prophet input frame: columns ``ds`` (datetime)
    and ``y``. ``predict(horizon, freq)`` returns a DataFrame with ``ds``
    and ``yhat`` — the reference forecaster's shape.
    """

    def __init__(self, n_changepoints: int = 10,
                 changepoint_prior_scale: float = 0.05,
                 yearly_seasonality="auto", weekly_seasonality="auto",
                 daily_seasonality="auto", seasonality_order: int = 5):
        self.n_changepoints = int(n_changepoints)
        self.cp_penalty = 1.0 / max(changepoint_prior_scale, 1e-6)
        self.yearly = yearly_seasonality
        self.weekly = weekly_seasonality
        self.daily = daily_seasonality
        self.order = int(seasonality_order)
        self._beta = None

    # ------------------------------------------------------------ features
    def _seasonal_blocks(self, span_seconds: float) -> List[float]:
        periods = []
        for flag, period, need in (
                (self.yearly, 365.25 * 86400, 2 * 365.25 * 86400),
                (self.weekly, 7 * 86400, 2 * 7 * 86400),
                (self.daily, 86400, 2 * 86400)):
            on = (flag is True) or (flag == "auto" and span_seconds >= need)
            if on:
                periods.append(period)
        return periods

    def _features(self, t: np.ndarray) -> np.ndarray:
        """t: seconds since t0. Columns: 1, t, relu(t - cp_i)..., fourier."""
        cols = [np.ones_like(t), t / self._scale]
        for cp in self._changepoints:
            cols.append(np.maximum(t - cp, 0.0) / self._scale)
        for period in self._periods:
            for k in range(1, self.order + 1):
                ang = 2 * np.pi * k * t / period
                cols.append(np.sin(ang))
                cols.append(np.cos(ang))
        return np.stack(cols, 1)

    def fit(self, df, validation_data=None, **kwargs):
        import pandas as pd
        ds = pd.to_datetime(df["ds"])
        y = np.asarray(df["y"], np.float64)
        t = (ds - ds.iloc[0]).dt.total_seconds().to_numpy()
        self._t0 = ds.iloc[0]
        self._t_max = float(t[-1])
        self._scale = max(self._t_max, 1.0)
        span = float(t[-1] - t[0])
        self._periods = self._seasonal_blocks(span)
        # changepoints at quantiles of the first 80% (Prophet's default)
        qs = np.linspace(0, 0.8, self.n_changepoints + 2)[1:-1]
        self._changepoints = np.quantile(t, qs) if self.n_changepoints \
            else np.array([])
        X = self._features(t)
        # ridge only on the changepoint slope deltas (Prophet's laplace
        # prior analog); trend/seasonality unpenalized
        n_cp = len(self._changepoints)
        penalty = np.zeros(X.shape[1])
        penalty[2:2 + n_cp] = self.cp_penalty
        A = X.T @ X + np.diag(penalty)
        b = X.T @ y
        self._beta = np.linalg.solve(A, b)
        self._y_last = y
        return self

    def predict(self, horizon: int = 1, freq: str = "D", **kwargs):
        import pandas as pd
        if self._beta is None:
            raise RuntimeError("fit before predict")
        # date_range handles calendar frequencies ('M', 'Y', ...) that have
        # no fixed timedelta
        last = self._t0 + pd.to_timedelta(self._t_max, unit="s")
        ds = pd.date_range(start=last, periods=horizon + 1, freq=freq)[1:]
        t = (ds - self._t0).total_seconds().to_numpy()
        yhat = self._features(t) @ self._beta
        return pd.DataFrame({"ds": ds, "yhat": yhat})

    def evaluate(self, target_df, metrics=("mse",)) -> Dict:
        import pandas as pd
        from analytics_zoo_tpu.automl.metrics import Evaluator
        ds = pd.to_datetime(target_df["ds"])
        t = (ds - self._t0).dt.total_seconds().to_numpy()
        yhat = self._features(t) @ self._beta
        y = np.asarray(target_df["y"], np.float64)
        return {m: Evaluator.evaluate(m, y, yhat) for m in metrics}

    def save(self, path: str):
        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, "prophet.npz"),
                 beta=self._beta, changepoints=self._changepoints,
                 periods=np.asarray(self._periods),
                 meta=np.array([self._t_max, self._scale, self.order]))
        with open(os.path.join(path, "prophet_t0.json"), "w") as f:
            json.dump({"t0": str(self._t0)}, f)
        return path

    def restore(self, path: str):
        import pandas as pd
        blob = np.load(os.path.join(path, "prophet.npz"))
        self._beta = blob["beta"]
        self._changepoints = blob["changepoints"]
        self._periods = list(blob["periods"])
        self._t_max, self._scale, order = blob["meta"]
        self.order = int(order)
        with open(os.path.join(path, "prophet_t0.json")) as f:
            self._t0 = pd.Timestamp(json.load(f)["t0"])
        return self
