"""Forecaster API (ref ``pyzoo/zoo/zouwu/model/forecast/`` — LSTMForecaster,
Seq2SeqForecaster, TCNForecaster, MTNetForecaster wrap tfpark KerasModels
there; here each wraps a flax module trained through the zoo Estimator, so
fit runs as one jitted data-parallel train step on the mesh)."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.learn import losses as losses_lib
from analytics_zoo_tpu.learn.estimator import Estimator
from analytics_zoo_tpu.zouwu.model.nets import (
    MTNetModule, Seq2SeqNet, TemporalConvNet, VanillaLSTMNet,
)


class Forecaster:
    """Common fit/predict/evaluate surface (ref forecast.py Forecaster
    base; sklearn-style like the reference's)."""

    def __init__(self, *, optimizer="adam", loss="mse",
                 model_dir: Optional[str] = None, seed: int = 0,
                 dtype: str = "float32"):
        self.optimizer = optimizer
        self.loss = loss
        self.model_dir = model_dir
        self.seed = seed
        self._est: Optional[object] = None
        # "float32" (default) or "mixed_bfloat16": bf16 compute with fp32
        # params — the keras/policy.py table is the single source of
        # truth for names and semantics (the loss tail stays fp32 via
        # learn/losses.py)
        from analytics_zoo_tpu.keras.policy import _POLICIES
        if dtype not in _POLICIES:
            raise ValueError(
                f"unknown dtype {dtype!r}; one of {sorted(_POLICIES)}")
        self.dtype = dtype

    @property
    def _net_dtype(self):
        from analytics_zoo_tpu.keras.policy import _POLICIES
        return _POLICIES[self.dtype]

    # subclasses implement
    def _build_module(self, x: np.ndarray):  # pragma: no cover
        raise NotImplementedError

    def _ensure_est(self, x: np.ndarray):
        if self._est is None:
            module = self._build_module(x)
            self._est = Estimator.from_flax(
                model=module, loss=losses_lib.get(self.loss),
                optimizer=self.optimizer, metrics=None,
                sample_input=x[:1], model_dir=self.model_dir,
                seed=self.seed)
        return self._est

    def fit(self, x: np.ndarray, y: np.ndarray, epochs: int = 1,
            batch_size: int = 32, validation_data=None, **kwargs):
        """x: [n, lookback, F]; y: [n, horizon]."""
        est = self._ensure_est(x)
        return est.fit((x, y), epochs=epochs, batch_size=batch_size,
                       validation_data=validation_data, **kwargs)

    def predict(self, x: np.ndarray, batch_size: int = 256) -> np.ndarray:
        if self._est is None:
            raise RuntimeError("call fit (or restore) before predict")
        return np.asarray(self._est.predict(x, batch_size=batch_size))

    def evaluate(self, x: np.ndarray, y: np.ndarray,
                 metrics: Sequence[str] = ("mse",),
                 batch_size: int = 256) -> dict:
        from analytics_zoo_tpu.automl.metrics import Evaluator
        pred = self.predict(x, batch_size)
        return {m: Evaluator.evaluate(m, y, pred) for m in metrics}

    def save(self, path: str):
        self._est.save(path)

    def restore(self, path: str, sample_x: Optional[np.ndarray] = None):
        if self._est is None:
            if sample_x is None:
                raise ValueError("pass sample_x to restore an unbuilt model")
            self._ensure_est(sample_x)
        self._est.load(path)


class LSTMForecaster(Forecaster):
    """(ref forecast/LSTMForecaster)"""

    def __init__(self, target_dim: int = 1,
                 lstm_units: Tuple[int, ...] = (32, 32),
                 dropouts: Tuple[float, ...] = (0.2, 0.2), **kwargs):
        super().__init__(**kwargs)
        self.target_dim = target_dim
        self.lstm_units = tuple(lstm_units)
        self.dropouts = tuple(dropouts)

    def _build_module(self, x):
        return VanillaLSTMNet(output_dim=self.target_dim,
                              lstm_units=self.lstm_units,
                              dropouts=self.dropouts,
                              dtype=self._net_dtype)


class Seq2SeqForecaster(Forecaster):
    """(ref forecast/Seq2SeqForecaster)"""

    def __init__(self, future_seq_len: int = 1, latent_dim: int = 64,
                 dropout: float = 0.2, **kwargs):
        super().__init__(**kwargs)
        self.future_seq_len = future_seq_len
        self.latent_dim = latent_dim
        self.dropout = dropout

    def _build_module(self, x):
        return Seq2SeqNet(future_seq_len=self.future_seq_len,
                          latent_dim=self.latent_dim, dropout=self.dropout,
                          dtype=self._net_dtype)


class TCNForecaster(Forecaster):
    """(ref forecast/TCNForecaster → zouwu/model/tcn.py)"""

    def __init__(self, future_seq_len: int = 1,
                 num_channels: Tuple[int, ...] = (30, 30, 30),
                 kernel_size: int = 7, dropout: float = 0.2, **kwargs):
        super().__init__(**kwargs)
        self.future_seq_len = future_seq_len
        self.num_channels = tuple(num_channels)
        self.kernel_size = kernel_size
        self.dropout = dropout

    def _build_module(self, x):
        return TemporalConvNet(future_seq_len=self.future_seq_len,
                               num_channels=self.num_channels,
                               kernel_size=self.kernel_size,
                               dropout=self.dropout,
                               dtype=self._net_dtype)


class MTNetForecaster(Forecaster):
    """(ref forecast/MTNetForecaster over MTNet_keras.py; input seq len
    must equal (long_num + 1) * time_step — the ref's [long_input,
    short_input] pair concatenated along time).

    Accepts the REFERENCE hyperparameter names (``time_step``,
    ``long_num``, ``cnn_height``, ``rnn_hid_sizes`` list, ``cnn_dropout``,
    ``rnn_dropout`` — MTNet_keras.py apply_config defaults) and keeps the
    earlier aliases (series_length/long_series_num/cnn_kernel_size/
    rnn_hid_size/dropout) working."""

    def __init__(self, future_seq_len: int = 1,
                 time_step: Optional[int] = None,
                 long_num: Optional[int] = None,
                 cnn_height: Optional[int] = None,
                 cnn_hid_size: int = 32,
                 rnn_hid_sizes: Optional[Sequence[int]] = None,
                 ar_window: int = 4,
                 cnn_dropout: Optional[float] = None,
                 rnn_dropout: Optional[float] = None,
                 # earlier spellings (None = not passed, so a legacy-alias
                 # call is detectable)
                 long_series_num: Optional[int] = None,
                 series_length: Optional[int] = None,
                 rnn_hid_size: Optional[int] = None,
                 cnn_kernel_size: Optional[int] = None,
                 dropout: Optional[float] = None,
                 **kwargs):
        super().__init__(**kwargs)
        legacy_call = any(v is not None for v in (
            long_series_num, series_length, cnn_kernel_size, dropout,
            rnn_hid_size))
        if rnn_hid_sizes is None:
            if rnn_hid_size:
                rnn_hid_sizes = (rnn_hid_size,)
            elif legacy_call:
                # a legacy-alias caller that never chose an RNN size gets
                # the pre-round-4 single 32-unit GRU: the stacked (16, 32)
                # default changes the param-tree shape, so old scripts
                # would silently train a different architecture and old
                # checkpoints would fail to restore
                rnn_hid_sizes = (32,)
            else:
                rnn_hid_sizes = (16, 32)   # MTNet_keras.py apply_config
        if long_series_num is None:
            long_series_num = 4
        if series_length is None:
            series_length = 8
        if cnn_kernel_size is None:
            cnn_kernel_size = 3
        if dropout is None:
            dropout = 0.1
        self.kw = dict(
            output_dim=future_seq_len,
            long_num=long_num if long_num is not None else long_series_num,
            time_step=time_step if time_step is not None else series_length,
            cnn_hid_size=cnn_hid_size,
            rnn_hid_sizes=tuple(int(h) for h in rnn_hid_sizes),
            cnn_height=cnn_height if cnn_height is not None
            else cnn_kernel_size,
            ar_window=ar_window,
            # legacy `dropout` was ONE dropout before the GRU — map it to
            # cnn_dropout only (mapping it to both would stack two layers
            # and double the effective rate vs earlier rounds)
            cnn_dropout=cnn_dropout if cnn_dropout is not None else dropout,
            rnn_dropout=rnn_dropout if rnn_dropout is not None else 0.0)

    def _build_module(self, x):
        return MTNetModule(dtype=self._net_dtype, **self.kw)


# High-dimensional panel forecaster (ref zouwu/model/forecast/
# tcmf_forecaster.py lives beside the per-series forecasters)
from analytics_zoo_tpu.zouwu.model.tcmf import TCMFForecaster  # noqa: E402,F401
