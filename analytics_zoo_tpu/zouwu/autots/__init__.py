from analytics_zoo_tpu.zouwu.autots.forecast import AutoTSTrainer, TSPipeline

__all__ = ["AutoTSTrainer", "TSPipeline"]
