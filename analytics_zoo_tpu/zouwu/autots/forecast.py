"""AutoTS — automated time-series model selection + tuning.

API-parity with ``zoo.zouwu.autots.forecast`` (ref
pyzoo/zoo/zouwu/autots/forecast.py:22-181: ``AutoTSTrainer.fit(train_df,
validation_df, recipe) -> TSPipeline``; the pipeline bundles the fitted
feature transformer + best model with fit/evaluate/predict/save/load).
The search itself runs on the local search engine instead of Ray Tune
(ref regression/time_sequence_predictor.py:23 + automl/regression/
base_predictor.py:66).
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

import numpy as np
import pandas as pd

from analytics_zoo_tpu.automl.metrics import Evaluator
from analytics_zoo_tpu.automl.model_builder import ModelBuilder
from analytics_zoo_tpu.automl.search import LocalSearchEngine
from analytics_zoo_tpu.learn.optimizers import Adam
from analytics_zoo_tpu.zouwu.config.recipe import Recipe, SmokeRecipe
from analytics_zoo_tpu.zouwu.feature.time_sequence import (
    TimeSequenceFeatureTransformer,
)
from analytics_zoo_tpu.zouwu.model.forecast import (
    LSTMForecaster,
    MTNetForecaster,
    Seq2SeqForecaster,
    TCNForecaster,
)

_MODEL_KEYS = {
    "VanillaLSTM": ("lstm_units", "dropouts"),
    "TCN": ("num_channels", "kernel_size"),
    "Seq2Seq": ("latent_dim", "dropout"),
    "MTNet": ("long_series_num", "series_length", "ar_window"),
}


def _build_forecaster(config: dict, future_seq_len: int):
    model = config.get("model", "VanillaLSTM")
    lr = float(config.get("lr", 1e-3))
    kw = {k: config[k] for k in _MODEL_KEYS.get(model, ())
          if k in config}
    opt = Adam(learningrate=lr)
    if model == "VanillaLSTM":
        if "lstm_units" in kw:
            kw["lstm_units"] = tuple(kw["lstm_units"])
        if "dropouts" in kw:
            d = kw["dropouts"]
            # recipes may sample a scalar rate (e.g. RandomRecipe's
            # hp.uniform) — apply it to every LSTM layer
            if np.isscalar(d):
                n = len(kw.get("lstm_units", (None, None)))
                d = (float(d),) * n
            kw["dropouts"] = tuple(d)
        return LSTMForecaster(target_dim=future_seq_len, optimizer=opt, **kw)
    if model == "TCN":
        if "num_channels" in kw:
            kw["num_channels"] = tuple(kw["num_channels"])
        return TCNForecaster(future_seq_len=future_seq_len, optimizer=opt,
                             **kw)
    if model == "Seq2Seq":
        return Seq2SeqForecaster(future_seq_len=future_seq_len, optimizer=opt,
                                 **kw)
    if model == "MTNet":
        return MTNetForecaster(future_seq_len=future_seq_len, optimizer=opt,
                               **kw)
    raise ValueError(f"unknown model family {model!r}")


def _effective_past_seq_len(config: dict) -> int:
    if config.get("model") == "MTNet":
        # MTNet consumes (long_series_num + 1) contiguous windows of
        # series_length each (ref MTNet input layout).
        lsn = int(config.get("long_series_num", 4))
        sl = int(config.get("series_length", 8))
        return (lsn + 1) * sl
    return int(config.get("past_seq_len", 24))


class _TSTrialModel:
    """One AutoTS trial: feature transformer + forecaster trained as a
    unit (the search engine drives ``fit_eval`` once per epoch)."""

    def __init__(self, config: dict, dt_col: str, target_col: str,
                 extra_features_col, future_seq_len: int):
        self.config = dict(config)
        self.dt_col, self.target_col = dt_col, target_col
        self.extra_features_col = extra_features_col
        self.future_seq_len = future_seq_len
        self.transformer = TimeSequenceFeatureTransformer(
            past_seq_len=_effective_past_seq_len(config),
            future_seq_len=future_seq_len, dt_col=dt_col,
            target_col=target_col, extra_features_col=extra_features_col,
            selected_features=config.get("selected_features"))
        self.forecaster = _build_forecaster(config, future_seq_len)
        self._train_xy = None
        self._val_xy = None

    def fit_eval(self, data, validation_data=None, epochs: int = 1,
                 metric: str = "mse", batch_size: Optional[int] = None
                 ) -> float:
        if self._train_xy is None:
            self._train_xy = self.transformer.fit_transform(data)
        x, y = self._train_xy
        bs = int(batch_size or self.config.get("batch_size", 32))
        bs = min(bs, len(x))
        self.forecaster.fit(x, y, epochs=epochs, batch_size=bs)
        if validation_data is not None:
            if self._val_xy is None:
                self._val_xy = self.transformer.transform(validation_data)
            vx, vy = self._val_xy
        else:
            vx, vy = x, y
        pred = self.forecaster.predict(vx)
        return Evaluator.evaluate(metric, vy, pred)

    def save(self, path: str):
        os.makedirs(path, exist_ok=True)
        self.transformer.save(os.path.join(path, "transformer"))
        self.forecaster.save(os.path.join(path, "model"))
        meta = {"config": {k: (list(v) if isinstance(v, tuple) else v)
                           for k, v in self.config.items()},
                "dt_col": self.dt_col, "target_col": self.target_col,
                "extra_features_col": list(self.extra_features_col or []),
                "future_seq_len": self.future_seq_len}
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f)

    def restore(self, path: str, sample_x=None):
        self.transformer.restore(os.path.join(path, "transformer"))
        n_feat = self.transformer.n_features
        dummy = np.zeros((1, self.transformer.past_seq_len, n_feat),
                         np.float32)
        self.forecaster.restore(os.path.join(path, "model"), sample_x=dummy)


class _TSModelBuilder(ModelBuilder):
    def __init__(self, dt_col, target_col, extra_features_col,
                 future_seq_len):
        self.kw = dict(dt_col=dt_col, target_col=target_col,
                       extra_features_col=extra_features_col,
                       future_seq_len=future_seq_len)

    def build(self, config):
        return _TSTrialModel(config, **self.kw)


class TSPipeline:
    """Fitted transformer + model bundle (ref
    pyzoo/zoo/zouwu/pipeline/time_sequence.py:27 TimeSequencePipeline)."""

    def __init__(self, trial_model: _TSTrialModel):
        self._m = trial_model

    # -- inference ---------------------------------------------------------
    def predict(self, input_df: pd.DataFrame) -> np.ndarray:
        """[n_windows, horizon] forecasts in original target units."""
        x = self._m.transformer.transform(input_df, with_y=False)
        pred = self._m.forecaster.predict(x)
        return self._m.transformer.unscale_y(pred)

    def evaluate(self, input_df: pd.DataFrame,
                 metrics: Sequence[str] = ("mse",)) -> dict:
        x, y = self._m.transformer.transform(input_df)
        pred = self._m.forecaster.predict(x)
        y_true = self._m.transformer.unscale_y(y)
        y_pred = self._m.transformer.unscale_y(pred)
        return {m: Evaluator.evaluate(m, y_true, y_pred) for m in metrics}

    # -- incremental fit ---------------------------------------------------
    def fit(self, input_df: pd.DataFrame, epochs: int = 1,
            batch_size: Optional[int] = None):
        """Continue training on new data with the fitted scaling."""
        x, y = self._m.transformer.transform(input_df)
        bs = int(batch_size or self._m.config.get("batch_size", 32))
        self._m.forecaster.fit(x, y, epochs=epochs,
                               batch_size=min(bs, len(x)))
        return self

    # -- persistence -------------------------------------------------------
    def save(self, path: str):
        self._m.save(path)
        return path

    @staticmethod
    def load(path: str) -> "TSPipeline":
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        model = _TSTrialModel(meta["config"], meta["dt_col"],
                              meta["target_col"],
                              meta["extra_features_col"] or None,
                              int(meta["future_seq_len"]))
        model.restore(path)
        return TSPipeline(model)

    @property
    def config(self) -> dict:
        return dict(self._m.config)


class AutoTSTrainer:
    """(ref autots/forecast.py:22 AutoTSTrainer)"""

    def __init__(self, dt_col: str = "datetime", target_col: str = "value",
                 horizon: int = 1,
                 extra_features_col: Optional[Sequence[str]] = None,
                 logs_dir: str = "/tmp/analytics_zoo_tpu_automl",
                 name: str = "autots", seed: int = 0):
        self.dt_col, self.target_col = dt_col, target_col
        self.horizon = int(horizon)
        self.extra_features_col = extra_features_col
        self.builder = _TSModelBuilder(dt_col, target_col,
                                       extra_features_col, self.horizon)
        self.engine = LocalSearchEngine(self.builder, logs_dir=logs_dir,
                                        name=name, seed=seed)

    def fit(self, train_df: pd.DataFrame,
            validation_df: Optional[pd.DataFrame] = None,
            recipe: Recipe = None, metric: str = "mse",
            scheduler: Optional[str] = None) -> TSPipeline:
        recipe = recipe or SmokeRecipe()
        rt = recipe.runtime_params()
        # what the recipe's selected_features axis may draw from
        available = TimeSequenceFeatureTransformer(
            dt_col=self.dt_col, target_col=self.target_col,
            extra_features_col=self.extra_features_col
        ).all_available_features
        self.engine.compile(train_df, recipe.search_space(available),
                            n_sampling=rt["n_sampling"], epochs=rt["epochs"],
                            validation_data=validation_df, metric=metric,
                            scheduler=scheduler,
                            search_alg=rt.get("search_alg"))
        self.engine.run()
        best = self.engine.get_best_trial()
        model = self.builder.build(best.config)
        model.restore(best.checkpoint)
        return TSPipeline(model)
