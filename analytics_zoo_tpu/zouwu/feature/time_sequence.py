"""Rolling-window + datetime feature engineering for time series.

Rebuild of ref ``pyzoo/zoo/zouwu/feature/time_sequence.py``
(TimeSequenceFeatureTransformer: fit_transform → rolling windows over a
datetime-indexed frame, derived datetime features, min-max scaling with
inverse transform for the target; ``:31``).

Output discipline: fixed-shape float32 arrays [n, lookback, F] / [n, horizon]
so the jitted train step sees static shapes."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

_DT_FEATURES = ("HOUR", "DAY", "DAYOFWEEK", "MONTH", "IS_WEEKEND")


class TimeSequenceFeatureTransformer:
    """fit_transform(df) → (x, y); transform(df) for val/test;
    ``unscale_y`` inverts target scaling for metric reporting."""

    def __init__(self, past_seq_len: int = 50, future_seq_len: int = 1,
                 dt_col: str = "datetime", target_col: str = "value",
                 extra_features_col: Optional[Sequence[str]] = None,
                 with_dt_features: bool = True, scale: bool = True,
                 selected_features: Optional[Sequence[str]] = None):
        self.past_seq_len = int(past_seq_len)
        self.future_seq_len = int(future_seq_len)
        self.dt_col = dt_col
        self.target_col = target_col
        self.extra_features_col = list(extra_features_col or [])
        self.with_dt_features = with_dt_features
        self.scale = scale
        # feature-selection axis (ref recipes sample `selected_features`
        # from all_available_features): names among the non-target features
        # to keep; the target itself is always feature 0
        self.selected_features = (None if selected_features is None
                                  else [str(s) for s in selected_features])
        if self.selected_features is not None:
            unknown = set(self.selected_features) - set(
                self.all_available_features)
            if unknown:
                raise ValueError(f"unknown selected_features {sorted(unknown)}"
                                 f"; available: {self.all_available_features}")
        self._mins: Optional[np.ndarray] = None
        self._maxs: Optional[np.ndarray] = None

    # ---------- feature matrix ----------

    def _dt_features(self, dt: pd.Series) -> np.ndarray:
        dt = pd.to_datetime(dt)
        cols = [
            dt.dt.hour.to_numpy(np.float32) / 23.0,
            (dt.dt.day.to_numpy(np.float32) - 1) / 30.0,
            dt.dt.dayofweek.to_numpy(np.float32) / 6.0,
            (dt.dt.month.to_numpy(np.float32) - 1) / 11.0,
            (dt.dt.dayofweek >= 5).to_numpy(np.float32),
        ]
        return np.stack(cols, axis=1)

    def _feature_matrix(self, df: pd.DataFrame) -> np.ndarray:
        feats = [df[self.target_col].to_numpy(np.float32)[:, None]]
        for c in self.extra_features_col:
            feats.append(df[c].to_numpy(np.float32)[:, None])
        if self.with_dt_features:
            feats.append(self._dt_features(df[self.dt_col]))
        mat = np.concatenate(feats, axis=1)
        if self.selected_features is not None:
            keep = set(self.selected_features)
            cols = [0] + [i for i, n in enumerate(
                self.all_available_features, start=1) if n in keep]
            mat = mat[:, cols]
        return mat

    @property
    def all_available_features(self) -> List[str]:
        """Every selectable (non-target) feature name — what a recipe's
        ``selected_features`` axis draws from (ref
        TimeSequenceFeatureTransformer.get_feature_list)."""
        names = list(self.extra_features_col)
        if self.with_dt_features:
            names += list(_DT_FEATURES)
        return names

    @property
    def feature_names(self) -> List[str]:
        if self.selected_features is not None:
            keep = set(self.selected_features)
            return [self.target_col] + [n for n in
                                        self.all_available_features
                                        if n in keep]
        return [self.target_col] + self.all_available_features

    @property
    def n_features(self) -> int:
        return len(self.feature_names)

    # ---------- scaling ----------

    def _fit_scale(self, mat: np.ndarray):
        self._mins = mat.min(0)
        self._maxs = mat.max(0)

    def _apply_scale(self, mat: np.ndarray) -> np.ndarray:
        span = np.where(self._maxs - self._mins == 0, 1.0,
                        self._maxs - self._mins)
        return (mat - self._mins) / span

    def unscale_y(self, y: np.ndarray) -> np.ndarray:
        """Invert target scaling (target is feature 0)."""
        if not self.scale or self._mins is None:
            return y
        return y * (self._maxs[0] - self._mins[0]) + self._mins[0]

    # ---------- rolling ----------

    def _roll(self, mat: np.ndarray, with_y: bool) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        p, f = self.past_seq_len, self.future_seq_len
        n = len(mat) - p - (f if with_y else 0) + 1
        if n <= 0:
            raise ValueError(
                f"need at least {p + (f if with_y else 0)} rows, have {len(mat)}")
        idx = np.arange(p)[None, :] + np.arange(n)[:, None]
        x = mat[idx]                                   # [n, p, F]
        y = None
        if with_y:
            yidx = p + np.arange(f)[None, :] + np.arange(n)[:, None]
            y = mat[yidx, 0]                           # [n, f] target only
        return x.astype(np.float32), None if y is None else y.astype(np.float32)

    # ---------- public API (ref time_sequence.py fit_transform/transform) --

    def fit_transform(self, df: pd.DataFrame) -> Tuple[np.ndarray, np.ndarray]:
        mat = self._feature_matrix(df)
        if self.scale:
            self._fit_scale(mat)
            mat = self._apply_scale(mat)
        return self._roll(mat, with_y=True)

    def transform(self, df: pd.DataFrame, with_y: bool = True):
        mat = self._feature_matrix(df)
        if self.scale:
            if self._mins is None:
                raise RuntimeError("call fit_transform first")
            mat = self._apply_scale(mat)
        x, y = self._roll(mat, with_y=with_y)
        return (x, y) if with_y else x

    def save(self, path: str):
        scaled = self._mins is not None
        np.savez(
            path,
            mins=self._mins if scaled else np.zeros(0, np.float32),
            maxs=self._maxs if scaled else np.zeros(0, np.float32),
            fitted_scale=scaled,
            past=self.past_seq_len, future=self.future_seq_len,
            dt_col=self.dt_col, target_col=self.target_col,
            extra_features_col=np.asarray(self.extra_features_col, dtype=object)
            if self.extra_features_col else np.zeros(0, dtype="U1"),
            with_dt_features=self.with_dt_features, scale=self.scale,
            has_selected=self.selected_features is not None,
            selected_features=np.asarray(self.selected_features, dtype=object)
            if self.selected_features else np.zeros(0, dtype="U1"))

    def restore(self, path: str):
        d = np.load(path if path.endswith(".npz") else path + ".npz",
                    allow_pickle=True)
        if bool(d["fitted_scale"]):
            self._mins, self._maxs = d["mins"], d["maxs"]
        else:
            self._mins = self._maxs = None
        self.past_seq_len = int(d["past"])
        self.future_seq_len = int(d["future"])
        self.dt_col = str(d["dt_col"])
        self.target_col = str(d["target_col"])
        self.extra_features_col = [str(c) for c in d["extra_features_col"]]
        self.with_dt_features = bool(d["with_dt_features"])
        self.scale = bool(d["scale"])
        if "has_selected" in d and bool(d["has_selected"]):
            self.selected_features = [str(c) for c in d["selected_features"]]
        else:
            self.selected_features = None
