from analytics_zoo_tpu.zouwu.feature.time_sequence import (  # noqa: F401
    TimeSequenceFeatureTransformer,
)
