"""Zouwu: scalable time-series analysis (TPU-native rebuild of ref
``pyzoo/zoo/zouwu/`` — forecasters, feature transform, anomaly detection,
AutoTS)."""
