"""ONNX import — parse + translate ONNX graphs to jax, no onnx package.

TPU-native replacement for the reference's ONNX loader
(ref ``pyzoo/zoo/pipeline/api/net/onnx/onnx_loader.py:141`` — converts
ONNX nodes to BigDL layers). The baked environment has no ``onnx``
package, so this module reads the ONNX **protobuf wire format directly**
(a ~100-line reader for the stable subset of onnx.proto: ModelProto /
GraphProto / NodeProto / TensorProto / AttributeProto) and translates the
node graph into a pure jax function, exactly like ``torch_net.torch_to_jax``
— the result jits, shards and differentiates like any native model.

Supported op set (the reference loader's vocabulary plus the common
export surface): MatMul, Gemm, Add/Sub/Mul/Div/Pow/Neg/Abs,
Relu/LeakyRelu/Elu/Sigmoid/Tanh/Softmax/Erf, Exp/Log/Sqrt/Clip,
Conv (2d), MaxPool, AveragePool, GlobalAveragePool, BatchNormalization
(inference), Flatten, Reshape, Transpose, Concat, Gather,
Squeeze/Unsqueeze, ReduceMean/ReduceSum, Pad (constant), Cast, Where,
Expand, Slice (attr and input forms), Identity, Constant. Unsupported
nodes raise with the op name; integer/bool initializers stay static so
shape operands remain concrete under jit.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

import numpy as np

# ------------------------------------------------------------------ protobuf
# wire-level decoding is shared with data/tfrecord.py: common/protowire.py

from analytics_zoo_tpu.common.protowire import (  # noqa: E402
    WIRE_I32, WIRE_I64, WIRE_LEN, WIRE_VARINT, iter_fields, read_varint,
)

_read_varint = read_varint


def _fields(buf: bytes) -> Dict[int, List[Tuple[int, Any]]]:
    """Parse one message into {field_number: [(wire_type, value), ...]}."""
    out: Dict[int, List[Tuple[int, Any]]] = {}
    for field, wt, v in iter_fields(buf):
        out.setdefault(field, []).append((wt, v))
    return out


def _ints(entries) -> List[int]:
    """Repeated int64 field: packed (one LEN record) or unpacked."""
    vals: List[int] = []
    for wt, v in entries:
        if wt == WIRE_VARINT:
            vals.append(v)
        else:
            i = 0
            while i < len(v):
                x, i = _read_varint(v, i)
                vals.append(x)
    return vals


def _signed(v: int) -> int:
    # protobuf int64 stores negatives as 2^64 complements
    return v - (1 << 64) if v >= (1 << 63) else v


# -------------------------------------------------------------- onnx schema

_DTYPES = {1: np.float32, 6: np.int32, 7: np.int64, 9: np.bool_,
           10: np.float16, 11: np.float64}
try:                                    # jax ships ml_dtypes
    import ml_dtypes as _mld
    _DTYPES[16] = np.dtype(_mld.bfloat16)
except ImportError:                     # pragma: no cover
    pass


def _tensor(buf: bytes) -> Tuple[str, np.ndarray]:
    f = _fields(buf)
    dims = _ints(f.get(1, []))
    dtype = _DTYPES[f[2][0][1]] if 2 in f else np.float32
    name = f[8][0][1].decode() if 8 in f else ""
    if 9 in f:  # raw_data
        arr = np.frombuffer(f[9][0][1], dtype=dtype)
    elif 4 in f:  # float_data (packed floats arrive as one LEN record)
        chunks = []
        for wt, v in f[4]:
            if wt == WIRE_I32:
                chunks.append(struct.unpack("<f", v)[0])
            else:
                chunks.extend(np.frombuffer(v, np.float32))
        arr = np.asarray(chunks, np.float32)
    elif 7 in f:  # int64_data
        arr = np.asarray([_signed(x) for x in _ints(f[7])], np.int64)
    elif 5 in f:  # int32_data
        arr = np.asarray([_signed(x) for x in _ints(f[5])], np.int32)
    else:
        arr = np.zeros(dims, dtype)
    return name, np.asarray(arr, dtype).reshape(dims)


def _attr(buf: bytes) -> Tuple[str, Any]:
    """One AttributeProto → (name, value). proto3 serializers OMIT
    default-valued scalars (i=0, f=0.0), so the ``type`` field (20) decides
    the kind and absence of the value field means the type's zero value."""
    f = _fields(buf)
    name = f[1][0][1].decode()
    atype = f[20][0][1] if 20 in f else None

    def floats():
        vals = []
        for wt, v in f.get(7, []):
            if wt == WIRE_I32:
                vals.append(struct.unpack("<f", v)[0])
            else:
                vals.extend(np.frombuffer(v, np.float32))
        return [float(x) for x in vals]

    if atype == 1 or (atype is None and 3 in f):     # FLOAT
        return name, (struct.unpack("<f", f[3][0][1])[0]
                      if 3 in f else 0.0)
    if atype == 2 or (atype is None and 4 in f):     # INT
        return name, _signed(f[4][0][1]) if 4 in f else 0
    if atype == 3 or (atype is None and 5 in f):     # STRING
        return name, (f[5][0][1].decode(errors="replace")
                      if 5 in f else "")
    if atype == 4 or (atype is None and 6 in f):     # TENSOR
        return name, _tensor(f[6][0][1])[1] if 6 in f else None
    if atype == 6 or (atype is None and 7 in f):     # FLOATS
        return name, floats()
    if atype == 7 or (atype is None and 8 in f):     # INTS
        return name, [_signed(x) for x in _ints(f.get(8, []))]
    return name, None


class _Node:
    __slots__ = ("op", "inputs", "outputs", "attrs")

    def __init__(self, buf: bytes):
        f = _fields(buf)
        self.inputs = [v.decode() for _, v in f.get(1, [])]
        self.outputs = [v.decode() for _, v in f.get(2, [])]
        self.op = f[4][0][1].decode() if 4 in f else ""
        self.attrs = dict(_attr(v) for _, v in f.get(5, []))


def parse_onnx(data: bytes):
    """ModelProto bytes → (nodes, initializers, input names, output names)."""
    model = _fields(data)
    if 7 not in model:
        raise ValueError("not an ONNX ModelProto (no graph field)")
    g = _fields(model[7][0][1])
    nodes = [_Node(v) for _, v in g.get(1, [])]
    inits = dict(_tensor(v) for _, v in g.get(5, []))

    def names(entries):
        out = []
        for _, v in entries:
            vf = _fields(v)
            out.append(vf[1][0][1].decode() if 1 in vf else "")
        return out

    graph_inputs = [n for n in names(g.get(11, [])) if n not in inits]
    graph_outputs = names(g.get(12, []))
    return nodes, inits, graph_inputs, graph_outputs


# ------------------------------------------------------------ op translation

def _same_pads(in_shape, kernel, strides, dilations, upper: bool):
    """auto_pad SAME_UPPER/SAME_LOWER → explicit per-dim (lo, hi) pads."""
    pads = []
    for size, k, s, d in zip(in_shape, kernel, strides, dilations):
        eff = (k - 1) * d + 1
        total = max((int(np.ceil(size / s)) - 1) * s + eff - size, 0)
        lo = total // 2 if upper else total - total // 2
        pads.append((lo, total - lo))
    return pads


def _conv_pads(a, in_spatial, kernel, strides, dilations):
    auto = a.get("auto_pad", "") or "NOTSET"
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        return _same_pads(in_spatial, kernel, strides, dilations,
                          auto == "SAME_UPPER")
    if auto == "VALID":
        return [(0, 0)] * len(kernel)
    if auto != "NOTSET":
        raise NotImplementedError(f"auto_pad {auto!r} not supported")
    p = a.get("pads") or [0] * (2 * len(kernel))
    half = len(p) // 2
    return [(p[i], p[i + half]) for i in range(half)]


def _pool(x, a, reducer, init):
    import jax.lax as lax
    k = tuple(a["kernel_shape"])
    s = tuple(a.get("strides") or k)
    pads = _conv_pads(a, x.shape[2:], k, s, (1,) * len(k))
    padding = [(0, 0), (0, 0)] + pads
    return lax.reduce_window(x, init, reducer, (1, 1) + k, (1, 1) + s,
                             padding), pads, k, s


def _apply_node(node: _Node, env: Dict[str, Any]):
    import jax
    import jax.numpy as jnp
    import jax.lax as lax

    a = node.attrs
    x = [env[i] if i else None for i in node.inputs]
    op = node.op
    if op == "MatMul":
        return x[0] @ x[1]
    if op == "Gemm":
        A = x[0].T if a.get("transA") else x[0]
        B = x[1].T if a.get("transB") else x[1]
        out = a.get("alpha", 1.0) * (A @ B)
        if len(x) > 2 and x[2] is not None:
            out = out + a.get("beta", 1.0) * x[2]
        return out
    if op in ("Add", "Sum"):
        out = x[0]
        for v in x[1:]:          # Sum is variadic in ONNX
            out = out + v
        return out
    if op == "Sub":
        return x[0] - x[1]
    if op == "Mul":
        return x[0] * x[1]
    if op == "Div":
        return x[0] / x[1]
    if op == "Relu":
        return jnp.maximum(x[0], 0)
    if op == "Sigmoid":
        return jax.nn.sigmoid(x[0])
    if op == "Tanh":
        return jnp.tanh(x[0])
    if op == "Erf":
        return jax.lax.erf(x[0])
    if op == "Softmax":
        return jax.nn.softmax(x[0], axis=a.get("axis", -1))
    if op == "Conv":
        if a.get("group", 1) not in (0, 1):
            raise NotImplementedError("grouped Conv not supported")
        kernel = a.get("kernel_shape") or list(x[1].shape[2:])
        strides = tuple(a.get("strides") or [1] * len(kernel))
        dil = tuple(a.get("dilations") or [1] * len(kernel))
        pad = _conv_pads(a, x[0].shape[2:], kernel, strides, dil)
        out = lax.conv_general_dilated(
            x[0], x[1], window_strides=strides, padding=pad,
            rhs_dilation=dil,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        if len(x) > 2 and x[2] is not None:
            out = out + x[2].reshape((1, -1) + (1,) * (out.ndim - 2))
        return out
    if op == "MaxPool":
        return _pool(x[0], a, lax.max, -np.inf)[0]
    if op == "AveragePool":
        summed, pads, k, s = _pool(x[0], a, lax.add, 0.0)
        if a.get("count_include_pad", 0) or not any(
                p != (0, 0) for p in pads):
            return summed / float(np.prod(k))
        # ONNX default count_include_pad=0: divide by the number of VALID
        # cells in each window, not the full kernel size
        ones = jnp.ones((1, 1) + x[0].shape[2:], x[0].dtype)
        counts = lax.reduce_window(ones, 0.0, lax.add, (1, 1) + tuple(k),
                                   (1, 1) + tuple(s),
                                   [(0, 0), (0, 0)] + pads)
        return summed / counts
    if op == "GlobalAveragePool":
        return x[0].mean(axis=tuple(range(2, x[0].ndim)), keepdims=True)
    if op == "BatchNormalization":
        scale, bias, mean, var = x[1], x[2], x[3], x[4]
        shape = (1, -1) + (1,) * (x[0].ndim - 2)
        inv = jax.lax.rsqrt(var.reshape(shape) + a.get("epsilon", 1e-5))
        return (x[0] - mean.reshape(shape)) * inv * scale.reshape(shape) \
            + bias.reshape(shape)
    if op == "Flatten":
        # ONNX Flatten is always 2-D: (prod(d[:axis]), prod(d[axis:]))
        ax = a.get("axis", 1)
        lead = int(np.prod(x[0].shape[:ax])) if ax > 0 else 1
        return x[0].reshape(lead, -1)
    if op == "Reshape":
        shape = [int(v) for v in np.asarray(x[1])]
        shape = [x[0].shape[i] if s == 0 else s for i, s in enumerate(shape)]
        return x[0].reshape(shape)
    if op == "Transpose":
        perm = a.get("perm")
        return jnp.transpose(x[0], perm)
    if op == "Concat":
        return jnp.concatenate(x, axis=a.get("axis", 0))
    if op == "Gather":
        return jnp.take(x[0], jnp.asarray(x[1]).astype(jnp.int32),
                        axis=a.get("axis", 0))
    if op == "Squeeze":
        axes = a.get("axes") or ([int(v) for v in np.asarray(x[1])]
                                 if len(x) > 1 else None)
        return jnp.squeeze(x[0], axis=tuple(axes) if axes else None)
    if op == "Unsqueeze":
        axes = a.get("axes") or [int(v) for v in np.asarray(x[1])]
        out = x[0]
        for ax in sorted(axes):
            out = jnp.expand_dims(out, ax)
        return out
    if op == "Identity":
        return x[0]
    if op == "Constant":
        return jnp.asarray(a["value"])
    if op == "LeakyRelu":
        alpha = a.get("alpha", 0.01)
        return jnp.where(x[0] >= 0, x[0], alpha * x[0])
    if op == "Elu":
        alpha = a.get("alpha", 1.0)
        return jnp.where(x[0] >= 0, x[0], alpha * (jnp.exp(x[0]) - 1.0))
    if op == "Clip":
        # opset<11: attrs; opset>=11: optional min/max inputs
        lo = x[1] if len(x) > 1 and x[1] is not None else a.get("min")
        hi = x[2] if len(x) > 2 and x[2] is not None else a.get("max")
        return jnp.clip(x[0], lo, hi)
    if op == "Exp":
        return jnp.exp(x[0])
    if op == "Log":
        return jnp.log(x[0])
    if op == "Sqrt":
        return jnp.sqrt(x[0])
    if op == "Pow":
        return x[0] ** x[1]
    if op == "Neg":
        return -x[0]
    if op == "Abs":
        return jnp.abs(x[0])
    if op == "ReduceMean":
        axes = a.get("axes") or ([int(v) for v in np.asarray(x[1])]
                                 if len(x) > 1 and x[1] is not None
                                 else None)
        keep = bool(a.get("keepdims", 1))
        return x[0].mean(axis=tuple(axes) if axes else None, keepdims=keep)
    if op == "ReduceSum":
        axes = a.get("axes") or ([int(v) for v in np.asarray(x[1])]
                                 if len(x) > 1 and x[1] is not None
                                 else None)
        if not axes and a.get("noop_with_empty_axes"):
            return x[0]                 # opset-13: empty axes = identity
        keep = bool(a.get("keepdims", 1))
        return x[0].sum(axis=tuple(axes) if axes else None, keepdims=keep)
    if op == "Pad":
        mode = a.get("mode", b"constant")
        mode = mode.decode() if isinstance(mode, bytes) else mode
        if mode != "constant":
            raise NotImplementedError(f"Pad mode {mode!r} not supported")
        pads = a.get("pads") or [int(v) for v in np.asarray(x[1])]
        # keep the value traced — a float initializer lands in params
        value = (x[2] if len(x) > 2 and x[2] is not None
                 else a.get("value", 0.0))
        n = x[0].ndim
        widths = [(int(pads[i]), int(pads[i + n])) for i in range(n)]
        return jnp.pad(x[0], widths, constant_values=value)
    if op == "Cast":
        to = int(a["to"])
        if to not in _DTYPES:
            raise NotImplementedError(f"Cast to dtype code {to} "
                                      "not supported")
        return x[0].astype(_DTYPES[to])
    if op == "Where":
        return jnp.where(x[0].astype(bool), x[1], x[2])
    if op == "Expand":
        shape = [int(v) for v in np.asarray(x[1])]
        return jnp.broadcast_to(x[0], np.broadcast_shapes(x[0].shape,
                                                          tuple(shape)))
    if op == "Slice":
        # opset>=10: starts/ends[/axes/steps] inputs; opset<10: attrs
        if len(x) == 1:
            starts, ends = list(a["starts"]), list(a["ends"])
            axes = list(a.get("axes") or range(len(starts)))
            steps = [1] * len(starts)
        else:
            starts = [int(v) for v in np.asarray(x[1])]
            ends = [int(v) for v in np.asarray(x[2])]
            axes = ([int(v) for v in np.asarray(x[3])]
                    if len(x) > 3 and x[3] is not None
                    else list(range(len(starts))))
            steps = ([int(v) for v in np.asarray(x[4])]
                     if len(x) > 4 and x[4] is not None
                     else [1] * len(starts))
        idx = [slice(None)] * x[0].ndim
        for ax, st, en, sp in zip(axes, starts, ends, steps):
            idx[ax] = slice(st, en, sp)
        return x[0][tuple(idx)]
    raise NotImplementedError(f"ONNX op {op!r} has no TPU translation")


def onnx_to_jax(data: bytes):
    """ONNX ModelProto bytes → ``(apply_fn, {"params": initializers})``
    where ``apply_fn(variables, *inputs)`` is a pure jax function."""
    nodes, inits, graph_inputs, graph_outputs = parse_onnx(data)
    # integer/bool initializers are shape/index operands (Reshape, Slice,
    # Pad, Expand, Gather indices…) — they must stay STATIC so the
    # consuming op sees concrete values under jit; float initializers are
    # the trainable params
    params: Dict[str, Any] = {}
    static: Dict[str, Any] = {}
    for k, v in inits.items():
        arr = np.asarray(v)
        if np.issubdtype(arr.dtype, np.integer) or arr.dtype == np.bool_:
            static[k] = arr
        else:
            params[k] = arr

    def apply_fn(variables, *inputs):
        import jax.numpy as jnp
        env: Dict[str, Any] = dict(static)
        env.update({k: jnp.asarray(v)
                    for k, v in variables["params"].items()})
        if len(inputs) != len(graph_inputs):
            raise ValueError(f"model takes {len(graph_inputs)} inputs "
                             f"({graph_inputs}), got {len(inputs)}")
        env.update(dict(zip(graph_inputs, inputs)))
        for node in nodes:
            result = _apply_node(node, env)
            outs = result if isinstance(result, tuple) else (result,)
            for name, val in zip(node.outputs, outs):
                env[name] = val
        outs = [env[o] for o in graph_outputs]
        return outs[0] if len(outs) == 1 else tuple(outs)

    return apply_fn, {"params": params}


class ONNXNet:
    """Inference wrapper over a translated ONNX graph (mirrors TorchNet)."""

    def __init__(self, path_or_bytes, jit: bool = True):
        import jax
        data = path_or_bytes
        if isinstance(data, str):
            with open(data, "rb") as fh:
                data = fh.read()
        self.apply_fn, self.variables = onnx_to_jax(data)
        self._call = jax.jit(self.apply_fn) if jit else self.apply_fn

    @property
    def params(self):
        return self.variables["params"]

    def predict(self, *inputs):
        import jax
        arrs = tuple(np.asarray(a) for a in inputs)
        out = jax.device_get(self._call(self.variables, *arrs))
        if isinstance(out, tuple):  # multi-output graph
            return tuple(np.asarray(o) for o in out)
        return np.asarray(out)

    __call__ = predict
