from analytics_zoo_tpu.net.net import Net
from analytics_zoo_tpu.net.torch_net import TorchNet, torch_to_jax

__all__ = ["Net", "TorchNet", "torch_to_jax"]
