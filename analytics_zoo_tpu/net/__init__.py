from analytics_zoo_tpu.net.net import Net
from analytics_zoo_tpu.net.onnx_net import ONNXNet, onnx_to_jax
from analytics_zoo_tpu.net.torch_net import TorchNet, torch_to_jax

__all__ = ["Net", "ONNXNet", "TorchNet", "onnx_to_jax", "torch_to_jax"]
