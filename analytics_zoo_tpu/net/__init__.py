from analytics_zoo_tpu.net.net import Net
from analytics_zoo_tpu.net.onnx_net import ONNXNet, onnx_to_jax
from analytics_zoo_tpu.net.openvino_net import OpenVINONet, openvino_to_jax
from analytics_zoo_tpu.net.torch_net import TorchNet, torch_to_jax

__all__ = ["Net", "ONNXNet", "OpenVINONet", "TorchNet", "onnx_to_jax",
           "openvino_to_jax", "torch_to_jax"]
