"""TorchNet — run PyTorch modules on the TPU by *translation*, not embedding.

The reference executes torch modules inside each executor JVM through Jep
(embedded CPython + libtorch): pickled module bytes are broadcast, weights
are flattened into ONE JVM tensor pushed via ``vector_to_parameters`` before
every forward, and forward/backward are exec'd Python strings
(zoo/.../pipeline/api/net/TorchModel.scala:34-260, TorchNet.scala). That
design exists because the JVM cannot run torch math itself.

On TPU the idiomatic move is to *compile the model out of torch entirely*:
``torch_to_jax`` symbolically traces the module with ``torch.fx``, translates
the graph node-by-node into a pure jax function, and converts the state_dict
into a jax parameter pytree. The result jits, shards, and differentiates
like any native model — so ``Estimator.from_torch`` trains it with the same
pjit train step (no Jep, no flat-tensor shuttling; XLA owns the layout).

Supported surface: the torch layer/function vocabulary used across the
reference's torch examples and tests (Linear, Conv1d/2d, ConvTranspose2d,
BatchNorm1d/2d, GroupNorm, LayerNorm, Embedding, LSTM, GRU,
MultiheadAttention, TransformerEncoder(Layer), Dropout,
ReLU/GELU/ELU/SiLU/LeakyReLU/Tanh/Sigmoid/
Softmax/LogSoftmax/Softplus/Hardtanh, Max/AvgPool2d, AdaptiveAvgPool2d(1),
Flatten, Sequential + residual adds, cat, view/reshape/permute/transpose/
mean/sum, matmul). Unsupported nodes raise with the node name so the gap
is explicit.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import numpy as np


def _np(t):
    # copy: .numpy() returns a VIEW of the torch storage — without it the
    # translated params would alias live torch tensors (mutated by torch
    # optimizers / BN updates) and keep them alive
    return np.array(t.detach().cpu().numpy(), copy=True)


def _conv_general(x, w, b, stride, padding, dims):
    import jax.lax as lax
    import jax.numpy as jnp
    if isinstance(stride, int):
        stride = (stride,) * dims
    if isinstance(padding, str):
        if padding.lower() not in ("same", "valid"):
            raise NotImplementedError(f"conv padding {padding!r} unsupported")
        pad = padding.upper()
    else:
        if isinstance(padding, int):
            padding = (padding,) * dims
        pad = [(p, p) for p in padding]
    spec = ("NCH", "OIH", "NCH") if dims == 1 else ("NCHW", "OIHW", "NCHW")
    out = lax.conv_general_dilated(
        x, w, window_strides=tuple(stride), padding=pad,
        dimension_numbers=spec)
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * dims)
    return out


def _pool_args(mod):
    k, s = mod.kernel_size, mod.stride or mod.kernel_size
    k = (k, k) if isinstance(k, int) else tuple(k)
    s = (s, s) if isinstance(s, int) else tuple(s)
    p = mod.padding
    p = (p, p) if isinstance(p, int) else tuple(p)
    if getattr(mod, "ceil_mode", False):
        raise NotImplementedError("pooling ceil_mode=True not supported")
    return k, s, p


class _NoRule(NotImplementedError):
    """No translation rule exists for this module TYPE (distinct from an
    unsupported CONFIG of a known type, which raises plain
    NotImplementedError and must propagate)."""


def _sub_translate(sub, what: str):
    """Translate a composite rule's sub-component. A _NoRule here must NOT
    escape as _NoRule (torch_to_jax would misread it as 'no rule for the
    TOP module' and fall into fx tracing); stateful/ctx-needing
    sub-components are rejected clearly at translation time rather than
    crashing at first forward."""
    try:
        p, b, fn = _ModuleRule.translate(sub)
    except _NoRule as e:
        raise NotImplementedError(
            f"{what}: {type(sub).__name__} has no translation rule") from e
    if b or getattr(fn, "_needs_ctx", False):
        raise NotImplementedError(
            f"{what}: {type(sub).__name__} with frozen state or train-time "
            "randomness is not supported inside a composite rule")
    return p, b, fn


class _ModuleRule:
    """Translate one torch layer instance into
    ``(trainable params, frozen buffers, jax fn)``; the executor calls
    ``fn(merged_params_and_buffers, x)``. Putting running statistics in
    buffers (not params) keeps Estimator.from_torch from gradient-updating
    them — they ride the estimator's model_state instead."""

    @staticmethod
    def translate(mod) -> Tuple[Dict[str, np.ndarray],
                                Dict[str, np.ndarray], Callable]:
        import torch.nn as tnn
        import jax.numpy as jnp
        import jax

        if isinstance(mod, tnn.Linear):
            p = {"kernel": _np(mod.weight).T}
            if mod.bias is not None:
                p["bias"] = _np(mod.bias)
            return p, {}, lambda pr, x: x @ pr["kernel"] + pr.get("bias", 0.0)
        if isinstance(mod, (tnn.Conv1d, tnn.Conv2d)):
            dims = 1 if isinstance(mod, tnn.Conv1d) else 2
            if any(d != 1 for d in np.atleast_1d(mod.dilation)) or mod.groups != 1:
                raise NotImplementedError("dilated/grouped conv not supported")
            p = {"kernel": _np(mod.weight)}
            if mod.bias is not None:
                p["bias"] = _np(mod.bias)
            stride, padding = mod.stride, mod.padding
            return p, {}, lambda pr, x: _conv_general(
                x, pr["kernel"], pr.get("bias"), stride, padding, dims)
        if isinstance(mod, tnn.ConvTranspose2d):
            if any(d != 1 for d in np.atleast_1d(mod.dilation)) \
                    or mod.groups != 1 \
                    or any(p != 0 for p in np.atleast_1d(mod.output_padding)):
                raise NotImplementedError(
                    "dilated/grouped/output-padded ConvTranspose2d "
                    "not supported")
            p = {"kernel": _np(mod.weight)}        # [in, out, kh, kw]
            if mod.bias is not None:
                p["bias"] = _np(mod.bias)
            stride = (mod.stride if isinstance(mod.stride, tuple)
                      else (mod.stride,) * 2)
            pad = (mod.padding if isinstance(mod.padding, tuple)
                   else (mod.padding,) * 2)

            def deconv(pr, x):
                import jax.lax as lax
                k = pr["kernel"]
                kh, kw = k.shape[2], k.shape[3]
                # torch's transposed conv correlates with the FLIPPED
                # kernel; padding p maps to (k - 1 - p) on the dilated grid
                out = lax.conv_general_dilated(
                    x, jnp.flip(k, (2, 3)).transpose(1, 0, 2, 3),
                    window_strides=(1, 1),
                    padding=[(kh - 1 - pad[0], kh - 1 - pad[0]),
                             (kw - 1 - pad[1], kw - 1 - pad[1])],
                    lhs_dilation=stride,
                    dimension_numbers=("NCHW", "OIHW", "NCHW"))
                if "bias" in pr:
                    out = out + pr["bias"].reshape(1, -1, 1, 1)
                return out
            return p, {}, deconv
        if isinstance(mod, tnn.GroupNorm):
            if mod.weight is None:  # affine=False
                c = mod.num_channels
                p = {"scale": np.ones(c, np.float32),
                     "bias": np.zeros(c, np.float32)}
            else:
                p = {"scale": _np(mod.weight), "bias": _np(mod.bias)}
            groups, eps = mod.num_groups, mod.eps

            def gn(pr, x):
                b, c = x.shape[0], x.shape[1]
                g = x.reshape((b, groups, c // groups) + x.shape[2:])
                axes = tuple(range(2, g.ndim))
                mu = g.mean(axes, keepdims=True)
                var = ((g - mu) ** 2).mean(axes, keepdims=True)
                g = (g - mu) * jax.lax.rsqrt(var + eps)
                shape = (1, c) + (1,) * (x.ndim - 2)
                return g.reshape(x.shape) * pr["scale"].reshape(shape) \
                    + pr["bias"].reshape(shape)
            return p, {}, gn
        if isinstance(mod, (tnn.BatchNorm1d, tnn.BatchNorm2d)):
            # train-mode forward normalizes by BATCH statistics (matching
            # torch .train() semantics for loss/gradients); eval uses the
            # translated running statistics, which stay frozen — there is no
            # running-stat update on the jax side (warned in torch_to_jax)
            p = {"scale": _np(mod.weight), "bias": _np(mod.bias)}
            buf = {"mean": _np(mod.running_mean), "var": _np(mod.running_var)}
            eps = mod.eps

            def bn(pr, x):
                shape = (1, -1) + (1,) * (x.ndim - 2)
                if pr.get("__train__", False):
                    axes = (0,) + tuple(range(2, x.ndim))
                    mean = x.mean(axes).reshape(shape)
                    var = ((x - mean) ** 2).mean(axes).reshape(shape)
                else:
                    mean = pr["mean"].reshape(shape)
                    var = pr["var"].reshape(shape)
                inv = jax.lax.rsqrt(var + eps)
                return (x - mean) * inv * pr["scale"].reshape(shape) \
                    + pr["bias"].reshape(shape)
            bn._needs_ctx = True
            return p, buf, bn
        if isinstance(mod, tnn.LayerNorm):
            p = {"scale": _np(mod.weight), "bias": _np(mod.bias)}
            eps = mod.eps

            def ln(pr, x):
                mu = x.mean(-1, keepdims=True)
                var = ((x - mu) ** 2).mean(-1, keepdims=True)
                return (x - mu) * jax.lax.rsqrt(var + eps) * pr["scale"] \
                    + pr["bias"]
            return p, {}, ln
        if isinstance(mod, tnn.Embedding):
            p = {"embedding": _np(mod.weight)}
            return p, {}, lambda pr, x: pr["embedding"][x.astype(jnp.int32)]
        if isinstance(mod, tnn.MultiheadAttention):
            if mod.in_proj_weight is None:
                raise NotImplementedError(
                    "MultiheadAttention with distinct q/k/v embed dims "
                    "not supported")
            if mod.bias_k is not None or mod.add_zero_attn:
                raise NotImplementedError(
                    "add_bias_kv / add_zero_attn not supported")
            if mod.dropout:
                import logging
                logging.getLogger(__name__).warning(
                    "translated MultiheadAttention: attention dropout "
                    "(p=%.2f) is inert — eval semantics in both modes",
                    mod.dropout)
            E, H = mod.embed_dim, mod.num_heads
            mha_batch_first = mod.batch_first
            p = {"in_w": _np(mod.in_proj_weight),      # (3E, E)
                 "out_w": _np(mod.out_proj.weight)}    # (E, E)
            if mod.in_proj_bias is not None:
                p["in_b"] = _np(mod.in_proj_bias)
            if mod.out_proj.bias is not None:
                p["out_b"] = _np(mod.out_proj.bias)

            def mha(pr, q, k, v, key_padding_mask=None, need_weights=True,
                    attn_mask=None, average_attn_weights=True,
                    is_causal=False):
                if key_padding_mask is not None or attn_mask is not None \
                        or is_causal:
                    raise NotImplementedError(
                        "attention masks are not supported in the "
                        "translated MultiheadAttention")
                if q.ndim != 3:
                    raise NotImplementedError(
                        "translated MultiheadAttention needs batched "
                        "(B, T, E) / (T, B, E) input")
                if not mha_batch_first:                # (T,B,E) → (B,T,E)
                    q, k, v = (jnp.swapaxes(t, 0, 1) for t in (q, k, v))
                wq, wk, wv = jnp.split(pr["in_w"], 3, axis=0)
                bq = bk = bv = 0.0
                if "in_b" in pr:
                    bq, bk, bv = jnp.split(pr["in_b"], 3, axis=0)
                d = E // H

                def heads(x, w, b):
                    y = x @ w.T + b
                    return y.reshape(y.shape[0], y.shape[1], H, d)

                qh, kh, vh = heads(q, wq, bq), heads(k, wk, bk), \
                    heads(v, wv, bv)
                from analytics_zoo_tpu.ops.attention import (
                    _reference_attention, dot_product_attention,
                )
                if need_weights:
                    # probs must be materialized — shared reference chain
                    out, attn = _reference_attention(qh, kh, vh,
                                                     return_probs=True)
                    w_out = attn.mean(1) if average_attn_weights else attn
                else:
                    # shared attention core (pallas flash kernel on TPU
                    # when shapes are tile-aligned)
                    out = dot_product_attention(qh, kh, vh)
                    w_out = None
                out = out.reshape(out.shape[0], out.shape[1], E)
                out = out @ pr["out_w"].T + pr.get("out_b", 0.0)
                if not mha_batch_first:
                    out = jnp.swapaxes(out, 0, 1)
                return out, w_out
            return p, {}, mha
        if isinstance(mod, tnn.TransformerEncoderLayer):
            # compose from the already-translated pieces (fx treats the
            # whole layer as a leaf, so the rule recurses explicitly)
            pa, _, attn_fn = _sub_translate(mod.self_attn, "self_attn")
            p1, _, lin1_fn = _sub_translate(mod.linear1, "linear1")
            p2, _, lin2_fn = _sub_translate(mod.linear2, "linear2")
            pn1, _, norm1_fn = _sub_translate(mod.norm1, "norm1")
            pn2, _, norm2_fn = _sub_translate(mod.norm2, "norm2")
            norm_first = mod.norm_first
            import torch
            import torch.nn.functional as tF
            act_map = {tF.relu: jax.nn.relu, tF.gelu: jax.nn.gelu,
                       torch.relu: jax.nn.relu}
            act = act_map.get(mod.activation)
            if act is None and isinstance(mod.activation, tnn.Module):
                _, _, act_leaf = _sub_translate(mod.activation, "activation")
                act = lambda x: act_leaf({}, x)  # noqa: E731
            if act is None:
                raise NotImplementedError(
                    f"TransformerEncoderLayer activation "
                    f"{mod.activation} not supported")
            if mod.dropout1.p or mod.dropout.p:
                import logging
                logging.getLogger(__name__).warning(
                    "translated TransformerEncoderLayer: dropout is inert "
                    "— eval semantics in both modes")
            p = {"attn": pa, "lin1": p1, "lin2": p2,
                 "norm1": pn1, "norm2": pn2}

            def tel(pr, x, src_mask=None, src_key_padding_mask=None,
                    is_causal=False):
                if src_mask is not None or src_key_padding_mask is not None \
                        or is_causal:
                    raise NotImplementedError(
                        "masks are not supported in the translated "
                        "TransformerEncoderLayer")

                def sa(y):
                    return attn_fn(pr["attn"], y, y, y,
                                   need_weights=False)[0]

                def ff(y):
                    return lin2_fn(pr["lin2"],
                                   act(lin1_fn(pr["lin1"], y)))

                if norm_first:
                    x = x + sa(norm1_fn(pr["norm1"], x))
                    return x + ff(norm2_fn(pr["norm2"], x))
                x = norm1_fn(pr["norm1"], x + sa(x))
                return norm2_fn(pr["norm2"], x + ff(x))
            return p, {}, tel
        if isinstance(mod, tnn.TransformerEncoder):
            stack = [_sub_translate(layer, f"layers[{i}]")
                     for i, layer in enumerate(mod.layers)]
            final = None
            p = {f"layer{i}": lp for i, (lp, _, _) in enumerate(stack)}
            if mod.norm is not None:
                pn, _, final = _sub_translate(mod.norm, "norm")
                p["final_norm"] = pn
            layer_fns = [fn for _, _, fn in stack]
            final_fn = final

            def tenc(pr, x, mask=None, src_key_padding_mask=None,
                     is_causal=None):
                if mask is not None or src_key_padding_mask is not None \
                        or is_causal:
                    raise NotImplementedError(
                        "masks are not supported in the translated "
                        "TransformerEncoder")
                for i, fn in enumerate(layer_fns):
                    x = fn(pr[f"layer{i}"], x)
                if final_fn is not None:
                    x = final_fn(pr["final_norm"], x)
                return x
            return p, {}, tenc
        if isinstance(mod, (tnn.LSTM, tnn.GRU)):
            if mod.bidirectional:
                raise NotImplementedError("bidirectional RNNs not supported")
            if mod.dropout and mod.num_layers > 1:
                # single-layer dropout is a documented torch no-op
                raise NotImplementedError(
                    "inter-layer RNN dropout not supported; set dropout=0")
            if getattr(mod, "proj_size", 0):
                raise NotImplementedError("LSTM proj_size not supported")
            n_layers = mod.num_layers
            batch_first = mod.batch_first
            is_lstm = isinstance(mod, tnn.LSTM)
            p = {}
            for layer in range(n_layers):
                p[f"wi{layer}"] = _np(getattr(mod, f"weight_ih_l{layer}"))
                p[f"wh{layer}"] = _np(getattr(mod, f"weight_hh_l{layer}"))
                if mod.bias:
                    p[f"bi{layer}"] = _np(getattr(mod, f"bias_ih_l{layer}"))
                    p[f"bh{layer}"] = _np(getattr(mod, f"bias_hh_l{layer}"))
            hidden = mod.hidden_size

            def rnn(pr, x, *rest, hx=None):
                import jax.lax as lax
                if rest or hx is not None:
                    raise NotImplementedError(
                        "explicit initial RNN state is not supported — "
                        "the translated RNN always starts from zeros")
                unbatched = x.ndim == 2               # torch (T, I) input
                if unbatched:
                    x = x[:, None]                    # → (T, 1, I)
                elif batch_first:                     # (B,T,I) → (T,B,I)
                    x = jnp.swapaxes(x, 0, 1)
                T, B = x.shape[0], x.shape[1]
                finals_h, finals_c = [], []
                for layer in range(n_layers):
                    wi, wh = pr[f"wi{layer}"], pr[f"wh{layer}"]
                    bi = pr.get(f"bi{layer}", 0.0)
                    bh = pr.get(f"bh{layer}", 0.0)
                    h0 = jnp.zeros((B, hidden), x.dtype)

                    if is_lstm:
                        def step(carry, x_t, wi=wi, wh=wh, bi=bi, bh=bh):
                            h, c = carry
                            z = x_t @ wi.T + h @ wh.T + bi + bh
                            i, f, g, o = jnp.split(z, 4, axis=-1)
                            c = jax.nn.sigmoid(f) * c + \
                                jax.nn.sigmoid(i) * jnp.tanh(g)
                            h = jax.nn.sigmoid(o) * jnp.tanh(c)
                            return (h, c), h
                        (hT, cT), x = lax.scan(step, (h0, h0), x)
                        finals_c.append(cT)
                    else:
                        def step(h, x_t, wi=wi, wh=wh, bi=bi, bh=bh):
                            gi = x_t @ wi.T + bi
                            gh = h @ wh.T + bh
                            ir, iz, in_ = jnp.split(gi, 3, axis=-1)
                            hr, hz, hn = jnp.split(gh, 3, axis=-1)
                            r = jax.nn.sigmoid(ir + hr)
                            z = jax.nn.sigmoid(iz + hz)
                            n = jnp.tanh(in_ + r * hn)   # torch's gate form
                            h = (1.0 - z) * n + z * h
                            return h, h
                        hT, x = lax.scan(step, h0, x)
                    finals_h.append(hT)
                if unbatched:
                    out = x[:, 0]                     # (T, H)
                    h_n = jnp.stack(finals_h)[:, 0]   # (layers, H)
                    if is_lstm:
                        return out, (h_n, jnp.stack(finals_c)[:, 0])
                    return out, h_n
                out = jnp.swapaxes(x, 0, 1) if batch_first else x
                h_n = jnp.stack(finals_h)             # (layers, B, H)
                if is_lstm:
                    return out, (h_n, jnp.stack(finals_c))
                return out, h_n
            return p, {}, rnn
        if isinstance(mod, tnn.Identity):
            return {}, {}, lambda pr, x: x
        if isinstance(mod, tnn.Dropout):
            rate = float(mod.p)
            if rate <= 0.0:
                return {}, {}, lambda pr, x: x

            def do(pr, x):
                # real inverted dropout in train mode; identity at eval.
                # __train__ is a static python bool, __rng__ a traced key
                # injected per-instance by apply_fn.
                if not pr.get("__train__", False):
                    return x
                if pr.get("__rng__") is None:
                    raise ValueError(
                        "train-mode dropout needs an rng; pass rng= to "
                        "apply_fn (Estimator.from_torch does this)")
                keep = 1.0 - rate
                mask = jax.random.bernoulli(pr["__rng__"], keep, x.shape)
                return jnp.where(mask, x / keep, jnp.zeros_like(x))
            do._needs_ctx = True
            return {}, {}, do
        if isinstance(mod, tnn.Flatten):
            start = mod.start_dim
            return {}, {}, lambda pr, x: x.reshape(x.shape[:start] + (-1,))
        if isinstance(mod, tnn.ReLU):
            return {}, {}, lambda pr, x: jnp.maximum(x, 0)
        if isinstance(mod, tnn.LeakyReLU):
            slope = mod.negative_slope
            return {}, {}, lambda pr, x: jnp.where(x >= 0, x, slope * x)
        if isinstance(mod, tnn.ELU):
            alpha = mod.alpha
            return {}, {}, lambda pr, x: jnp.where(
                x >= 0, x, alpha * (jnp.exp(x) - 1.0))
        if isinstance(mod, tnn.Softplus):
            return {}, {}, lambda pr, x: jax.nn.softplus(x)
        if isinstance(mod, tnn.Hardtanh):
            lo, hi = mod.min_val, mod.max_val
            return {}, {}, lambda pr, x: jnp.clip(x, lo, hi)
        if isinstance(mod, tnn.SiLU):
            return {}, {}, lambda pr, x: jax.nn.silu(x)
        if isinstance(mod, tnn.GELU):
            return {}, {}, lambda pr, x: jax.nn.gelu(x)
        if isinstance(mod, tnn.Tanh):
            return {}, {}, lambda pr, x: jnp.tanh(x)
        if isinstance(mod, tnn.Sigmoid):
            return {}, {}, lambda pr, x: jax.nn.sigmoid(x)
        if isinstance(mod, tnn.Softmax):
            dim = mod.dim if mod.dim is not None else -1
            return {}, {}, lambda pr, x: jax.nn.softmax(x, axis=dim)
        if isinstance(mod, tnn.LogSoftmax):
            dim = mod.dim if mod.dim is not None else -1
            return {}, {}, lambda pr, x: jax.nn.log_softmax(x, axis=dim)
        if isinstance(mod, tnn.MaxPool2d):
            k, s, p = _pool_args(mod)

            def mp(pr, x):
                import jax.lax as lax
                return lax.reduce_window(
                    x, -jnp.inf, lax.max, (1, 1) + k, (1, 1) + s,
                    [(0, 0), (0, 0)] + [(a, a) for a in p])
            return {}, {}, mp
        if isinstance(mod, tnn.AvgPool2d):
            k, s, p = _pool_args(mod)
            if not mod.count_include_pad:
                raise NotImplementedError(
                    "AvgPool2d count_include_pad=False not supported")

            def ap(pr, x):
                import jax.lax as lax
                summed = lax.reduce_window(
                    x, 0.0, lax.add, (1, 1) + k, (1, 1) + s,
                    [(0, 0), (0, 0)] + [(a, a) for a in p])
                return summed / (k[0] * k[1])
            return {}, {}, ap
        if isinstance(mod, tnn.AdaptiveAvgPool2d):
            size = mod.output_size
            if size not in (1, (1, 1)):
                raise NotImplementedError("AdaptiveAvgPool2d only to (1,1)")
            return {}, {}, lambda pr, x: x.mean(axis=(2, 3), keepdims=True)
        raise _NoRule(
            f"torch module {type(mod).__name__} has no TPU translation rule")


def torch_to_jax(module) -> Tuple[Callable, Dict[str, Any]]:
    """Translate ``module`` (torch.nn.Module) →
    ``(apply_fn, {"params": ..., "buffers": ...})`` where
    ``apply_fn(variables, *inputs, train=False, rng=None)`` is a pure jax
    function. ``params`` are the trainable leaves; ``buffers`` (BN running
    stats, plain-tensor attributes) are frozen state. With ``train=True``
    dropout applies for real (inverted, needs ``rng``) and BatchNorm
    normalizes by batch statistics. Uses torch.fx symbolic tracing, so
    data-dependent Python control flow in the module is rejected by fx
    itself — the same restriction XLA imposes. All torch-side tensors are
    copied out during translation; nothing retains the torch module."""
    import torch
    import torch.fx as fx
    import operator
    import jax
    import jax.numpy as jnp

    module = module.eval()
    # A bare leaf module (e.g. nn.LSTM passed directly) must not be fx-
    # traced — fx only treats torch.nn classes as leaves when they are
    # SUBmodules; tracing into an RNN's forward hits data-dependent
    # control flow. Translate it directly instead. "has no TPU translation
    # rule" falls through to the fx path for containers/custom modules;
    # any other NotImplementedError (unsupported config of a known leaf)
    # propagates.
    try:
        p, b, fn = _ModuleRule.translate(module)
        is_leaf = True
    except _NoRule:
        is_leaf = False
    if is_leaf:
        variables = {"params": {"root": p}, "buffers": {"root": b}}

        def leaf_apply(variables, *inputs, train=False, rng=None, **kw):
            merged = dict(variables["buffers"].get("root", {}))
            merged.update(variables["params"].get("root", {}))
            if getattr(fn, "_needs_ctx", False):
                merged["__train__"] = train
                merged["__rng__"] = rng
            return fn(merged, *inputs, **kw)

        return leaf_apply, variables

    graph_module = fx.symbolic_trace(module)
    modules = dict(graph_module.named_modules())

    params: Dict[str, Any] = {}
    buffers: Dict[str, Any] = {}
    fns: Dict[str, Callable] = {}
    # graph NODE name -> rng index: keyed per call site, not per module, so
    # a Dropout instance reused at two places in forward() draws two
    # independent masks (matching torch's fresh randomness per call)
    ctx_nodes: Dict[str, int] = {}
    has_bn = False
    for node in graph_module.graph.nodes:
        if node.op == "call_module":
            mod = modules[node.target]
            has_bn = has_bn or isinstance(
                mod, (torch.nn.BatchNorm1d, torch.nn.BatchNorm2d))
            p, buf, fn = _ModuleRule.translate(mod)
            # dots, not slashes: estimator param paths join dict keys with
            # "/" so a slash inside one key would split the path
            key = node.target
            if p:
                params[key] = p
            if buf:
                buffers[key] = buf
            if getattr(fn, "_needs_ctx", False):
                ctx_nodes[node.name] = len(ctx_nodes)
            fns[node.name] = (key, fn)
        elif node.op == "get_attr":
            # nn.Parameter used directly in forward → trainable; any other
            # tensor attribute → frozen buffer
            t = graph_module
            for part in node.target.split("."):
                t = getattr(t, part)
            key = "attr." + node.target
            if isinstance(t, torch.nn.Parameter):
                params[key] = _np(t)
            else:
                buffers[key] = _np(torch.as_tensor(t))
            fns[node.name] = (key, None)

    _FN_MAP = {
        torch.relu: lambda *a, **k: jnp.maximum(a[0], 0),
        torch.nn.functional.relu: lambda *a, **k: jnp.maximum(a[0], 0),
        torch.tanh: lambda *a, **k: jnp.tanh(a[0]),
        torch.sigmoid: lambda *a, **k: jax.nn.sigmoid(a[0]),
        torch.nn.functional.gelu: lambda *a, **k: jax.nn.gelu(a[0]),
        torch.nn.functional.softmax: lambda x, dim=-1, **k: jax.nn.softmax(x, axis=dim),
        torch.nn.functional.log_softmax: lambda x, dim=-1, **k: jax.nn.log_softmax(x, axis=dim),
        torch.add: lambda a, b, **k: a + b,
        operator.add: lambda a, b: a + b,
        operator.sub: lambda a, b: a - b,
        operator.mul: lambda a, b: a * b,
        operator.truediv: lambda a, b: a / b,
        operator.getitem: lambda a, idx: a[idx],
        operator.matmul: lambda a, b: a @ b,
        torch.matmul: lambda a, b, **k: a @ b,
        torch.flatten: lambda x, start_dim=0, **k: x.reshape(
            x.shape[:start_dim] + (-1,)),
        torch.cat: lambda ts, dim=0, **k: jnp.concatenate(ts, axis=dim),
        torch.mean: lambda x, dim=None, keepdim=False, **k: x.mean(
            axis=dim, keepdims=keepdim),
        torch.sum: lambda x, dim=None, keepdim=False, **k: x.sum(
            axis=dim, keepdims=keepdim),
    }
    _METHODS = {
        "view": lambda x, *shape: x.reshape(
            tuple(int(s) for s in (shape[0] if isinstance(shape[0], (tuple, list))
                                   else shape))),
        "reshape": lambda x, *shape: x.reshape(
            tuple(int(s) for s in (shape[0] if isinstance(shape[0], (tuple, list))
                                   else shape))),
        "permute": lambda x, *dims: x.transpose(dims),
        "transpose": lambda x, a, b: jnp.swapaxes(x, a, b),
        "flatten": lambda x, start_dim=0: x.reshape(x.shape[:start_dim] + (-1,)),
        "mean": lambda x, dim=None, keepdim=False: x.mean(axis=dim, keepdims=keepdim),
        "sum": lambda x, dim=None, keepdim=False: x.sum(axis=dim, keepdims=keepdim),
        "size": lambda x, d=None: x.shape if d is None else x.shape[d],
        "contiguous": lambda x: x,
        "squeeze": lambda x, dim=None: jnp.squeeze(x, axis=dim),
        "unsqueeze": lambda x, dim: jnp.expand_dims(x, axis=dim),
    }

    # Node records with fx.Node references replaced by name refs and torch
    # tensors copied out, so the closure holds NO reference to graph_module
    # (otherwise every torch-side weight tensor stays alive for the model's
    # lifetime).
    class _Ref:
        __slots__ = ("name",)

        def __init__(self, name):
            self.name = name

    def freeze(a):
        if isinstance(a, fx.Node):
            return _Ref(a.name)
        if isinstance(a, (tuple, list)):
            return type(a)(freeze(v) for v in a)
        if isinstance(a, dict):
            return {k: freeze(v) for k, v in a.items()}
        if isinstance(a, torch.Tensor):
            return np.asarray(_np(a))
        return a

    # MultiheadAttention whose weights output is never consumed (every
    # user is getitem[0]) runs with need_weights=False: the flash-attention
    # path applies and the (B, H, Tq, Tk) probability matrix is never
    # materialized — torch defaults need_weights=True, so a traced model
    # that only keeps output[0] would otherwise silently pay for it.
    import torch.nn as _tnn

    def _weights_unused(n):
        """True when only element [0] of the (output, weights) tuple is
        ever consumed — `out, w = attn(...)` traces a dead getitem[1] for
        the unused w, which doesn't count as consumption."""
        if not n.users:
            return False
        for u in n.users:
            if not (u.op == "call_function"
                    and u.target is operator.getitem and len(u.args) > 1):
                return False
            if u.args[1] != 0 and u.users:
                return False
        return True

    mha_weightless = {
        n.name for n in graph_module.graph.nodes
        if n.op == "call_module"
        and isinstance(modules.get(n.target), _tnn.MultiheadAttention)
        # only rewrite the DEFAULT case: an explicit need_weights —
        # keyword or positional (5th arg, after q/k/v/key_padding_mask)
        # — is the caller's choice, and injecting a keyword on top of a
        # positional would collide at replay
        and "need_weights" not in n.kwargs and len(n.args) <= 4
        and _weights_unused(n)}

    node_recs = [(n.op, n.name, n.target, freeze(tuple(n.args)),
                  {**freeze(dict(n.kwargs)),
                   **({"need_weights": False}
                      if n.name in mha_weightless else {})})
                 for n in graph_module.graph.nodes]
    for op, name, target, _, _ in node_recs:
        if op == "call_function" and target not in _FN_MAP:
            raise NotImplementedError(
                f"torch fn {target} has no TPU translation")
        if op == "call_method" and target not in _METHODS:
            raise NotImplementedError(
                f"torch method .{target}() has no TPU translation")
    del graph_module, modules

    if has_bn:
        import logging
        logging.getLogger(__name__).warning(
            "translated BatchNorm: train-mode forward uses batch statistics "
            "(torch .train() semantics) but running statistics stay frozen "
            "at their translated values — eval-mode normalization will not "
            "track training-data drift")

    def apply_fn(variables, *inputs, train=False, rng=None):
        prms = dict(variables.get("params", {}))
        for k, v in variables.get("buffers", {}).items():
            if k in prms and isinstance(prms[k], dict):
                prms[k] = {**prms[k], **v}
            else:
                prms.setdefault(k, v)
        env: Dict[str, Any] = {}
        it = iter(inputs)

        def lookup(a):
            if isinstance(a, _Ref):
                return env[a.name]
            if isinstance(a, (tuple, list)):
                return type(a)(lookup(v) for v in a)
            if isinstance(a, dict):
                return {k: lookup(v) for k, v in a.items()}
            return a

        for op, name, target, args, kwargs in node_recs:
            if op == "placeholder":
                env[name] = next(it)
            elif op == "get_attr":
                key, _ = fns[name]
                env[name] = jnp.asarray(prms[key])
            elif op == "call_module":
                key, fn = fns[name]
                pr = prms.get(key, {})
                if name in ctx_nodes:
                    pr = dict(pr) if isinstance(pr, dict) else {}
                    pr["__train__"] = bool(train)
                    pr["__rng__"] = None if rng is None else \
                        jax.random.fold_in(rng, ctx_nodes[name])
                env[name] = fn(pr, *[lookup(a) for a in args],
                               **{k: lookup(v) for k, v in kwargs.items()})
            elif op == "call_function":
                env[name] = _FN_MAP[target](
                    *[lookup(a) for a in args],
                    **{k: lookup(v) for k, v in kwargs.items()})
            elif op == "call_method":
                env[name] = _METHODS[target](
                    *[lookup(a) for a in args],
                    **{k: lookup(v) for k, v in kwargs.items()})
            elif op == "output":
                return lookup(args[0])
        raise RuntimeError("graph had no output node")

    return apply_fn, {"params": params, "buffers": buffers}


class TorchNet:
    """Inference wrapper over a translated torch module (ref TorchNet.scala:
    frozen forward-only). ``TorchNet(module).predict(x)`` runs jitted on the
    accelerator."""

    def __init__(self, module, jit: bool = True):
        import jax
        self.apply_fn, self.variables = torch_to_jax(module)
        self._call = jax.jit(self.apply_fn) if jit else self.apply_fn

    @property
    def params(self):
        return self.variables["params"]

    def predict(self, *inputs):
        import jax
        arrs = tuple(np.asarray(a) for a in inputs)
        return np.asarray(jax.device_get(self._call(self.variables, *arrs)))

    __call__ = predict
