"""OpenVINO IR importer — run reference-published OpenVINO models on TPU.

The reference's OpenVINO path is a native x86 inference engine loaded via
``InferenceModel.load_openvino(model_path, weight_path)``
(ref ``pyzoo/zoo/pipeline/inference/inference_model.py:69`` →
``inferenceModelLoadOpenVINO``; engine in
``zoo/src/main/scala/com/intel/analytics/zoo/pipeline/inference/``). The
engine itself has no TPU analog — but the MODEL FORMAT does not need one:
this module parses OpenVINO IR directly (the ``.xml`` topology with
``xml.etree`` + the ``.bin`` weight blob by offset/size, no openvino
package) and translates the graph to a pure jax function, so IR artifacts
users already have serve on TPU through the same ``InferenceModel``
surface.

Covers the opset subset classic CV/MLP IRs use: Parameter/Const/Result,
Convolution/GroupConvolution (NCHW, explicit pads + auto_pad same_upper/
same_lower), MatMul, Add/Multiply/Subtract/Divide/Power,
ReLU/Sigmoid/Tanh/Elu/Clamp/PReLU, MaxPool/AvgPool (floor AND ceil
rounding, exclude-pad) /ReduceMean, BatchNormInference, SoftMax,
Reshape/Squeeze/Unsqueeze/Transpose/Concat/Gather (incl. batch_dims),
Sqrt/Exp. Unsupported layer types raise ``NotImplementedError`` naming
the type (same contract as ``onnx_net``).

Validation caveat: this environment has no network egress and no openvino
distribution, so the test IRs are built in-repo to the published IR-v10+
schema (attribute spellings as model-optimizer emits them — ceil-mode
pools, auto_pad variants, opset8 Gather) and checked numerically against
torch; no model-optimizer-exported artifact has run through this parser
yet. FakeQuantize/int8 IRs are not supported.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_DTYPES = {
    "f32": np.float32, "FP32": np.float32,
    "f16": np.float16, "FP16": np.float16,
    "f64": np.float64,
    "i64": np.int64, "I64": np.int64,
    "i32": np.int32, "I32": np.int32,
    "i8": np.int8, "u8": np.uint8,
    "boolean": np.bool_, "BOOL": np.bool_,
}


class _Layer:
    def __init__(self, el):
        self.id = int(el.get("id"))
        self.name = el.get("name", f"layer_{self.id}")
        self.type = el.get("type")
        self.version = el.get("version", "opset1")
        data = el.find("data")
        self.attrs: Dict[str, str] = dict(data.attrib) if data is not None \
            else {}
        self.in_ports: List[int] = [
            int(p.get("id")) for p in el.findall("./input/port")]
        self.out_ports: List[int] = [
            int(p.get("id")) for p in el.findall("./output/port")]

    def ints(self, key: str, default=None) -> Optional[Tuple[int, ...]]:
        v = self.attrs.get(key)
        if v is None or v == "":
            return default
        return tuple(int(x) for x in v.split(","))

    def __repr__(self):
        return f"<{self.type} {self.name!r}>"


def parse_ir(xml_bytes: bytes, bin_bytes: bytes):
    """IR xml+bin → (layers in topo order, edges, const arrays)."""
    root = ET.fromstring(xml_bytes)
    if root.tag != "net":
        raise ValueError("not an OpenVINO IR file (missing <net> root)")
    layers = [_Layer(el) for el in root.findall("./layers/layer")]
    by_id = {l.id: l for l in layers}
    # edge: (to_layer, to_port) <- (from_layer, from_port)
    edges: Dict[Tuple[int, int], Tuple[int, int]] = {}
    for e in root.findall("./edges/edge"):
        edges[(int(e.get("to-layer")), int(e.get("to-port")))] = (
            int(e.get("from-layer")), int(e.get("from-port")))

    consts: Dict[int, np.ndarray] = {}
    for l in layers:
        if l.type != "Const":
            continue
        dt = _DTYPES.get(l.attrs.get("element_type", "f32"))
        if dt is None:
            raise NotImplementedError(
                f"OpenVINO IR element_type "
                f"{l.attrs.get('element_type')!r} not supported")
        off = int(l.attrs["offset"])
        size = int(l.attrs["size"])
        shape = l.ints("shape", ())
        arr = np.frombuffer(bin_bytes[off:off + size], dtype=dt)
        consts[l.id] = arr.reshape(shape if shape else arr.shape).copy()

    # topological order over the edge graph — iterative DFS (deep IRs
    # easily exceed Python's recursion limit: every Const is a layer)
    order: List[_Layer] = []
    seen: set = set()

    def visit(root: int):
        stack: List[Tuple[int, bool]] = [(root, False)]
        while stack:
            lid, expanded = stack.pop()
            if expanded:
                order.append(by_id[lid])
                continue
            if lid in seen:
                continue
            seen.add(lid)
            stack.append((lid, True))
            for port in by_id[lid].in_ports:
                src = edges.get((lid, port))
                if src is not None and src[0] not in seen:
                    stack.append((src[0], False))

    has_results = any(l.type == "Result" for l in layers)
    for l in layers:
        if l.type == "Result":
            visit(l.id)
    # EVERY declared Parameter stays an input (a Parameter unreachable
    # from the Results must not change the model's input arity/binding)
    for l in layers:
        if l.type == "Parameter":
            visit(l.id)
    if not has_results:
        # graphs without Result layers (older IR): visit everything;
        # when Results exist, dangling non-Parameter subgraphs stay OUT
        for l in layers:
            visit(l.id)
    return order, edges, consts


def _auto_pads(l: _Layer, in_spatial, kernel, strides, dilations):
    """pads from explicit pads_begin/pads_end or auto_pad same_upper/
    same_lower (ref IR Convolution/Pooling attributes)."""
    auto = l.attrs.get("auto_pad", "explicit")
    if auto in ("same_upper", "same_lower"):
        pads = []
        for i, k in enumerate(kernel):
            eff = (k - 1) * dilations[i] + 1
            out = -(-in_spatial[i] // strides[i])
            total = max(0, (out - 1) * strides[i] + eff - in_spatial[i])
            lo = total // 2
            hi = total - lo
            pads.append((hi, lo) if auto == "same_lower" else (lo, hi))
        return pads
    begin = l.ints("pads_begin", (0,) * len(kernel))
    end = l.ints("pads_end", (0,) * len(kernel))
    return list(zip(begin, end))


def _conv(x, w, l: _Layer, groups: int):
    import jax.lax as lax
    spatial = len(x.shape) - 2
    strides = l.ints("strides", (1,) * spatial)
    dilations = l.ints("dilations", (1,) * spatial)
    kernel = w.shape[-spatial:]
    pads = _auto_pads(l, x.shape[2:], kernel, strides, dilations)
    letters = "DHW"[-spatial:]
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NC" + letters, "OI" + letters, "NC" + letters))
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pads,
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=groups)


def _pool(x, l: _Layer, reducer, init, average: bool):
    import jax.lax as lax
    import jax.numpy as jnp
    spatial = len(x.shape) - 2
    kernel = l.ints("kernel")
    strides = l.ints("strides", (1,) * spatial)
    pads = _auto_pads(l, x.shape[2:], kernel, strides,
                      (1,) * spatial)
    ceil_ext = [0] * spatial
    if l.attrs.get("rounding_type", "floor") == "ceil":
        # ceil output size == floor after extending the end padding so the
        # last (partial) window fits: out = ceil((in+pb+pe-k)/s)+1
        # (IR MaxPool/AvgPool rounding_type attribute; torch exporters emit
        # ceil_mode pools for squeezenet/googlenet-family models)
        pads = list(pads)
        for i, k in enumerate(kernel):
            pb, pe = pads[i]
            span = x.shape[2 + i] + pb + pe - k
            out_ceil = -(-span // strides[i]) + 1
            # Caffe/torch clamp: a window starting ENTIRELY in the end
            # padding is dropped (else MaxPool grows a -inf column and
            # exclude-pad AvgPool a 0/0 NaN one)
            if (out_ceil - 1) * strides[i] >= x.shape[2 + i] + pb:
                out_ceil -= 1
            extra = max(0, (out_ceil - 1) * strides[i] + k
                        - (x.shape[2 + i] + pb + pe))
            ceil_ext[i] = extra
            pads[i] = (pb, pe + extra)
    dims = (1, 1) + tuple(kernel)
    strd = (1, 1) + tuple(strides)
    padding = ((0, 0), (0, 0)) + tuple(pads)
    out = lax.reduce_window(x, init, reducer, dims, strd, padding)
    if average:
        if l.attrs.get("exclude-pad", "true") in ("true", "True", "1"):
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strd,
                                       padding)
            return out / counts
        if any(ceil_ext):
            # include-pad divisor counts the window clipped to input +
            # EXPLICIT pads — the ceil extension is not real padding
            # (torch avg_pool2d count_include_pad=True semantics)
            ones = jnp.ones_like(x)
            expl = ((0, 0), (0, 0)) + tuple(
                (pads[i][0], pads[i][1] - ceil_ext[i])
                for i in range(spatial))
            ones = jnp.pad(ones, expl, constant_values=1.0)
            ext_pad = ((0, 0), (0, 0)) + tuple(
                (0, ceil_ext[i]) for i in range(spatial))
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strd,
                                       ext_pad)
            return out / counts
        return out / float(np.prod(kernel))
    return out


def _apply_layer(l: _Layer, ins: List[Any]):
    import jax
    import jax.numpy as jnp

    t = l.type
    if t == "Convolution":
        return _conv(ins[0], ins[1], l, groups=1)
    if t == "GroupConvolution":
        # IR weights [G, O/G, I/G, kh, kw] → OIHW with O=G*(O/G)
        w = ins[1]
        g = w.shape[0]
        w = w.reshape((w.shape[0] * w.shape[1],) + w.shape[2:])
        return _conv(ins[0], w, l, groups=g)
    if t == "MatMul":
        a, b = ins
        if l.attrs.get("transpose_a", "false") == "true":
            a = jnp.swapaxes(a, -1, -2)
        if l.attrs.get("transpose_b", "false") == "true":
            b = jnp.swapaxes(b, -1, -2)
        return a @ b
    if t == "Add":
        return ins[0] + ins[1]
    if t == "Subtract":
        return ins[0] - ins[1]
    if t == "Multiply":
        return ins[0] * ins[1]
    if t == "Divide":
        return ins[0] / ins[1]
    if t == "Power":
        return ins[0] ** ins[1]
    if t == "Sqrt":
        return jnp.sqrt(ins[0])
    if t == "Exp":
        return jnp.exp(ins[0])
    if t == "ReLU":
        return jax.nn.relu(ins[0])
    if t == "PReLU":
        slope = ins[1]
        if slope.ndim == 1 and ins[0].ndim > 2:  # per-channel, NCHW
            slope = slope.reshape((1, -1) + (1,) * (ins[0].ndim - 2))
        return jnp.where(ins[0] > 0, ins[0], slope * ins[0])
    if t == "Sigmoid":
        return jax.nn.sigmoid(ins[0])
    if t == "Tanh":
        return jnp.tanh(ins[0])
    if t == "Elu":
        return jax.nn.elu(ins[0], alpha=float(l.attrs.get("alpha", 1.0)))
    if t == "Clamp":
        return jnp.clip(ins[0], float(l.attrs["min"]), float(l.attrs["max"]))
    if t in ("SoftMax", "Softmax"):
        return jax.nn.softmax(ins[0], axis=int(l.attrs.get("axis", 1)))
    if t == "MaxPool":
        import jax.lax as lax
        return _pool(ins[0], l, lax.max, -jnp.inf, average=False)
    if t == "AvgPool":
        import jax.lax as lax
        return _pool(ins[0], l, lax.add, 0.0, average=True)
    if t == "ReduceMean":
        axes = tuple(int(a) for a in np.asarray(ins[1]).reshape(-1))
        keep = l.attrs.get("keep_dims", "true") in ("true", "True", "1")
        return jnp.mean(ins[0], axis=axes, keepdims=keep)
    if t == "BatchNormInference":
        # input order CHANGED across opsets (opset5 release note: data
        # moved first): opset1 = (gamma, beta, data, mean, variance),
        # opset5+ = (data, gamma, beta, mean, variance)
        if l.version in ("opset1", "opset2", "opset3", "opset4"):
            gamma, beta, x, mean, var = ins
        else:
            x, gamma, beta, mean, var = ins
        eps = float(l.attrs.get("eps", l.attrs.get("epsilon", 1e-5)))
        shape = (1, -1) + (1,) * (x.ndim - 2)
        return (x - mean.reshape(shape)) * gamma.reshape(shape) \
            / jnp.sqrt(var.reshape(shape) + eps) + beta.reshape(shape)
    if t == "Reshape":
        target = [int(v) for v in np.asarray(ins[1]).reshape(-1)]
        if l.attrs.get("special_zero", "true") in ("true", "True", "1"):
            target = [ins[0].shape[i] if v == 0 else v
                      for i, v in enumerate(target)]
        return ins[0].reshape(target)
    if t == "Squeeze":
        axes = tuple(int(a) for a in np.asarray(ins[1]).reshape(-1)) \
            if len(ins) > 1 else None
        return jnp.squeeze(ins[0], axis=axes)
    if t == "Unsqueeze":
        out = ins[0]
        raw = [int(a) for a in np.asarray(ins[1]).reshape(-1)]
        out_rank = out.ndim + len(raw)
        # negative axes index the OUTPUT rank, not the intermediate one
        for a in sorted(a % out_rank for a in raw):
            out = jnp.expand_dims(out, a)
        return out
    if t == "Transpose":
        return jnp.transpose(ins[0],
                             [int(v) for v in np.asarray(ins[1]).reshape(-1)])
    if t == "Concat":
        return jnp.concatenate(ins, axis=int(l.attrs.get("axis", 0)))
    if t == "Gather":
        bd = int(l.attrs.get("batch_dims", 0))
        axis = int(np.asarray(ins[2]).reshape(())) if len(ins) > 2 \
            else int(l.attrs.get("axis", 0))
        data = ins[0]
        idx = jnp.asarray(ins[1]).astype(jnp.int32)
        if bd < 0:
            bd += idx.ndim
        if axis < 0:
            axis += data.ndim
        if bd == 0:
            return jnp.take(data, idx, axis=axis)
        # batch_dims > 0: vmap one shared leading dim at a time (IR
        # Gather-7/8 semantics — per-batch index tables, e.g. embedding
        # lookups exported with a batch of sequences)
        def g(d, i, rem):
            if rem == 0:
                return jnp.take(d, i, axis=axis - bd)
            return jax.vmap(lambda dd, ii: g(dd, ii, rem - 1))(d, i)
        return g(data, idx, bd)
    raise NotImplementedError(
        f"OpenVINO layer type {t!r} ({l.name}) has no TPU translation")


def openvino_to_jax(xml_bytes: bytes, bin_bytes: bytes):
    """IR → ``(apply_fn, {"params": float consts})``. Integer/bool consts
    (shape/axis/index operands) stay static so consumers see concrete
    values under jit — same split as ``onnx_net.onnx_to_jax``."""
    order, edges, consts = parse_ir(xml_bytes, bin_bytes)

    params: Dict[str, Any] = {}
    static: Dict[int, np.ndarray] = {}
    for lid, arr in consts.items():
        if np.issubdtype(arr.dtype, np.integer) or arr.dtype == np.bool_:
            static[lid] = arr
        else:
            params[str(lid)] = arr.astype(np.float32) \
                if arr.dtype == np.float16 else arr

    # declaration (id) order, not traversal order — positional binding
    # must follow the IR's declared input order
    graph_inputs = sorted((l for l in order if l.type == "Parameter"),
                          key=lambda l: l.id)
    # the closure must NOT pin the host numpy weights (variables carry the
    # live copies) — capture only the ids
    param_ids = list(params)

    def apply_fn(variables, *inputs):
        import jax.numpy as jnp
        if len(inputs) != len(graph_inputs):
            raise ValueError(
                f"model takes {len(graph_inputs)} inputs "
                f"({[l.name for l in graph_inputs]}), got {len(inputs)}")
        env: Dict[Tuple[int, int], Any] = {}
        for l, x in zip(graph_inputs, inputs):
            env[(l.id, l.out_ports[0])] = jnp.asarray(x)
        for lid, arr in static.items():
            env[(lid, 0)] = arr
        for lid in param_ids:
            env[(int(lid), 0)] = variables["params"][lid]
        outs: List[Any] = []
        for l in order:
            if l.type in ("Parameter", "Const"):
                continue
            ins = []
            for port in l.in_ports:
                src = edges.get((l.id, port))
                if src is None:
                    raise ValueError(
                        f"layer {l.name!r} input port {port} unconnected")
                ins.append(env[src])
            if l.type == "Result":
                outs.append(ins[0])
                continue
            out = _apply_layer(l, ins)
            env[(l.id, l.out_ports[0] if l.out_ports else 0)] = out
        return outs[0] if len(outs) == 1 else tuple(outs)

    apply_fn.n_inputs = len(graph_inputs)
    return apply_fn, {"params": params}


class OpenVINONet:
    """Inference wrapper over a translated IR (the TPU counterpart of the
    reference's OpenVINO engine handle)."""

    def __init__(self, model_path: str, weight_path: str, jit: bool = True):
        import jax
        with open(model_path, "rb") as f:
            xml_bytes = f.read()
        with open(weight_path, "rb") as f:
            bin_bytes = f.read()
        self.apply_fn, self.variables = openvino_to_jax(xml_bytes, bin_bytes)
        self.n_inputs = self.apply_fn.n_inputs
        self._call = jax.jit(self.apply_fn) if jit else self.apply_fn

    @property
    def params(self):
        return self.variables["params"]

    def predict(self, *inputs):
        out = self._call(self.variables, *inputs)
        import jax
        return jax.tree_util.tree_map(np.asarray, out)
