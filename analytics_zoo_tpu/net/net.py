"""Net — unified model import (ref zoo/.../pipeline/api/Net.scala:446 and
pyzoo/zoo/pipeline/api/net/net_load.py:69).

The reference fans out to BigDL/Keras/Caffe/TF/Torch loaders, each a foreign
runtime embedded in the JVM. Here every import path lands in the same place
— a jax ``(apply_fn, params)`` pair — so the loaded model composes with the
Estimator, InferenceModel and serving stacks identically:

- ``Net.load(path)``        — a saved ZooModel directory (our native format)
- ``Net.load_torch(module)``— live torch nn.Module via fx translation
- ``Net.load_torch_file(path)`` — torch-saved module/state_dict file
- ``Net.load_onnx(path)``   — gated on the optional ``onnx`` package
"""

from __future__ import annotations

import os


class Net:
    @staticmethod
    def load(path: str):
        from analytics_zoo_tpu.models.common import ZooModel
        return ZooModel.load_model(path)

    @staticmethod
    def load_torch(module) -> "TorchNet":
        from analytics_zoo_tpu.net.torch_net import TorchNet
        return TorchNet(module)

    @staticmethod
    def load_torch_file(path: str):
        """torch.save'd full module (ref Net.loadTorch, Net.scala)."""
        import torch
        obj = torch.load(path, map_location="cpu", weights_only=False)
        if not hasattr(obj, "forward"):
            raise ValueError(
                f"{path} holds a {type(obj).__name__}, not a torch module; "
                "for state_dicts load the module yourself and call load_torch")
        from analytics_zoo_tpu.net.torch_net import TorchNet
        return TorchNet(obj)

    @staticmethod
    def load_onnx(path: str):
        """ONNX import (ref pyzoo onnx_loader.py:141): parses the ONNX
        protobuf directly (no onnx package needed) and translates the node
        graph to a jitted jax function — see net/onnx_net.py."""
        from analytics_zoo_tpu.net.onnx_net import ONNXNet
        return ONNXNet(path)

    @staticmethod
    def load_openvino(model_path: str, weight_path: str):
        """OpenVINO IR import (ref InferenceModel.load_openvino /
        inferenceModelLoadOpenVINO): parses the IR xml+bin directly (no
        openvino package) and translates the layer graph to a jitted jax
        function — see net/openvino_net.py."""
        from analytics_zoo_tpu.net.openvino_net import OpenVINONet
        return OpenVINONet(model_path, weight_path)
