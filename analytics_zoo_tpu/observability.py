"""Thin observability helpers over :mod:`analytics_zoo_tpu.common.telemetry`.

One import surface for operators and notebooks::

    from analytics_zoo_tpu import observability as obs
    obs.scrape()            # Prometheus text exposition of everything
    obs.metrics()           # JSON-able snapshot (counters/gauges/hist stats)
    obs.trace("my-uri")     # a served record's stage decomposition
    obs.trace_table("uri")  # ... pretty-printed

Profiling layer (ISSUE 3)::

    obs.dump_trace("out.json")        # Chrome Trace Event JSON → Perfetto
    obs.chrome_trace()                # ... as a dict (GET /trace payload)
    obs.get_flight_recorder().dump()  # postmortem under zoo_tpu_logs/
    obs.backend_state()               # non-blocking backend/device probe

Fleet & SLO layer (ISSUE 6)::

    obs.merge_snapshot(a, b)   # mergeable-snapshot algebra (federation)
    obs.fleet_registry(port=p) # list/partition live serving replicas
    obs.get_slo_monitor()      # burn-rate SLO monitor (GET /slo payload)

The serving FrontEnd exposes the same data over HTTP (``GET /metrics``
content-negotiated JSON/Prometheus — ``?scope=fleet`` for the merged
fleet view, ``?format=snapshot`` for the mergeable wire format —
``GET /healthz`` with fleet/SLO state, ``GET /trace``, ``GET /slo``);
see docs/observability.md for the stable metric catalog.
"""

from __future__ import annotations

from typing import Dict, List

from analytics_zoo_tpu.common.compile_ahead import (  # noqa: F401  (re-exports)
    WARMUP_TRACE_ID, BucketLadder, ExecutableCache, configure_persistent_cache,
)
from analytics_zoo_tpu.common.fleet import (  # noqa: F401  (re-exports)
    Heartbeater, ReplicaInfo, ReplicaRegistry,
)
from analytics_zoo_tpu.common.slo import (  # noqa: F401  (re-exports)
    SLO, SLOMonitor, default_slos,
)
from analytics_zoo_tpu.common.slo import get_monitor as get_slo_monitor  # noqa: F401
from analytics_zoo_tpu.common.profiling import (  # noqa: F401  (re-exports)
    FlightRecorder, StepProfiler, backend_state, chrome_trace,
    compiled_step_flops, device_peak_flops, dump_trace, get_flight_recorder,
    hbm_bytes, maybe_arm_from_env,
)
from analytics_zoo_tpu.common.telemetry import (  # noqa: F401  (re-exports)
    MetricsRegistry, Span, Tracer, bench_snapshot, get_registry, get_tracer,
    instrument_jit, observe_device_block, prometheus_text, set_trace_sampling,
    snapshot, timed_block_until_ready, traced_device_get, traced_device_put,
)

__all__ = [
    "scrape", "metrics", "trace", "trace_table", "get_registry",
    "get_tracer", "instrument_jit", "set_trace_sampling", "bench_snapshot",
    "prometheus_text", "snapshot", "traced_device_put", "traced_device_get",
    "observe_device_block", "timed_block_until_ready",
    "chrome_trace", "dump_trace", "StepProfiler", "FlightRecorder",
    "get_flight_recorder", "maybe_arm_from_env", "backend_state",
    "compiled_step_flops", "device_peak_flops", "hbm_bytes",
    "BucketLadder", "ExecutableCache", "configure_persistent_cache",
    "WARMUP_TRACE_ID",
    "merge_snapshot", "fleet_registry", "ReplicaRegistry", "ReplicaInfo",
    "Heartbeater", "SLO", "SLOMonitor", "default_slos", "get_slo_monitor",
]


def merge_snapshot(base: Dict, other: Dict) -> Dict:
    """Merge two registry snapshots (the federation algebra): counters
    and gauges sum, histograms add bucket counts and union reservoirs.
    See :meth:`MetricsRegistry.merge_snapshot`."""
    return MetricsRegistry.merge_snapshot(base, other)


def fleet_registry(host: str = "127.0.0.1", port: int = 6399
                   ) -> ReplicaRegistry:
    """A :class:`ReplicaRegistry` over the given broker — ``.list()`` /
    ``.partition()`` enumerate serving replicas by heartbeat."""
    return ReplicaRegistry(host, port)


def scrape() -> str:
    """Prometheus text exposition of the process-wide registry."""
    return prometheus_text()


def metrics() -> Dict:
    """JSON-able snapshot of the process-wide registry."""
    return snapshot()


def trace(trace_id: str) -> List[Span]:
    """All spans recorded for ``trace_id`` (a serving record's uri)."""
    return get_tracer().get(trace_id)


def trace_table(trace_id: str) -> str:
    """The trace as an aligned text table (offsets relative to the first
    span's start, durations in ms) — the quick-look CLI view."""
    spans = sorted(trace(trace_id), key=lambda s: s.start)
    if not spans:
        return f"(no trace for {trace_id!r})"
    t0 = spans[0].start
    rows = [f"{'span':<16} {'start_ms':>10} {'dur_ms':>10}  parent"]
    for s in spans:
        rows.append(f"{s.name:<16} {(s.start - t0) * 1e3:>10.3f} "
                    f"{s.duration * 1e3:>10.3f}  {s.parent or '-'}")
    return "\n".join(rows)
