from analytics_zoo_tpu.serving.broker import Broker, BrokerClient
from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
from analytics_zoo_tpu.serving.config import ServingConfig
from analytics_zoo_tpu.serving.engine import ClusterServing, image_pipeline
from analytics_zoo_tpu.serving.frontend import FrontEnd

__all__ = ["Broker", "BrokerClient", "InputQueue", "OutputQueue",
           "ServingConfig", "ClusterServing", "FrontEnd", "image_pipeline"]
