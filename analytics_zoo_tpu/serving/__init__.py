from analytics_zoo_tpu.serving.broker import Broker, BrokerClient, ShedError
from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
from analytics_zoo_tpu.serving.config import ServingConfig
from analytics_zoo_tpu.serving.engine import ClusterServing, image_pipeline
from analytics_zoo_tpu.serving.frontend import FrontEnd
from analytics_zoo_tpu.serving.schema import (DeadlineExpiredError,
                                              ServingError)

__all__ = ["Broker", "BrokerClient", "InputQueue", "OutputQueue",
           "ServingConfig", "ClusterServing", "FrontEnd", "image_pipeline",
           "ShedError", "ServingError", "DeadlineExpiredError"]
