"""HTTP frontend — synchronous predict endpoint over the serving plane.

Replaces the reference's akka-http frontend
(zoo/.../serving/http/FrontEndApp.scala:41,362: POST a payload, the handler
enqueues to Redis and awaits the result). Endpoints:

- ``POST /predict``  body = JSON ``{"inputs": {name: {dtype, shape, data}}}``
  (schema.py tensor encoding) → ``{"uri", "result": tensor}``
- ``GET  /metrics``  → engine metrics JSON
- ``GET  /``         → liveness

stdlib ``ThreadingHTTPServer`` — no framework dependency; each request
thread owns its queue clients (the broker protocol is connection-oriented).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from analytics_zoo_tpu.serving import schema
from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # quiet
        pass

    def _json(self, code: int, obj):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv = self.server  # type: ignore[assignment]
        if self.path == "/metrics":
            engine = srv.engine
            self._json(200, engine.metrics() if engine else {})
        else:
            self._json(200, {"status": "ok"})

    def do_POST(self):
        srv = self.server  # type: ignore[assignment]
        if self.path != "/predict":
            self._json(404, {"error": "unknown path"})
            return
        in_q = out_q = None
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n))
            inputs = {k: schema.decode_tensor(v)
                      for k, v in payload["inputs"].items()}
            in_q = InputQueue(host=srv.broker_host,
                              port=srv.broker_port, cipher=srv.cipher)
            uri = in_q.enqueue(payload.get("uri"), **inputs)
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            self._json(400, {"error": f"bad request: {e}"})
            return
        finally:
            if in_q is not None:
                in_q.close()
        try:
            out_q = OutputQueue(host=srv.broker_host,
                                port=srv.broker_port, cipher=srv.cipher)
            result = out_q.query(uri, timeout=srv.timeout_s, delete=True)
        except schema.ServingError as e:
            self._json(422, {"uri": uri, "error": str(e)})
            return
        finally:
            if out_q is not None:
                out_q.close()
        if result is None:
            self._json(504, {"uri": uri, "error": "timed out"})
        else:
            self._json(200, {"uri": uri,
                             "result": schema.encode_tensor(result)})


class FrontEnd:
    """``FrontEnd(broker_port, engine).start()`` → serving HTTP on ``port``."""

    def __init__(self, broker_port: int, engine=None, port: int = 0,
                 timeout: float = 30.0, cipher: schema.Cipher = None,
                 host: str = "127.0.0.1",
                 broker_host: str = "127.0.0.1"):
        # host="0.0.0.0" for containers (the EXPOSEd port must bind
        # beyond loopback to be reachable through docker port mapping)
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.broker_host = broker_host       # type: ignore[attr-defined]
        self._httpd.broker_port = broker_port       # type: ignore[attr-defined]
        self._httpd.engine = engine                 # type: ignore[attr-defined]
        self._httpd.timeout_s = timeout             # type: ignore[attr-defined]
        self._httpd.cipher = cipher                 # type: ignore[attr-defined]
        # BaseHTTPRequestHandler reads .timeout off the server for socket
        # timeouts; keep our own name distinct
        self._httpd.timeout = None                  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FrontEnd":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
