"""HTTP frontend — synchronous predict endpoint over the serving plane.

Replaces the reference's akka-http frontend
(zoo/.../serving/http/FrontEndApp.scala:41,362: POST a payload, the handler
enqueues to Redis and awaits the result). Endpoints:

- ``POST /predict``  body = JSON ``{"inputs": {name: {dtype, shape, data}}}``
  (schema.py tensor encoding) → ``{"uri", "result": tensor}``. Optional
  ``"priority"`` (one of schema.PRIORITIES) routes the record onto a
  broker lane and ``"deadline_ms"`` bounds result staleness: a shed lane
  answers 429 immediately (``code: "shed"``), an expired deadline answers
  504 with ``code: "expired"`` instead of the generic poll timeout.
  Optional ``"generate"`` (``{"max_new_tokens", "mode", "temperature",
  "seed"}``) turns the record into an autoregressive generate request —
  inputs then carry the encoder tensor plus a ``start`` tensor, and the
  result is the engine's generated ``[steps, dim]`` sequence.
- ``GET  /metrics``  → engine metrics JSON by default; Prometheus text
  exposition (format 0.0.4) when the request asks for it — ``Accept:``
  containing ``text/plain`` or ``openmetrics``, or ``?format=prometheus``.
  The Prometheus view is the process-wide telemetry registry, so engine
  counters, stage histograms, JIT/transfer metrics and frontend request
  counters all scrape from one endpoint.
  ``?format=snapshot`` returns the raw mergeable registry snapshot
  (histograms with ``le`` edges + ``bucket_counts`` — the federation wire
  format). ``?scope=fleet`` federates: list live replicas from the fleet
  registry (common/fleet.py), scrape each peer's snapshot, and serve the
  merged view (telemetry.merge_snapshot) in either format; a failed
  peer scrape counts ``zoo_fleet_scrape_errors_total{replica}`` and
  degrades the response to partial instead of failing it.
- ``GET  /healthz``  → readiness JSON: broker reachability, input queue
  depth (total and per priority lane), consumer-group backlog, lane
  admission state, fleet replica counts, SLO burn rates, and — when the
  model is sharded — the ``sharding`` block with per-shard HBM bytes.
  503 when the broker is unreachable, when the queue depth exceeds
  ``max_backlog``, or when the SLO monitor (common/slo.py) sheds —
  every window's burn rate past ``ZOO_SLO_SHED_BURN`` — so load
  balancers back off on *measured* p99/error burn before the raw
  backlog ever looks scary.
- ``GET  /slo``      → the SLO monitor's full report: per-objective,
  per-window burn rates, bad fractions, and the shed decision.
- ``GET  /metrics/history`` → the retained time-series rings
  (common/timeseries.py): every series' sampled points with age-relative
  timestamps. ``?name=`` filters (repeatable), ``?window=`` bounds the
  age. ``?format=windows`` renders windowed *snapshot-shaped deltas*
  (default 60/300/3600 s, override ``?windows=60,300``) — the federation
  wire format. ``?scope=fleet`` merges every live replica's windowed
  history through the snapshot-merge algebra; a dead peer degrades the
  response to partial (``partial: true``) without touching the retained
  local windows.
- ``GET  /query``    → one windowed aggregate:
  ``?name=zoo_serving_latency_seconds&window=60&agg=p99`` (any other
  query param is a label filter, e.g. ``&priority=batch``). Histogram
  points carry an ``exemplar`` trace id when one landed in the window —
  resolvable via ``GET /trace?uri=``.
- ``GET  /``         → liveness

stdlib ``ThreadingHTTPServer`` — no framework dependency; each request
thread owns its queue clients (the broker protocol is connection-oriented).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from analytics_zoo_tpu.common import fleet, profiling, resilience, slo, \
    telemetry, timeseries
from analytics_zoo_tpu.serving import schema
from analytics_zoo_tpu.serving.broker import BrokerClient, ShedError
from analytics_zoo_tpu.serving.client import (INPUT_STREAM, InputQueue,
                                              OutputQueue)

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: per-peer timeout for ?scope=fleet scrapes — bounded so one dead
#: replica delays, never wedges, the federated response
FLEET_SCRAPE_TIMEOUT_S = 2.0


def scrape_fleet(broker_host: str, broker_port: int,
                 own_replica_id: Optional[str] = None,
                 timeout_s: float = FLEET_SCRAPE_TIMEOUT_S):
    """Merge the local registry snapshot with every live replica's
    ``/metrics?format=snapshot``. Returns ``(merged, meta)`` where meta
    lists scraped/failed/stale replica ids; a peer that cannot be
    scraped (no advertised port, HTTP error, unmergeable snapshot)
    lands in ``failed`` and increments
    ``zoo_fleet_scrape_errors_total{replica}`` — the fleet view degrades
    to partial rather than erroring. Raises the broker's
    ``ConnectionError``/``OSError`` only when the registry itself is
    unreachable."""
    import urllib.request
    registry = fleet.ReplicaRegistry(broker_host, broker_port)
    live, stale = registry.partition()
    merged = telemetry.snapshot()
    errs = telemetry.get_registry().counter(
        "zoo_fleet_scrape_errors_total",
        "Replica snapshot scrapes that failed during fleet federation",
        ("replica",))
    scraped, failed = [], []
    for r in live:
        if own_replica_id is not None and r.replica_id == own_replica_id:
            scraped.append(r.replica_id)   # self = the local snapshot
            continue
        try:
            if r.port <= 0:
                raise ValueError("replica advertises no scrape port")
            with urllib.request.urlopen(
                    f"http://{r.host}:{r.port}/metrics?format=snapshot",
                    timeout=timeout_s) as resp:
                peer = json.loads(resp.read())
            merged = telemetry.MetricsRegistry.merge_snapshot(merged, peer)
            scraped.append(r.replica_id)
        except Exception:
            errs.labels(r.replica_id).inc()
            failed.append(r.replica_id)
    return merged, {"scraped": scraped, "failed": failed,
                    "stale": [r.replica_id for r in stale]}


def scrape_fleet_history(broker_host: str, broker_port: int,
                         own_replica_id: Optional[str] = None,
                         windows=timeseries.DEFAULT_WINDOWS_S,
                         timeout_s: float = FLEET_SCRAPE_TIMEOUT_S):
    """Merge the local store's windowed deltas with every live replica's
    ``/metrics/history?format=windows``. Window deltas are snapshot-
    shaped, so each window folds through the SAME merge algebra as the
    point-in-time fleet scrape — merged counter deltas over a window are
    the fleet's windowed rate. A peer that cannot be scraped or merged
    lands in ``failed`` (``zoo_fleet_scrape_errors_total{replica}``) and
    the response degrades to partial; the local retained windows are
    never mutated (merge copies)."""
    import urllib.request
    registry = fleet.ReplicaRegistry(broker_host, broker_port)
    live, stale = registry.partition()
    store = timeseries.get_store()
    store.tick_if_stale()
    merged = store.windows_delta(windows)
    errs = telemetry.get_registry().counter(
        "zoo_fleet_scrape_errors_total",
        "Replica snapshot scrapes that failed during fleet federation",
        ("replica",))
    wparam = ",".join(str(int(w)) for w in windows)
    scraped, failed = [], []
    for r in live:
        if own_replica_id is not None and r.replica_id == own_replica_id:
            scraped.append(r.replica_id)   # self = the local windows
            continue
        try:
            if r.port <= 0:
                raise ValueError("replica advertises no scrape port")
            with urllib.request.urlopen(
                    f"http://{r.host}:{r.port}/metrics/history"
                    f"?format=windows&windows={wparam}",
                    timeout=timeout_s) as resp:
                peer = json.loads(resp.read())["windows"]
            # all-or-nothing per peer: a window that fails to merge
            # discards this peer's whole contribution (failed scrape),
            # never a half-merged aggregate
            merged = {
                wname: telemetry.MetricsRegistry.merge_snapshot(
                    snap_w, peer.get(wname, {}))
                for wname, snap_w in merged.items()}
            scraped.append(r.replica_id)
        except Exception:
            errs.labels(r.replica_id).inc()
            failed.append(r.replica_id)
    return merged, {"scraped": scraped, "failed": failed,
                    "stale": [r.replica_id for r in stale]}


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # quiet
        pass

    def _count(self, path: str, code: int):
        self.server.http_counter.labels(  # type: ignore[attr-defined]
            path, str(code)).inc()

    def _json(self, code: int, obj, path: str = ""):
        body = json.dumps(obj).encode()
        self._count(path or self.path, code)
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, code: int, text: str, content_type: str):
        body = text.encode("utf-8")
        self._count(self.path.split("?", 1)[0], code)
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # ----------------------------------------------------------------- GET
    def _wants_prometheus(self) -> bool:
        if "format=prometheus" in self.path:
            return True
        if "format=snapshot" in self.path:
            return False
        accept = (self.headers.get("Accept") or "").lower()
        return "text/plain" in accept or "openmetrics" in accept

    def _metrics(self):
        if "scope=fleet" in self.path:
            self._metrics_fleet()
            return
        if "format=snapshot" in self.path:
            # the mergeable wire format peers scrape during federation
            self._json(200, telemetry.snapshot(), path="/metrics")
            return
        if self._wants_prometheus():
            self._text(200, telemetry.prometheus_text(),
                       PROMETHEUS_CONTENT_TYPE)
            return
        engine = self.server.engine  # type: ignore[attr-defined]
        self._json(200, engine.metrics() if engine else {},
                   path="/metrics")

    def _metrics_fleet(self):
        srv = self.server  # type: ignore[assignment]
        own = srv.engine.replica_id if srv.engine else None
        try:
            merged, meta = scrape_fleet(srv.broker_host, srv.broker_port,
                                        own_replica_id=own)
        except (ConnectionError, OSError) as e:
            self._json(503, {"error": f"fleet registry unreachable: {e}"},
                       path="/metrics")
            return
        if self._wants_prometheus():
            # rebuild a registry from the merged snapshot so the fleet
            # view speaks the same 0.0.4 exposition as scope=self
            text = telemetry.MetricsRegistry.from_snapshot(
                merged).prometheus_text()
            self._text(200, text, PROMETHEUS_CONTENT_TYPE)
            return
        self._json(200, {"scope": "fleet", "partial": bool(meta["failed"]),
                         "replicas": meta, "metrics": merged},
                   path="/metrics")

    def _qs(self) -> dict:
        from urllib.parse import parse_qs
        if "?" not in self.path:
            return {}
        return parse_qs(self.path.split("?", 1)[1])

    def _history(self):
        q = self._qs()
        windows = timeseries.DEFAULT_WINDOWS_S
        if "windows" in q:
            try:
                windows = tuple(max(1.0, float(p))
                                for p in q["windows"][0].split(",") if p)
            except ValueError:
                self._json(400, {"error": "bad windows= parameter"},
                           path="/metrics/history")
                return
        if (q.get("scope") or [""])[0] == "fleet":
            self._history_fleet(windows)
            return
        store = timeseries.get_store()
        store.tick_if_stale()
        if (q.get("format") or [""])[0] == "windows":
            # the federation wire format: snapshot-shaped per-window
            # deltas, mergeable via MetricsRegistry.merge_snapshot
            self._json(200, {"windows": store.windows_delta(windows)},
                       path="/metrics/history")
            return
        window = None
        if "window" in q:
            try:
                window = float(q["window"][0])
            except ValueError:
                self._json(400, {"error": "bad window= parameter"},
                           path="/metrics/history")
                return
        self._json(200, store.history(names=q.get("name") or None,
                                      window=window),
                   path="/metrics/history")

    def _history_fleet(self, windows):
        srv = self.server  # type: ignore[assignment]
        own = srv.engine.replica_id if srv.engine else None
        try:
            merged, meta = scrape_fleet_history(
                srv.broker_host, srv.broker_port, own_replica_id=own,
                windows=windows)
        except (ConnectionError, OSError) as e:
            self._json(503, {"error": f"fleet registry unreachable: {e}"},
                       path="/metrics/history")
            return
        self._json(200, {"scope": "fleet",
                         "partial": bool(meta["failed"]),
                         "replicas": meta, "windows": merged},
                   path="/metrics/history")

    #: /query params with reserved meaning — everything else filters labels
    QUERY_RESERVED = frozenset({"name", "window", "agg", "scope", "format",
                                "windows"})

    def _query(self):
        q = self._qs()
        name = (q.get("name") or [None])[0]
        if not name:
            self._json(400, {"error": "query needs name="}, path="/query")
            return
        store = timeseries.get_store()
        # a query window's right edge must include traffic up to the
        # request itself, not the last background tick — force a sample
        # (cheap: one registry walk)
        store.tick()
        try:
            out = store.query(
                name,
                labels={k: v[0] for k, v in q.items()
                        if k not in self.QUERY_RESERVED},
                window=float((q.get("window") or ["60"])[0]),
                agg=(q.get("agg") or [None])[0])
        except ValueError as e:
            self._json(400, {"error": str(e)}, path="/query")
            return
        self._json(200, out, path="/query")

    @staticmethod
    def _lane_state(client: BrokerClient, stream: str, engine) -> dict:
        """Per-lane scheduling state shared by /healthz and /slo: queue
        depth per priority lane, the broker's shed flags, and the
        engine's admission-control mirrors."""
        out = {"lanes": {lane: client.xlen(stream, lane)
                         for lane in schema.PRIORITIES},
               "shed_lanes": client.xshed(stream)}
        if engine is not None:
            out["admission"] = {
                "shedding": bool(getattr(engine, "admission_shedding",
                                         False)),
                "records_expired": int(getattr(engine, "records_expired",
                                               0))}
        return out

    def _healthz(self):
        srv = self.server  # type: ignore[assignment]
        engine = srv.engine
        stream = engine.stream if engine else INPUT_STREAM
        group = engine.group if engine else "serving"
        out = {"status": "ok", "broker": "up",
               "queue_depth": 0, "backlog": 0,
               "engine": bool(engine and engine._thread is not None)}
        code = 200
        client = None
        try:
            client = BrokerClient(host=srv.broker_host,
                                  port=srv.broker_port)
            out["queue_depth"] = client.xlen(stream)
            out.update(self._lane_state(client, stream, engine))
            try:
                out["backlog"] = client.xpending(stream, group)
            except Exception:
                # group not created yet (no engine started): not an error
                out["backlog"] = 0
        except (ConnectionError, OSError) as e:
            out.update(status="unavailable", broker=f"down: {e}")
            code = 503
        finally:
            if client is not None:
                client.close()
        if code == 200 and out["queue_depth"] > srv.max_backlog:
            out["status"] = "overloaded"
            out["reason"] = "backlog"
            code = 503
        # fleet view: who else is serving, by heartbeat freshness, plus
        # the multi-replica delivery state. Membership is read FRESH from
        # the registry on every call — the supervisor's cached sweep can
        # predate a just-joined replica by a full sweep interval, and a
        # health endpoint must not under-report the fleet. The cached
        # sweep only contributes the delivery state (per-consumer pending
        # leases, orphaned entries), falling back to a direct broker read
        # when this frontend runs engine-less.
        if out["broker"] == "up":
            try:
                live, stale = fleet.ReplicaRegistry(
                    srv.broker_host, srv.broker_port).partition()
                out["fleet"] = {"replicas": len(live),
                                "stale": len(stale)}
                rsup = getattr(engine, "_replica_supervisor", None)
                snap = rsup.snapshot() if rsup is not None else {}
                if snap:
                    out["fleet"].update(
                        pending_per_replica=snap["pending_per_replica"],
                        orphan_entries=snap["orphan_entries"],
                        reclaim_sweeps=snap["sweeps"])
                else:
                    try:
                        fc = BrokerClient(host=srv.broker_host,
                                          port=srv.broker_port)
                        try:
                            out["fleet"]["pending_per_replica"] = \
                                fc.xpending_detail(stream, group)
                        finally:
                            fc.close()
                    except Exception:
                        pass
                if engine is not None:
                    out["fleet"]["lease_reclaims"] = engine.lease_reclaims
                    out["fleet"]["records_redelivered"] = \
                        engine.records_redelivered
            except Exception:
                out["fleet"] = {"replicas": 0, "stale": 0}
        # burn-rate shedding: the *measured* overload signal — p99/error
        # budget burning past ZOO_SLO_SHED_BURN on every window trips 503
        # while the raw backlog may still look fine (the backlog check
        # above survives only as the coarse fallback)
        mon = slo.get_monitor()
        mon.tick_if_stale()
        shedding = mon.overloaded()
        out["slo"] = {"burn_rates": mon.burn_rates(), "shedding": shedding}
        # CPU failover (ISSUE 7): a replica still answering every record
        # on its fallback rungs is degraded, NOT down — shedding it would
        # turn a survived wedge into an outage, so the SLO trip (whose
        # burn is dominated by the wedge itself) is suppressed while the
        # engine reports failover
        failover = bool(engine is not None
                        and getattr(engine, "failover_active", False))
        if failover:
            out["failover"] = "cpu-fallback"
        if code == 200 and shedding and not failover:
            out["status"] = "overloaded"
            out["reason"] = "slo-burn"
            code = 503
        # surface the JAX backend so a CPU-fallback or wedged-device
        # replica is visible from the probe itself; the probe thread is
        # timeout-joined, so a wedged backend can never hang /healthz
        out["backend"] = profiling.backend_state(timeout_s=2.0)
        # model-parallel placement when the engine's model is sharded:
        # strategy, shard count, total and PER-SHARD parameter HBM bytes
        # — capacity dashboards read placement from the liveness probe
        si = getattr(getattr(engine, "model", None), "shard_info", None)
        if si is not None:
            try:
                info = si()
            except Exception:
                info = None
            if info:
                out["sharding"] = info
        sup = resilience.supervisor_snapshot()
        if sup is not None:
            out["backend_supervisor"] = sup
        # decode occupancy: live sequences, paged-KV pressure and the
        # preemption count since start — capacity dashboards watch page
        # exhaustion from the probe, not from a metrics scrape
        if engine is not None and hasattr(engine, "decode_state"):
            try:
                out["decode"] = engine.decode_state()
            except Exception:
                pass
        if code == 200 and (failover
                            or out["backend"].get("status") == "wedged"
                            or (sup or {}).get("state")
                            in ("suspect", "wedged", "recovering")):
            out["status"] = "degraded"
        self._json(code, out, path="/healthz")

    def _trace(self):
        # the span store as Chrome Trace Event JSON: open in Perfetto /
        # chrome://tracing. ?uri=<trace_id> restricts to one record.
        trace_id = None
        if "?" in self.path:
            from urllib.parse import parse_qs
            q = parse_qs(self.path.split("?", 1)[1])
            trace_id = (q.get("uri") or q.get("trace_id") or [None])[0]
        self._json(200, profiling.chrome_trace(trace_id), path="/trace")

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            self._metrics()
        elif path == "/healthz":
            self._healthz()
        elif path == "/trace":
            self._trace()
        elif path == "/metrics/history":
            self._history()
        elif path == "/query":
            self._query()
        elif path == "/slo":
            mon = slo.get_monitor()
            mon.tick_if_stale()
            rep = mon.report()
            # live lane state alongside the burn report — one endpoint
            # answers "is admission control shedding and why"
            srv = self.server  # type: ignore[assignment]
            stream = srv.engine.stream if srv.engine else INPUT_STREAM
            try:
                client = BrokerClient(host=srv.broker_host,
                                      port=srv.broker_port)
                try:
                    rep.update(self._lane_state(client, stream,
                                                srv.engine))
                finally:
                    client.close()
            except (ConnectionError, OSError):
                pass        # the burn report stands on its own
            self._json(200, rep, path="/slo")
        else:
            self._json(200, {"status": "ok"}, path=path)

    # ---------------------------------------------------------------- POST
    def do_POST(self):
        srv = self.server  # type: ignore[assignment]
        if self.path != "/predict":
            self._json(404, {"error": "unknown path"})
            return
        tracer = telemetry.get_tracer()
        sampled = tracer.should_sample()
        t_req0 = time.perf_counter()
        in_q = out_q = None
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n))
            inputs = {k: schema.decode_tensor(v)
                      for k, v in payload["inputs"].items()}
            in_q = InputQueue(host=srv.broker_host,
                              port=srv.broker_port, cipher=srv.cipher)
            t_enq0 = time.perf_counter()
            uri = in_q.enqueue(payload.get("uri"),
                               priority=payload.get("priority"),
                               deadline_ms=payload.get("deadline_ms"),
                               generate=payload.get("generate"),
                               **inputs)
            t_enq1 = time.perf_counter()
        except ShedError as e:
            # admission control refused the lane at the broker: tell the
            # caller to back off NOW instead of letting it poll into a
            # timeout (429 = retry later, unlike the terminal 4xx family)
            self._json(429, {"error": f"lane shedding: {e}",
                             "code": "shed"})
            return
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            self._json(400, {"error": f"bad request: {e}"})
            return
        finally:
            if in_q is not None:
                in_q.close()
        try:
            out_q = OutputQueue(host=srv.broker_host,
                                port=srv.broker_port, cipher=srv.cipher)
            t_wait0 = time.perf_counter()
            result = out_q.query(uri, timeout=srv.timeout_s, delete=True)
            t_wait1 = time.perf_counter()
        except schema.DeadlineExpiredError as e:
            # distinct from the generic poll timeout below: the ENGINE
            # declared the deadline lapsed and stored a typed result
            self._json(504, {"uri": uri, "error": str(e),
                             "code": "expired"})
            return
        except schema.ServingError as e:
            self._json(422, {"uri": uri, "error": str(e)})
            return
        finally:
            if out_q is not None:
                out_q.close()
        if sampled:
            # the record's uri keys the trace, so these HTTP-side spans
            # land in the same trace as the engine's stage spans — the
            # "wait" span brackets the engine's whole "serve" span plus
            # both broker hops
            tracer.record(uri, "enqueue", t_enq0, t_enq1,
                          parent="http_predict")
            tracer.record(uri, "wait", t_wait0, t_wait1,
                          parent="http_predict")
            tracer.record(uri, "http_predict", t_req0, time.perf_counter())
        if result is None:
            self._json(504, {"uri": uri, "error": "timed out"})
        else:
            self._json(200, {"uri": uri,
                             "result": schema.encode_tensor(result)})


class FrontEnd:
    """``FrontEnd(broker_port, engine).start()`` → serving HTTP on ``port``."""

    def __init__(self, broker_port: int, engine=None, port: int = 0,
                 timeout: float = 30.0, cipher: schema.Cipher = None,
                 host: str = "127.0.0.1",
                 broker_host: str = "127.0.0.1",
                 max_backlog: int = 10000):
        # host="0.0.0.0" for containers (the EXPOSEd port must bind
        # beyond loopback to be reachable through docker port mapping)
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.broker_host = broker_host       # type: ignore[attr-defined]
        self._httpd.broker_port = broker_port       # type: ignore[attr-defined]
        self._httpd.engine = engine                 # type: ignore[attr-defined]
        self._httpd.timeout_s = timeout             # type: ignore[attr-defined]
        self._httpd.cipher = cipher                 # type: ignore[attr-defined]
        # /healthz flips to 503 "overloaded" past this input-queue depth
        self._httpd.max_backlog = int(max_backlog)  # type: ignore[attr-defined]
        self._httpd.http_counter = (                # type: ignore[attr-defined]
            telemetry.get_registry().counter(
                "zoo_http_requests_total", "Frontend HTTP requests",
                ("path", "code")))
        # BaseHTTPRequestHandler reads .timeout off the server for socket
        # timeouts; keep our own name distinct
        self._httpd.timeout = None                  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        if engine is not None and hasattr(engine, "set_advertise"):
            # tell the engine's heartbeat where peers can scrape this
            # replica; a wildcard bind advertises loopback (peers cannot
            # dial 0.0.0.0)
            adv = "127.0.0.1" if host in ("", "0.0.0.0", "::") else host
            engine.set_advertise(adv, self.port)
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FrontEnd":
        # idempotent (like ClusterServing.start): ``with FrontEnd().start()``
        # calls start twice; a second serve_forever loop on the same socket
        # races the first into a blocking accept() that shutdown() cannot
        # reach, leaking the thread past stop()
        if self._thread is not None:
            return self
        # an engine-less frontend (metrics-only sidecar) still needs the
        # history sampler ticking or /metrics/history serves empty rings
        timeseries.get_store().start()
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        t, self._thread = self._thread, None
        if t is not None:
            self._httpd.shutdown()
            t.join(timeout=5)
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
