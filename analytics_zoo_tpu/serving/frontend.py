"""HTTP frontend — synchronous predict endpoint over the serving plane.

Replaces the reference's akka-http frontend
(zoo/.../serving/http/FrontEndApp.scala:41,362: POST a payload, the handler
enqueues to Redis and awaits the result). Endpoints:

- ``POST /predict``  body = JSON ``{"inputs": {name: {dtype, shape, data}}}``
  (schema.py tensor encoding) → ``{"uri", "result": tensor}``
- ``GET  /metrics``  → engine metrics JSON by default; Prometheus text
  exposition (format 0.0.4) when the request asks for it — ``Accept:``
  containing ``text/plain`` or ``openmetrics``, or ``?format=prometheus``.
  The Prometheus view is the process-wide telemetry registry, so engine
  counters, stage histograms, JIT/transfer metrics and frontend request
  counters all scrape from one endpoint.
- ``GET  /healthz``  → readiness JSON: broker reachability, input queue
  depth, consumer-group backlog. 503 when the broker is unreachable or
  the queue depth exceeds ``max_backlog`` — load balancers use this to
  stop routing to a drowning replica.
- ``GET  /``         → liveness

stdlib ``ThreadingHTTPServer`` — no framework dependency; each request
thread owns its queue clients (the broker protocol is connection-oriented).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from analytics_zoo_tpu.common import profiling, telemetry
from analytics_zoo_tpu.serving import schema
from analytics_zoo_tpu.serving.broker import BrokerClient
from analytics_zoo_tpu.serving.client import (INPUT_STREAM, InputQueue,
                                              OutputQueue)

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # quiet
        pass

    def _count(self, path: str, code: int):
        self.server.http_counter.labels(  # type: ignore[attr-defined]
            path, str(code)).inc()

    def _json(self, code: int, obj, path: str = ""):
        body = json.dumps(obj).encode()
        self._count(path or self.path, code)
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, code: int, text: str, content_type: str):
        body = text.encode("utf-8")
        self._count(self.path.split("?", 1)[0], code)
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # ----------------------------------------------------------------- GET
    def _wants_prometheus(self) -> bool:
        if "format=prometheus" in self.path:
            return True
        accept = (self.headers.get("Accept") or "").lower()
        return "text/plain" in accept or "openmetrics" in accept

    def _metrics(self):
        if self._wants_prometheus():
            self._text(200, telemetry.prometheus_text(),
                       PROMETHEUS_CONTENT_TYPE)
            return
        engine = self.server.engine  # type: ignore[attr-defined]
        self._json(200, engine.metrics() if engine else {},
                   path="/metrics")

    def _healthz(self):
        srv = self.server  # type: ignore[assignment]
        engine = srv.engine
        stream = engine.stream if engine else INPUT_STREAM
        group = engine.group if engine else "serving"
        out = {"status": "ok", "broker": "up",
               "queue_depth": 0, "backlog": 0,
               "engine": bool(engine and engine._thread is not None)}
        code = 200
        client = None
        try:
            client = BrokerClient(host=srv.broker_host,
                                  port=srv.broker_port)
            out["queue_depth"] = client.xlen(stream)
            try:
                out["backlog"] = client.xpending(stream, group)
            except Exception:
                # group not created yet (no engine started): not an error
                out["backlog"] = 0
        except (ConnectionError, OSError) as e:
            out.update(status="unavailable", broker=f"down: {e}")
            code = 503
        finally:
            if client is not None:
                client.close()
        if code == 200 and out["queue_depth"] > srv.max_backlog:
            out["status"] = "overloaded"
            code = 503
        # surface the JAX backend so a CPU-fallback or wedged-device
        # replica is visible from the probe itself; the probe thread is
        # timeout-joined, so a wedged backend can never hang /healthz
        out["backend"] = profiling.backend_state(timeout_s=2.0)
        if out["backend"].get("status") == "wedged" and code == 200:
            out["status"] = "degraded"
        self._json(code, out, path="/healthz")

    def _trace(self):
        # the span store as Chrome Trace Event JSON: open in Perfetto /
        # chrome://tracing. ?uri=<trace_id> restricts to one record.
        trace_id = None
        if "?" in self.path:
            from urllib.parse import parse_qs
            q = parse_qs(self.path.split("?", 1)[1])
            trace_id = (q.get("uri") or q.get("trace_id") or [None])[0]
        self._json(200, profiling.chrome_trace(trace_id), path="/trace")

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            self._metrics()
        elif path == "/healthz":
            self._healthz()
        elif path == "/trace":
            self._trace()
        else:
            self._json(200, {"status": "ok"}, path=path)

    # ---------------------------------------------------------------- POST
    def do_POST(self):
        srv = self.server  # type: ignore[assignment]
        if self.path != "/predict":
            self._json(404, {"error": "unknown path"})
            return
        tracer = telemetry.get_tracer()
        sampled = tracer.should_sample()
        t_req0 = time.perf_counter()
        in_q = out_q = None
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n))
            inputs = {k: schema.decode_tensor(v)
                      for k, v in payload["inputs"].items()}
            in_q = InputQueue(host=srv.broker_host,
                              port=srv.broker_port, cipher=srv.cipher)
            t_enq0 = time.perf_counter()
            uri = in_q.enqueue(payload.get("uri"), **inputs)
            t_enq1 = time.perf_counter()
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            self._json(400, {"error": f"bad request: {e}"})
            return
        finally:
            if in_q is not None:
                in_q.close()
        try:
            out_q = OutputQueue(host=srv.broker_host,
                                port=srv.broker_port, cipher=srv.cipher)
            t_wait0 = time.perf_counter()
            result = out_q.query(uri, timeout=srv.timeout_s, delete=True)
            t_wait1 = time.perf_counter()
        except schema.ServingError as e:
            self._json(422, {"uri": uri, "error": str(e)})
            return
        finally:
            if out_q is not None:
                out_q.close()
        if sampled:
            # the record's uri keys the trace, so these HTTP-side spans
            # land in the same trace as the engine's stage spans — the
            # "wait" span brackets the engine's whole "serve" span plus
            # both broker hops
            tracer.record(uri, "enqueue", t_enq0, t_enq1,
                          parent="http_predict")
            tracer.record(uri, "wait", t_wait0, t_wait1,
                          parent="http_predict")
            tracer.record(uri, "http_predict", t_req0, time.perf_counter())
        if result is None:
            self._json(504, {"uri": uri, "error": "timed out"})
        else:
            self._json(200, {"uri": uri,
                             "result": schema.encode_tensor(result)})


class FrontEnd:
    """``FrontEnd(broker_port, engine).start()`` → serving HTTP on ``port``."""

    def __init__(self, broker_port: int, engine=None, port: int = 0,
                 timeout: float = 30.0, cipher: schema.Cipher = None,
                 host: str = "127.0.0.1",
                 broker_host: str = "127.0.0.1",
                 max_backlog: int = 10000):
        # host="0.0.0.0" for containers (the EXPOSEd port must bind
        # beyond loopback to be reachable through docker port mapping)
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.broker_host = broker_host       # type: ignore[attr-defined]
        self._httpd.broker_port = broker_port       # type: ignore[attr-defined]
        self._httpd.engine = engine                 # type: ignore[attr-defined]
        self._httpd.timeout_s = timeout             # type: ignore[attr-defined]
        self._httpd.cipher = cipher                 # type: ignore[attr-defined]
        # /healthz flips to 503 "overloaded" past this input-queue depth
        self._httpd.max_backlog = int(max_backlog)  # type: ignore[attr-defined]
        self._httpd.http_counter = (                # type: ignore[attr-defined]
            telemetry.get_registry().counter(
                "zoo_http_requests_total", "Frontend HTTP requests",
                ("path", "code")))
        # BaseHTTPRequestHandler reads .timeout off the server for socket
        # timeouts; keep our own name distinct
        self._httpd.timeout = None                  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FrontEnd":
        # idempotent (like ClusterServing.start): ``with FrontEnd().start()``
        # calls start twice; a second serve_forever loop on the same socket
        # races the first into a blocking accept() that shutdown() cannot
        # reach, leaking the thread past stop()
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        t, self._thread = self._thread, None
        if t is not None:
            self._httpd.shutdown()
            t.join(timeout=5)
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
