"""Serving clients — InputQueue / OutputQueue.

API parity with the reference python client (pyzoo/zoo/serving/client.py:
``InputQueue:82`` with ``enqueue:144``, ``OutputQueue:234`` with
``query``/``dequeue``): enqueue named tensors under a uri, poll the result
store for the answer. The transport is the zbroker stream/hash protocol
instead of Redis, and tensors ride the schema.py record format.
"""

from __future__ import annotations

import time
import uuid
from typing import Dict, Optional

import numpy as np

from analytics_zoo_tpu.common import telemetry
from analytics_zoo_tpu.serving.broker import BrokerClient, ShedError
from analytics_zoo_tpu.serving import schema

INPUT_STREAM = "serving_stream"
RESULT_HASH = "result"

__all__ = ["InputQueue", "OutputQueue", "ShedError",
           "INPUT_STREAM", "RESULT_HASH"]


class InputQueue:
    def __init__(self, host: str = "127.0.0.1", port: int = 6399,
                 stream: str = INPUT_STREAM, cipher: schema.Cipher = None,
                 arrow: bool = False):
        """``arrow=True`` encodes records in the REFERENCE client's Arrow
        wire format (ref client.py:149 data_to_b64) instead of the native
        JSON tensors — the engine auto-detects either."""
        self._client = BrokerClient(host, port)
        self.stream = stream
        self.cipher = cipher
        self.arrow = bool(arrow)
        self._tracer = telemetry.get_tracer()

    @staticmethod
    def _coerce(v):
        """ndarray (incl. string tensors) passes through; raw encoded
        image bytes become an ImageBytes entry — decoded and preprocessed
        ENGINE-side, like the reference client's image enqueue
        (client.py:144 b64-encodes the file's bytes; the server decodes in
        PreProcessing.scala:67-90). File paths go through
        ``enqueue_image`` — a blanket str->open() here would break string
        tensors and read arbitrary local files."""
        if isinstance(v, schema.ImageBytes):
            return v
        if isinstance(v, (bytes, bytearray)):
            return schema.ImageBytes(bytes(v))
        return np.asarray(v)

    def _shed_counter(self, priority: str):
        """Client-observed shed rejections: an XADD the broker refused
        never reaches the engine, so the client is the only process that
        can count it (the zero-silent-drops ledger needs every terminal
        outcome on a counter)."""
        return telemetry.get_registry().counter(
            "zoo_serving_shed_total",
            "enqueues rejected by lane admission control",
            ("stream", "priority")).labels(self.stream, priority)

    def _encode(self, uri: Optional[str], inputs: Dict,
                priority: Optional[str] = None,
                deadline_ms: Optional[float] = None,
                generate: Optional[Dict] = None
                ) -> "tuple[str, str, Optional[tuple], str]":
        """(uri, payload, trace, lane) — ``trace`` is ``(t_enc_pc,
        sampled)`` for natively-encoded records (the stamp the engine's
        queue-wait accounting reads), None for Arrow records (the
        reference wire format has no side channel, so Arrow records get
        lane routing but no deadline or generate options). ``lane`` is
        the validated priority the broker partitions delivery on."""
        if not inputs:
            raise ValueError("enqueue needs at least one named tensor")
        lane = schema.validate_priority(priority)
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
        gen = schema.validate_generate(generate)
        uri = schema.validate_uri(uri or uuid.uuid4().hex)
        coerced = {k: self._coerce(v) for k, v in inputs.items()}
        if self.arrow:
            if gen is not None:
                raise ValueError(
                    "generate requests need the native record format — "
                    "the Arrow wire format carries no side channel")
            return uri, schema.encode_record_arrow(
                uri, coerced, self.cipher), None, lane
        # dual-clock stamp: perf_counter is CLOCK_MONOTONIC on Linux
        # (comparable across processes on ONE host — the engine checks
        # plausibility before trusting it); t_wall is the cross-host
        # fallback, tolerant of NTP slew at queue-wait magnitudes
        sampled = self._tracer.should_sample()
        t_pc = time.perf_counter()
        trace = {"id": uri, "t_pc": t_pc,
                 "t_wall": time.time(),  # zoolint: disable=wallclock-hotpath
                 "s": int(sampled)}
        if lane != schema.DEFAULT_PRIORITY:
            trace["p"] = lane
        if deadline_ms is not None:
            trace["d"] = float(deadline_ms)
        if gen is not None:
            trace["g"] = gen
        payload = schema.encode_record(uri, coerced, self.cipher,
                                       trace=trace)
        return uri, payload, (t_pc, sampled), lane

    def enqueue(self, uri: Optional[str] = None,
                priority: Optional[str] = None,
                deadline_ms: Optional[float] = None,
                generate: Optional[Dict] = None, **inputs) -> str:
        """``enqueue("img1", x=ndarray)``; returns the uri (generated when
        not given). Multi-input models pass several named tensors.
        ``enqueue("img1", image=jpeg_bytes)`` sends the raw encoded image
        for engine-side decode + preprocessing (``enqueue_image`` for
        file paths).

        ``priority`` routes the record onto a broker lane
        (``schema.PRIORITIES``; default "default") and ``deadline_ms``
        bounds how stale a result is still useful — the engine stores an
        explicit expired error once it lapses.

        ``generate`` turns the record into an autoregressive generate
        request (``{"max_new_tokens": 16, "mode": "greedy",
        "temperature": 1.0, "seed": None}``, all optional): the record
        carries the encoder tensor plus a ``start`` tensor (the decoder
        start sign), and the engine answers with the generated
        ``[steps, dim]`` sequence from the model's bucketed decode loop
        instead of a one-shot prediction.

        The names ``priority``, ``deadline_ms`` and ``generate`` are
        therefore reserved and cannot name input tensors. Raises
        :class:`ShedError` immediately when admission control is shedding
        the lane — a fast-fail instead of a poll timeout."""
        uri, payload, trace, lane = self._encode(uri, inputs, priority,
                                                 deadline_ms, generate)
        try:
            self._client.xadd(self.stream, payload, lane=lane)
        except ShedError:
            self._shed_counter(lane).inc()
            raise
        if trace is not None and trace[1]:
            # encode + broker write, on the record's own trace id — the
            # timeline head GET /trace?uri= shows before queue_wait
            self._tracer.record(uri, "client_enqueue", trace[0],
                                time.perf_counter())
        return uri

    def enqueue_image(self, uri: Optional[str] = None, image=None,
                      key: str = "image") -> str:
        """Enqueue one raw encoded image — bytes or a path to a
        jpeg/png file (the reference client's image enqueue takes local
        file uris, client.py:144). The ENGINE decodes and runs the
        configured preprocessing chain."""
        if isinstance(image, str):
            with open(image, "rb") as f:
                image = f.read()
        if not isinstance(image, (bytes, bytearray, schema.ImageBytes)):
            raise TypeError("enqueue_image takes encoded image bytes or "
                            "a file path")
        return self.enqueue(uri, **{key: schema.ImageBytes(bytes(image))
                                    if not isinstance(image,
                                                      schema.ImageBytes)
                                    else image})

    def enqueue_batch(self, records, priority: Optional[str] = None,
                      deadline_ms: Optional[float] = None,
                      generate: Optional[Dict] = None) -> "list[str]":
        """Enqueue many records in pipelined socket writes — the high-
        throughput path (the reference client achieves the same with a
        redis-py pipeline of XADDs). ``records`` is an iterable of
        ``(uri, {name: tensor, ...})`` pairs; pass ``None`` as a uri to
        have one generated. Returns the uris in order. ``priority`` /
        ``deadline_ms`` / ``generate`` apply to every record in the
        batch; a shedding lane raises :class:`ShedError` (some earlier
        records of the batch may have been accepted — uris are returned
        only on full success)."""
        uris, cmds, traces = [], [], []
        lane = schema.validate_priority(priority)
        for uri, inputs in records:
            uri, payload, trace, _ = self._encode(uri, inputs, priority,
                                                  deadline_ms, generate)
            uris.append(uri)
            traces.append(trace)
            cmds.append(("XADD", self.stream, payload, lane))
        try:
            self._client.pipeline(cmds)
        except ShedError:
            self._shed_counter(lane).inc()
            raise
        t1 = time.perf_counter()
        for uri, trace in zip(uris, traces):
            if trace is not None and trace[1]:
                self._tracer.record(uri, "client_enqueue", trace[0], t1)
        return uris

    def __len__(self):
        return self._client.xlen(self.stream)

    def close(self):
        self._client.close()


class OutputQueue:
    def __init__(self, host: str = "127.0.0.1", port: int = 6399,
                 result_key: str = RESULT_HASH, cipher: schema.Cipher = None):
        self._client = BrokerClient(host, port)
        self.result_key = result_key
        self.cipher = cipher

    def query(self, uri: str, timeout: float = 0.0,
              poll_interval: float = 0.01,
              delete: bool = False) -> Optional[np.ndarray]:
        """Result for ``uri`` or None. ``timeout > 0`` polls until then
        (the reference client polls the Redis hash the same way).
        ``delete=True`` removes the entry once fetched — one-shot consumers
        (the HTTP frontend) use it so the result hash stays bounded."""
        # monotonic clock: a wall-clock step (NTP) must not stretch or
        # collapse the polling deadline
        deadline = time.monotonic() + timeout
        while True:
            val = self._client.hget(self.result_key, uri)
            if val is not None:
                if delete:
                    self._client.hdel(self.result_key, uri)
                return schema.decode_result(val, self.cipher)
            if time.monotonic() >= deadline:
                return None
            time.sleep(poll_interval)

    def query_many(self, uris, timeout: float = 0.0,
                   poll_interval: float = 0.01,
                   delete: bool = False) -> Dict[str, Optional[np.ndarray]]:
        """Results for many uris, polling with pipelined HGETs (one socket
        roundtrip per poll instead of one per uri). Returns
        ``{uri: ndarray | None}``; None marks uris still unanswered at the
        deadline."""
        pending = list(dict.fromkeys(uris))
        out: Dict[str, Optional[np.ndarray]] = {u: None for u in pending}
        deadline = time.monotonic() + timeout
        while pending:
            vals = self._client.pipeline(
                ("HGET", self.result_key, u) for u in pending)
            hits = [(u, v) for u, v in zip(pending, vals) if v is not None]
            for u, v in hits:
                out[u] = schema.decode_result(v, self.cipher)
            if hits and delete:
                self._client.pipeline(
                    ("HDEL", self.result_key, u) for u, _ in hits)
            pending = [u for u in pending if out[u] is None]
            if not pending or time.monotonic() >= deadline:
                break
            time.sleep(poll_interval)
        return out

    def dequeue(self) -> Dict[str, np.ndarray]:
        """Drain all available results (ref OutputQueue.dequeue)."""
        out = {}
        for uri in self._client.hkeys(self.result_key):
            val = self._client.hget(self.result_key, uri)
            if val is not None and self._client.hdel(self.result_key, uri):
                out[uri] = schema.decode_result(val, self.cipher)
        return out

    def close(self):
        self._client.close()
