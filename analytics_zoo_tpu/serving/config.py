"""Serving config — the reference's ``config.yaml`` surface
(ref zoo/.../serving/utils/ConfigParser.scala:27 and
scripts/cluster-serving/config.yaml: model path, redis host/port,
batch size, record encryption flag).

Parsed with PyYAML when available; otherwise a built-in reader that covers
the two-level ``section: / key: value`` shape the serving config uses.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


def _parse_scalar(s: str):
    s = s.strip().strip('"').strip("'")
    low = s.lower()
    if low in ("true", "yes"):
        return True
    if low in ("false", "no"):
        return False
    if low in ("null", "~", ""):
        return None
    for cast in (int, float):
        try:
            return cast(s)
        except ValueError:
            pass
    return s


def _mini_yaml(text: str) -> dict:
    root: dict = {}
    section = None
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].rstrip()
        if not line.strip():
            continue
        indented = line[0] in " \t"
        key, _, val = line.strip().partition(":")
        if not _:
            continue
        if not indented:
            if val.strip():
                root[key] = _parse_scalar(val)
                section = None
            else:
                section = root.setdefault(key, {})
        elif section is not None:
            section[key] = _parse_scalar(val)
    return root


def load_yaml(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    try:
        import yaml
        return yaml.safe_load(text) or {}
    except ImportError:
        return _mini_yaml(text)


@dataclass
class ServingConfig:
    model_path: str = ""
    broker_host: str = "127.0.0.1"
    broker_port: int = 6399
    batch_size: int = 8
    record_encrypted: bool = False
    stream: str = "serving_stream"
    result_key: str = "result"
    # engine-side raw-image preprocessing (ref PreProcessing.scala is
    # driven by the serving config the same way): either a model-zoo
    # preset name, or explicit resize/crop/mean/scale
    image_preset: Optional[str] = None
    image_source: str = "imagenet"
    image_resize: Optional[int] = None
    image_crop: Optional[int] = None
    image_mean: Optional[tuple] = None
    image_scale: float = 1.0

    @classmethod
    def load(cls, path: str) -> "ServingConfig":
        raw = load_yaml(path)
        model = raw.get("model", {}) or {}
        data = raw.get("data", {}) or {}
        params = raw.get("params", {}) or {}
        pre = raw.get("preprocessing", {}) or {}
        src = (data.get("src") or
               f"{cls.broker_host}:{cls.broker_port}")
        host, _, port = str(src).partition(":")
        mean = pre.get("mean")
        if isinstance(mean, str):
            mean = tuple(float(v) for v in mean.split(","))
        return cls(
            model_path=model.get("path", "") or "",
            broker_host=host or "127.0.0.1",
            broker_port=int(port or 6399),
            batch_size=int(params.get("batch_size", 8) or 8),
            record_encrypted=bool(data.get("record_encrypted", False)),
            stream=data.get("stream", "serving_stream") or "serving_stream",
            result_key=data.get("result_key", "result") or "result",
            image_preset=pre.get("preset") or None,
            image_source=pre.get("source", "imagenet") or "imagenet",
            image_resize=(int(pre["resize"]) if pre.get("resize")
                          else None),
            image_crop=int(pre["crop"]) if pre.get("crop") else None,
            image_mean=mean,
            image_scale=float(pre.get("scale", 1.0) or 1.0))

    def build_image_preprocess(self):
        """The engine's raw-image chain from this config, or None when no
        ``preprocessing:`` section was given."""
        if self.image_preset:
            from analytics_zoo_tpu.serving.engine import image_pipeline
            return image_pipeline(self.image_preset,
                                  source=self.image_source)
        if not (self.image_resize or self.image_crop or self.image_mean
                or self.image_scale != 1.0):
            return None
        from analytics_zoo_tpu.feature.image import (
            ChainedPreprocessing, ImageCenterCrop,
            ImageChannelScaledNormalizer, ImageMatToTensor, ImageResize,
        )
        steps = []
        if self.image_resize:
            steps.append(ImageResize(self.image_resize, self.image_resize))
        if self.image_crop:
            steps.append(ImageCenterCrop(self.image_crop, self.image_crop))
        if self.image_mean or self.image_scale != 1.0:
            mean = self.image_mean or (0.0, 0.0, 0.0)
            steps.append(ImageChannelScaledNormalizer(
                *mean, self.image_scale))
        steps.append(ImageMatToTensor())
        from analytics_zoo_tpu.serving.engine import ndarray_chain
        return ndarray_chain(ChainedPreprocessing(steps))
