"""Serving config — the reference's ``config.yaml`` surface
(ref zoo/.../serving/utils/ConfigParser.scala:27 and
scripts/cluster-serving/config.yaml: model path, redis host/port,
batch size, record encryption flag).

Parsed with PyYAML when available; otherwise a built-in reader that covers
the two-level ``section: / key: value`` shape the serving config uses.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


def _parse_scalar(s: str):
    s = s.strip().strip('"').strip("'")
    low = s.lower()
    if low in ("true", "yes"):
        return True
    if low in ("false", "no"):
        return False
    if low in ("null", "~", ""):
        return None
    for cast in (int, float):
        try:
            return cast(s)
        except ValueError:
            pass
    return s


def _mini_yaml(text: str) -> dict:
    root: dict = {}
    section = None
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].rstrip()
        if not line.strip():
            continue
        indented = line[0] in " \t"
        key, _, val = line.strip().partition(":")
        if not _:
            continue
        if not indented:
            if val.strip():
                root[key] = _parse_scalar(val)
                section = None
            else:
                section = root.setdefault(key, {})
        elif section is not None:
            section[key] = _parse_scalar(val)
    return root


def load_yaml(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    try:
        import yaml
        return yaml.safe_load(text) or {}
    except ImportError:
        return _mini_yaml(text)


@dataclass
class ServingConfig:
    model_path: str = ""
    broker_host: str = "127.0.0.1"
    broker_port: int = 6399
    batch_size: int = 8
    record_encrypted: bool = False
    stream: str = "serving_stream"
    result_key: str = "result"

    @classmethod
    def load(cls, path: str) -> "ServingConfig":
        raw = load_yaml(path)
        model = raw.get("model", {}) or {}
        data = raw.get("data", {}) or {}
        params = raw.get("params", {}) or {}
        src = (data.get("src") or
               f"{cls.broker_host}:{cls.broker_port}")
        host, _, port = str(src).partition(":")
        return cls(
            model_path=model.get("path", "") or "",
            broker_host=host or "127.0.0.1",
            broker_port=int(port or 6399),
            batch_size=int(params.get("batch_size", 8) or 8),
            record_encrypted=bool(data.get("record_encrypted", False)),
            stream=data.get("stream", "serving_stream") or "serving_stream",
            result_key=data.get("result_key", "result") or "result")
