// zbroker — native stream broker for Cluster Serving.
//
// TPU-native analog of the Redis server the reference uses as its serving
// data plane (ref zoo/.../serving/engine/FlinkRedisSource.scala:32-106
// consumes via XREADGROUP, FlinkRedisSink XADDs results; the python client
// pyzoo/zoo/serving/client.py speaks the same stream + hash commands).
// Rather than embed a Redis dependency, this is a single-file C++ broker
// speaking a line protocol with the subset of semantics serving needs:
//
//   PING                                        -> +PONG
//   XADD <stream> <b64> [lane]                  -> +<id> | -SHED ... when
//                                                  the lane's shed flag is
//                                                  set (lane defaults to
//                                                  "default")
//   XLEN <stream> [lane]                        -> :<n> (lane-filtered
//                                                  when lane given)
//   XREADGROUP <group> <consumer> <stream> <count> <block_ms> [lanes]
//                                               -> *<n> then n lines
//                                                  "<id> <b64>", or
//                                                  "<id> <lane> <b64>"
//                                                  when lanes (comma-
//                                                  separated priority
//                                                  order) is given —
//                                                  delivery drains lanes
//                                                  in that order
//   XACK <stream> <group> <id>                  -> :<n-acked>
//   XCLAIM <stream> <group> <consumer> <min_idle_ms> <count> [lanes]
//                                               -> *<n> then n lines
//                                                  "<id> <b64>" (laneless)
//                                                  or "<id> <lane> <b64>",
//                                                  claiming in lane order
//   XPENDING <stream> <group>                   -> :<n-pending>
//   XPENDING <stream> <group> DETAIL            -> *<n> then n lines
//                                                  "<consumer> <count>"
//   XSHED <stream> <lane> <0|1>                 -> +OK (set/clear the
//                                                  lane's admission shed
//                                                  flag)
//   XSHED <stream>                              -> *<n> then n lines
//                                                  "<lane>" (shedding)
//   HSET <key> <field> <b64>                    -> +OK
//   HGET <key> <field>                          -> $<b64> | $-1
//   HKEYS <key>                                 -> *<n> then n lines "<field>"
//   HDEL <key> <field>                          -> :<n-deleted>
//   DEL <key>                                   -> +OK
//   SHUTDOWN                                    -> +BYE (process exits)
//
// Concurrency: one thread per connection; one global mutex over state (the
// payloads are opaque b64 strings, so critical sections are pointer work);
// blocking XREADGROUP waits on a condition_variable. Delivery semantics
// mirror Redis streams: per-(stream,group,lane) cursor of last-delivered
// id (one id space across lanes, so ack/lease/GC semantics stay unified
// while delivery partitions by priority); un-ACKed entries are tracked per
// group with their owning consumer and last-delivery time — a delivery
// LEASE: XCLAIM transfers entries whose lease has been idle past
// min_idle_ms to another consumer (never back to their current owner),
// and XPENDING DETAIL attributes the backlog per consumer for crash
// visibility.
//
// Build: g++ -O2 -std=c++17 -pthread -o zbroker zbroker.cpp

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Entry {
  long long id;
  std::string payload;
  std::string lane;  // priority class; "default" when XADD gave none
};

struct PendingEntry {
  std::string consumer;  // current lease owner
  long long ts = 0;      // last delivery (ms, steady clock) — the lease
  long long deliveries = 0;  // total deliveries incl. XCLAIM redeliveries
  std::string lane;          // so XCLAIM can recover by priority
};

struct Group {
  // last delivered id PER LANE: draining one lane must not mark another
  // lane's (lower-id) entries as already seen
  std::map<std::string, long long> cursor;
  // delivered-not-acked: id -> lease record, so XCLAIM can re-deliver
  // entries whose owning consumer died (lease idle too long) and
  // XPENDING DETAIL can attribute backlog per consumer
  std::map<long long, PendingEntry> pending;
};

long long NowMs() {
  // steady clock: TTL/idle arithmetic must not jump with NTP steps or
  // suspend/resume (all uses are relative durations)
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

struct Stream {
  std::vector<Entry> entries;
  long long next_id = 1;
  std::map<std::string, Group> groups;
};

std::mutex g_mu;
std::condition_variable g_cv;
std::map<std::string, Stream> g_streams;
// stream -> lanes whose XADDs are rejected (admission control, see XSHED)
std::map<std::string, std::set<std::string>> g_shed;
std::map<std::string, std::map<std::string, std::string>> g_hashes;
// last-write time per hash field: the result hash would otherwise grow
// forever if a client never collects (TTL eviction bounds broker memory;
// Redis gets this from EXPIRE, ref serving keeps results in a Redis hash)
std::map<std::string, std::map<std::string, long long>> g_hash_times;
// write-order FIFO per key: g_hash_times is name-ordered, so bounding the
// HSET-path eviction to the OLDEST fields needs a separate queue. Entries
// for fields already evicted (or since rewritten) are skipped on pop via
// a timestamp match against g_hash_times.
std::map<std::string,
         std::deque<std::pair<std::string, long long>>> g_hash_fifo;
long long g_hash_ttl_ms = 600000;  // 0 disables
bool g_shutdown = false;
int g_srv_fd = -1;

// drop expired fields of one hash key; caller holds g_mu
void EvictExpired(const std::string& key, long long now_ms) {
  if (g_hash_ttl_ms <= 0) return;
  auto t = g_hash_times.find(key);
  if (t == g_hash_times.end()) return;
  auto h = g_hashes.find(key);
  for (auto it = t->second.begin(); it != t->second.end();) {
    if (now_ms - it->second >= g_hash_ttl_ms) {
      if (h != g_hashes.end()) h->second.erase(it->first);
      it = t->second.erase(it);
    } else {
      ++it;
    }
  }
  if (t->second.empty()) {
    g_hash_times.erase(t);
    g_hash_fifo.erase(key);  // all fields gone -> queue is all stale
  }
  if (h != g_hashes.end() && h->second.empty()) g_hashes.erase(h);
}

// Amortized eviction for the HSET hot path: pop at most `limit` expired
// entries off the key's write-order FIFO. A full-key scan here is
// O(live fields) per write exactly when the result consumer is slow —
// the scenario TTL exists for; the ttl/4 sweeper bounds memory anyway.
// Caller holds g_mu.
void EvictSome(const std::string& key, long long now_ms, int limit) {
  if (g_hash_ttl_ms <= 0) return;
  auto q = g_hash_fifo.find(key);
  if (q == g_hash_fifo.end()) return;
  auto t = g_hash_times.find(key);
  auto h = g_hashes.find(key);
  int n = 0;
  while (!q->second.empty() && n < limit) {
    auto& front = q->second.front();
    bool current = false;
    if (t != g_hash_times.end()) {
      auto ft = t->second.find(front.first);
      // the queue entry is the field's CURRENT write only if the
      // timestamps match — otherwise it's a tombstone (field HDEL'd by
      // the consumer, or rewritten with a later queue entry covering it)
      current = ft != t->second.end() && ft->second == front.second;
    }
    if (!current) {
      // tombstones pop regardless of age: under a healthy
      // write-then-HDEL serving flow nearly every entry becomes one,
      // and keeping them for the full TTL would hold O(rate x TTL)
      // memory that the pre-FIFO implementation never did
      q->second.pop_front();
      ++n;
      continue;
    }
    if (now_ms - front.second < g_hash_ttl_ms) break;  // oldest is live
    t->second.erase(front.first);
    if (h != g_hashes.end()) h->second.erase(front.first);
    q->second.pop_front();
    ++n;
  }
  if (q->second.empty()) g_hash_fifo.erase(q);
  if (t != g_hash_times.end() && t->second.empty()) g_hash_times.erase(t);
  if (h != g_hashes.end() && h->second.empty()) g_hashes.erase(h);
}

// periodic sweep so memory stays bounded even with no client traffic
void SweeperLoop() {
  std::unique_lock<std::mutex> lk(g_mu);
  while (!g_shutdown) {
    long long wait_ms = g_hash_ttl_ms > 0 ? std::max(g_hash_ttl_ms / 4,
                                                     1000LL)
                                          : 60000LL;
    g_cv.wait_for(lk, std::chrono::milliseconds(wait_ms),
                  []() { return g_shutdown; });
    if (g_shutdown) break;
    long long now_ms = NowMs();
    std::vector<std::string> keys;
    for (auto& kv : g_hash_times) keys.push_back(kv.first);
    for (auto& k : keys) EvictExpired(k, now_ms);
  }
}

// Per-connection receive buffer: bulk recv instead of byte-at-a-time
// syscalls, and leftover bytes carry over so pipelined commands (many
// lines in one TCP segment) parse correctly.
struct ConnBuf {
  std::string buf;
  size_t pos = 0;
};

std::string ReadLine(int fd, ConnBuf* cb, bool* ok) {
  while (true) {
    size_t nl = cb->buf.find('\n', cb->pos);
    if (nl != std::string::npos) {
      std::string line = cb->buf.substr(cb->pos, nl - cb->pos);
      cb->pos = nl + 1;
      if (cb->pos > (1u << 20)) {  // compact consumed prefix
        cb->buf.erase(0, cb->pos);
        cb->pos = 0;
      }
      if (!line.empty() && line.back() == '\r') line.pop_back();
      *ok = true;
      return line;
    }
    if (cb->buf.size() - cb->pos > (64u << 20)) {
      *ok = false;
      return std::string();
    }
    char chunk[65536];
    ssize_t n = recv(fd, chunk, sizeof(chunk), 0);
    if (n <= 0) {
      *ok = false;
      return std::string();
    }
    cb->buf.append(chunk, static_cast<size_t>(n));
  }
}

void SendAll(int fd, const std::string& s) {
  size_t off = 0;
  while (off < s.size()) {
    ssize_t n = send(fd, s.data() + off, s.size() - off, MSG_NOSIGNAL);
    if (n <= 0) return;
    off += static_cast<size_t>(n);
  }
}

std::vector<std::string> Split(const std::string& s, size_t max_parts) {
  std::vector<std::string> out;
  size_t i = 0;
  while (i < s.size() && out.size() + 1 < max_parts) {
    size_t j = s.find(' ', i);
    if (j == std::string::npos) break;
    out.push_back(s.substr(i, j - i));
    i = j + 1;
  }
  if (i <= s.size()) out.push_back(s.substr(i));
  return out;
}

// "a,b,c" -> {"a","b","c"} (the lanes argument of XREADGROUP/XCLAIM)
std::vector<std::string> SplitComma(const std::string& s) {
  std::vector<std::string> out;
  size_t i = 0;
  while (i <= s.size()) {
    size_t j = s.find(',', i);
    if (j == std::string::npos) j = s.size();
    if (j > i) out.push_back(s.substr(i, j - i));
    i = j + 1;
  }
  return out;
}

void HandleConn(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  ConnBuf cb;
  while (true) {
    bool ok;
    std::string line = ReadLine(fd, &cb, &ok);
    if (!ok) break;
    if (line.empty()) continue;
    std::vector<std::string> p = Split(line, 8);
    const std::string& cmd = p[0];

    if (cmd == "PING") {
      SendAll(fd, "+PONG\n");
    } else if (cmd == "SHUTDOWN") {
      SendAll(fd, "+BYE\n");
      {
        std::lock_guard<std::mutex> lk(g_mu);
        g_shutdown = true;
      }
      g_cv.notify_all();
      if (g_srv_fd >= 0) shutdown(g_srv_fd, SHUT_RDWR);  // unblock accept()
      break;
    } else if (cmd == "XADD" && p.size() >= 3) {
      const std::string lane = p.size() >= 4 ? p[3] : "default";
      long long id = 0;
      bool shed = false;
      {
        std::lock_guard<std::mutex> lk(g_mu);
        auto sh = g_shed.find(p[1]);
        if (sh != g_shed.end() && sh->second.count(lane)) {
          shed = true;
        } else {
          Stream& st = g_streams[p[1]];
          id = st.next_id++;
          st.entries.push_back({id, p[2], lane});
        }
      }
      if (shed) {
        SendAll(fd, "-SHED lane " + lane + " is shedding\n");
      } else {
        g_cv.notify_all();
        SendAll(fd, "+" + std::to_string(id) + "\n");
      }
    } else if (cmd == "XLEN" && p.size() >= 2) {
      std::lock_guard<std::mutex> lk(g_mu);
      size_t n = 0;
      if (p.size() >= 3 && !p[2].empty()) {
        for (const Entry& e : g_streams[p[1]].entries)
          if (e.lane == p[2]) ++n;
      } else {
        n = g_streams[p[1]].entries.size();
      }
      SendAll(fd, ":" + std::to_string(n) + "\n");
    } else if (cmd == "XREADGROUP" && p.size() >= 6) {
      const std::string &group = p[1], &consumer = p[2], &stream = p[3];
      int count = atoi(p[4].c_str());
      int block_ms = atoi(p[5].c_str());
      // optional lanes arg: comma-separated delivery order — lanes[0]
      // drains first. Empty/missing = legacy laneless delivery in id
      // order, replies without the lane field.
      const bool laned = p.size() >= 7 && !p[6].empty();
      std::vector<std::string> lanes =
          laned ? SplitComma(p[6]) : std::vector<std::string>{""};
      std::vector<Entry> got;
      {
        std::unique_lock<std::mutex> lk(g_mu);
        auto deliver = [&]() {
          Stream& st = g_streams[stream];
          Group& gr = st.groups[group];
          long long now_ms = NowMs();
          for (const std::string& want : lanes) {
            for (const Entry& e : st.entries) {
              if (laned && e.lane != want) continue;
              auto c = gr.cursor.find(e.lane);
              if (c != gr.cursor.end() && e.id <= c->second) continue;
              got.push_back(e);
              gr.cursor[e.lane] = e.id;
              gr.pending[e.id] = PendingEntry{consumer, now_ms, 1, e.lane};
              if (static_cast<int>(got.size()) >= count) return true;
            }
          }
          return !got.empty();
        };
        if (!deliver() && block_ms > 0) {
          g_cv.wait_for(lk, std::chrono::milliseconds(block_ms), [&]() {
            return g_shutdown || deliver();
          });
        }
      }
      std::ostringstream os;
      os << "*" << got.size() << "\n";
      for (const Entry& e : got) {
        if (laned) os << e.id << " " << e.lane << " " << e.payload << "\n";
        else os << e.id << " " << e.payload << "\n";
      }
      SendAll(fd, os.str());
    } else if (cmd == "XACK" && p.size() >= 4) {
      int n = 0;
      {
        std::lock_guard<std::mutex> lk(g_mu);
        Stream& st = g_streams[p[1]];
        Group& gr = st.groups[p[2]];
        n = static_cast<int>(gr.pending.erase(atoll(p[3].c_str())));
        // GC: drop entries delivered to every group and acked everywhere
        // (Redis needs explicit XTRIM; serving never re-reads old ids).
        // Cursors are per-lane: an entry is collectible only when every
        // group has passed it ON ITS LANE and nobody holds it pending;
        // the prefix drop stops at the first keeper.
        if (!st.groups.empty()) {
          size_t drop = 0;
          while (drop < st.entries.size()) {
            const Entry& e = st.entries[drop];
            bool consumed = true;
            for (auto& kv : st.groups) {
              auto c = kv.second.cursor.find(e.lane);
              long long cur = c == kv.second.cursor.end() ? 0 : c->second;
              if (cur < e.id || kv.second.pending.count(e.id)) {
                consumed = false;
                break;
              }
            }
            if (!consumed) break;
            ++drop;
          }
          if (drop > 0)
            st.entries.erase(st.entries.begin(), st.entries.begin() + drop);
        }
      }
      SendAll(fd, ":" + std::to_string(n) + "\n");
    } else if (cmd == "XCLAIM" && p.size() >= 6) {
      // XCLAIM <stream> <group> <consumer> <min_idle_ms> <count> [lanes]:
      // re-deliver pending entries whose lease expired — idle >=
      // min_idle_ms AND owned by a DIFFERENT consumer (recovery of
      // entries whose consumer died before XACK — Redis XAUTOCLAIM
      // analog). Claiming transfers ownership, refreshes the lease
      // clock and bumps the delivery count. With lanes the claim drains
      // lanes in the given order (a dead replica's interactive leases
      // come back before its batch backlog) and replies carry the lane.
      const std::string& claimer = p[3];
      long long min_idle = atoll(p[4].c_str());
      int count = atoi(p[5].c_str());
      const bool laned = p.size() >= 7 && !p[6].empty();
      std::vector<std::string> lanes =
          laned ? SplitComma(p[6]) : std::vector<std::string>{""};
      std::vector<Entry> got;
      {
        std::lock_guard<std::mutex> lk(g_mu);
        Stream& st = g_streams[p[1]];
        Group& gr = st.groups[p[2]];
        long long now_ms = NowMs();
        if (!gr.pending.empty()) {
          // one id->payload index per call, not an O(entries) scan per
          // pending id (the engine polls XCLAIM; backlog must stay cheap)
          std::map<long long, const Entry*> index;
          for (const Entry& e : st.entries) index[e.id] = &e;
          for (const std::string& want : lanes) {
            if (static_cast<int>(got.size()) >= count) break;
            for (auto& kv : gr.pending) {
              if (static_cast<int>(got.size()) >= count) break;
              if (kv.second.consumer == claimer) continue;
              if (laned && kv.second.lane != want) continue;
              if (now_ms - kv.second.ts < min_idle) continue;
              auto it = index.find(kv.first);
              if (it != index.end()) {
                got.push_back(*it->second);
                kv.second.consumer = claimer;
                kv.second.ts = now_ms;
                kv.second.deliveries += 1;
              }
            }
          }
        }
      }
      std::ostringstream os;
      os << "*" << got.size() << "\n";
      for (const Entry& e : got) {
        if (laned) os << e.id << " " << e.lane << " " << e.payload << "\n";
        else os << e.id << " " << e.payload << "\n";
      }
      SendAll(fd, os.str());
    } else if (cmd == "XSHED" && p.size() >= 4) {
      // XSHED <stream> <lane> <0|1>: set/clear the lane's admission shed
      // flag (absolute write — the engine repeats it safely)
      {
        std::lock_guard<std::mutex> lk(g_mu);
        if (p[3] == "0") g_shed[p[1]].erase(p[2]);
        else g_shed[p[1]].insert(p[2]);
      }
      SendAll(fd, "+OK\n");
    } else if (cmd == "XSHED" && p.size() >= 2) {
      std::ostringstream os;
      {
        std::lock_guard<std::mutex> lk(g_mu);
        auto sh = g_shed.find(p[1]);
        size_t n = sh == g_shed.end() ? 0 : sh->second.size();
        os << "*" << n << "\n";
        if (sh != g_shed.end())
          for (const std::string& lane : sh->second) os << lane << "\n";
      }
      SendAll(fd, os.str());
    } else if (cmd == "XPENDING" && p.size() >= 4) {
      // XPENDING <stream> <group> DETAIL -> per-consumer pending counts
      // ("<consumer> <count>" lines, sorted by consumer id)
      std::map<std::string, long long> per;
      {
        std::lock_guard<std::mutex> lk(g_mu);
        Group& gr = g_streams[p[1]].groups[p[2]];
        for (auto& kv : gr.pending) per[kv.second.consumer] += 1;
      }
      std::ostringstream os;
      os << "*" << per.size() << "\n";
      for (auto& kv : per) os << kv.first << " " << kv.second << "\n";
      SendAll(fd, os.str());
    } else if (cmd == "XPENDING" && p.size() >= 3) {
      std::lock_guard<std::mutex> lk(g_mu);
      Group& gr = g_streams[p[1]].groups[p[2]];
      SendAll(fd, ":" + std::to_string(gr.pending.size()) + "\n");
    } else if (cmd == "HSET" && p.size() >= 4) {
      {
        std::lock_guard<std::mutex> lk(g_mu);
        long long now_ms = NowMs();
        EvictSome(p[1], now_ms, 8);  // bounded: full scan is O(live
                                     // fields) under a slow consumer
        g_hashes[p[1]][p[2]] = p[3];
        if (g_hash_ttl_ms > 0) {
          g_hash_times[p[1]][p[2]] = now_ms;
          g_hash_fifo[p[1]].emplace_back(p[2], now_ms);
        }
      }
      g_cv.notify_all();
      SendAll(fd, "+OK\n");
    } else if (cmd == "HGET" && p.size() >= 3) {
      std::string val;
      bool found = false;
      {
        std::lock_guard<std::mutex> lk(g_mu);
        auto h = g_hashes.find(p[1]);
        if (h != g_hashes.end()) {
          auto f = h->second.find(p[2]);
          if (f != h->second.end()) { val = f->second; found = true; }
        }
        if (found && g_hash_ttl_ms > 0) {
          // only the requested field's clock — O(log n), not a key scan
          auto t = g_hash_times.find(p[1]);
          if (t != g_hash_times.end()) {
            auto ft = t->second.find(p[2]);
            if (ft != t->second.end() &&
                NowMs() - ft->second >= g_hash_ttl_ms) {
              h->second.erase(p[2]);
              t->second.erase(ft);
              found = false;
            }
          }
        }
      }
      SendAll(fd, found ? "$" + val + "\n" : "$-1\n");
    } else if (cmd == "HKEYS" && p.size() >= 2) {
      std::ostringstream os;
      {
        std::lock_guard<std::mutex> lk(g_mu);
        EvictExpired(p[1], NowMs());
        auto h = g_hashes.find(p[1]);
        size_t n = (h == g_hashes.end()) ? 0 : h->second.size();
        os << "*" << n << "\n";
        if (h != g_hashes.end())
          for (auto& kv : h->second) os << kv.first << "\n";
      }
      SendAll(fd, os.str());
    } else if (cmd == "HDEL" && p.size() >= 3) {
      int n = 0;
      {
        std::lock_guard<std::mutex> lk(g_mu);
        auto h = g_hashes.find(p[1]);
        if (h != g_hashes.end())
          n = static_cast<int>(h->second.erase(p[2]));
        auto t = g_hash_times.find(p[1]);
        if (t != g_hash_times.end()) t->second.erase(p[2]);
      }
      SendAll(fd, ":" + std::to_string(n) + "\n");
    } else if (cmd == "DEL" && p.size() >= 2) {
      {
        std::lock_guard<std::mutex> lk(g_mu);
        g_streams.erase(p[1]);
        g_shed.erase(p[1]);
        g_hashes.erase(p[1]);
        g_hash_times.erase(p[1]);
        g_hash_fifo.erase(p[1]);
      }
      SendAll(fd, "+OK\n");
    } else {
      SendAll(fd, "-ERR unknown command\n");
    }
    {
      std::lock_guard<std::mutex> lk(g_mu);
      if (g_shutdown) break;
    }
  }
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  int port = argc > 1 ? atoi(argv[1]) : 6399;
  if (argc > 2) g_hash_ttl_ms = atoll(argv[2]);
  // joinable (not detached): a detached sweeper would race static
  // destruction of g_mu/g_cv at shutdown (UB)
  std::thread sweeper(SweeperLoop);
  int srv = socket(AF_INET, SOCK_STREAM, 0);
  g_srv_fd = srv;
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  auto fail = [&sweeper](const char* what) {
    perror(what);
    {
      std::lock_guard<std::mutex> lk(g_mu);
      g_shutdown = true;
    }
    g_cv.notify_all();
    sweeper.join();  // a joinable thread's destructor would std::terminate
    return 1;
  };
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
    return fail("bind");
  if (listen(srv, 64) != 0) return fail("listen");
  // readiness handshake for the launcher
  fprintf(stdout, "READY %d\n", port);
  fflush(stdout);
  while (true) {
    int fd = accept(srv, nullptr, nullptr);
    {
      std::lock_guard<std::mutex> lk(g_mu);
      if (g_shutdown) { if (fd >= 0) close(fd); break; }
    }
    if (fd < 0) {
      std::lock_guard<std::mutex> lk(g_mu);
      if (g_shutdown) break;
      continue;
    }
    // detached: connections are short-lived client sessions; keeping a
    // growing vector of finished threads would leak
    std::thread(HandleConn, fd).detach();
  }
  close(srv);
  {
    std::lock_guard<std::mutex> lk(g_mu);
    g_shutdown = true;
  }
  g_cv.notify_all();
  sweeper.join();
  return 0;
}
