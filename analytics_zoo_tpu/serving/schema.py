"""Serving wire format — how tensors travel through the data plane.

The reference's client b64-encodes either Arrow-serialized ndarrays or raw
image bytes into Redis stream fields (pyzoo/zoo/serving/client.py:144
``enqueue``; JVM decode in serving/preprocessing/PreProcessing.scala:67-90).
Here a record is one JSON object — ``{"uri", "inputs": {name: tensor}}`` —
where each tensor carries dtype/shape plus b64 raw bytes (C-order), the
whole record b64-wrapped for the line protocol. Arrow adds nothing for
fixed-dtype dense tensors and this keeps the broker payloads opaque ASCII.

Optional record encryption (the reference's PPML ``recordEncrypted`` flag,
FlinkInference.scala:55) plugs in as an (encrypt, decrypt) byte-callable
pair.
"""

from __future__ import annotations

import base64
import json
import re
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

Cipher = Optional[Tuple[Callable[[bytes], bytes], Callable[[bytes], bytes]]]

# uris become fields of the space/newline-delimited broker protocol: a
# permissive uri would corrupt the framing (or inject commands), so the
# charset is locked down at the schema boundary.
_URI_RE = re.compile(r"^[A-Za-z0-9._:-]{1,256}$")


class ServingError(RuntimeError):
    """An error result stored in place of a prediction."""


class DeadlineExpiredError(ServingError):
    """The record's ``deadline_ms`` elapsed before the engine could serve
    it — the engine stored an explicit expired result (never a silent
    drop), and decoding that result raises this."""


# Priority lanes, highest first. The lane name doubles as the broker-side
# lane tag and the ``priority`` label on serving metrics.
PRIORITIES = ("interactive", "default", "batch")
DEFAULT_PRIORITY = "default"


def validate_priority(priority: Optional[str]) -> str:
    if priority is None:
        return DEFAULT_PRIORITY
    if priority not in PRIORITIES:
        raise ValueError(
            f"bad priority {priority!r}: one of {PRIORITIES}")
    return priority


#: generation feedback modes a generate record may request (mirrors
#: inference/generation.MODES — duplicated so the wire schema stays
#: importable without the jax-backed inference stack)
GENERATE_MODES = ("raw", "greedy", "sample")


def validate_generate(generate) -> Optional[Dict[str, Any]]:
    """Normalize a client ``generate`` request into the compact wire form
    carried on the record's trace side channel (the ``"g"`` key):
    ``{"n": steps[, "m": mode, "t": temperature, "s": seed]}`` — defaults
    (greedy, temperature 1.0, no seed) are omitted from the wire. Accepts
    the long keys ``max_new_tokens``/``mode``/``temperature``/``seed`` or
    the wire keys; ``None`` passes through (not a generate record)."""
    if generate is None:
        return None
    if not isinstance(generate, dict):
        raise ValueError("generate must be a dict of decode options")
    g = dict(generate)
    n = g.pop("max_new_tokens", g.pop("n", 16))
    mode = g.pop("mode", g.pop("m", "greedy"))
    temperature = g.pop("temperature", g.pop("t", 1.0))
    seed = g.pop("seed", g.pop("s", None))
    if g:
        raise ValueError(f"unknown generate keys: {sorted(g)}")
    n = int(n)
    if n < 1:
        raise ValueError(f"generate max_new_tokens must be >= 1, got {n}")
    if mode not in GENERATE_MODES:
        raise ValueError(
            f"bad generate mode {mode!r}: one of {GENERATE_MODES}")
    out: Dict[str, Any] = {"n": n}
    if mode != "greedy":
        out["m"] = str(mode)
    if float(temperature) != 1.0:
        out["t"] = float(temperature)
    if seed is not None:
        out["s"] = int(seed)
    return out


class ImageBytes:
    """Raw encoded image (JPEG/PNG) riding a record — decoded and run
    through the engine-side preprocessing chain, exactly the reference's
    serving flow (client.py:144 enqueues b64 image bytes; the JVM decodes
    and preprocesses in PreProcessing.scala:67-90)."""

    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = bytes(data)


def validate_uri(uri: str) -> str:
    if not _URI_RE.match(uri or ""):
        raise ValueError(
            f"bad uri {uri!r}: use 1-256 chars of [A-Za-z0-9._:-]")
    return uri


def encode_tensor(arr) -> dict:
    if isinstance(arr, ImageBytes):
        return {"image": base64.b64encode(arr.data).decode()}
    arr = np.ascontiguousarray(arr)
    return {"dtype": arr.dtype.str, "shape": list(arr.shape),
            "data": base64.b64encode(arr.tobytes()).decode()}


def decode_tensor(obj: dict):
    if "image" in obj:
        return ImageBytes(base64.b64decode(obj["image"]))
    raw = base64.b64decode(obj["data"])
    return np.frombuffer(raw, dtype=np.dtype(obj["dtype"])).reshape(
        obj["shape"]).copy()


def encode_record(uri: str, inputs: Dict[str, np.ndarray],
                  cipher: Cipher = None,
                  trace: Optional[Dict[str, Any]] = None) -> str:
    """``trace`` is the optional end-to-end tracing stamp the client
    attaches (``{"id", "t_pc", "t_wall", "s"}`` — enqueue time on both
    the monotonic and wall clocks plus the sampling flag, plus the
    scheduling fields ``"p"``/``"d"``: the record's priority lane and its
    relative ``deadline_ms``); the engine turns it into the measured
    ``queue_wait`` span and the ``zoo_queue_wait_seconds`` /
    ``zoo_serving_latency_seconds`` histograms, and uses the deadline to
    expire records whose slack ran out. Decoders that ignore it
    (``decode_record``) are unaffected — the field is additive."""
    obj: Dict[str, Any] = {
        "uri": uri,
        "inputs": {k: encode_tensor(v if isinstance(v, ImageBytes)
                                    else np.asarray(v))
                   for k, v in inputs.items()}}
    if trace:
        obj["trace"] = trace
    body = json.dumps(obj).encode()
    if cipher is not None:
        body = cipher[0](body)
    return base64.b64encode(body).decode()


def decode_record_meta(payload_b64: str, cipher: Cipher = None
                       ) -> Tuple[str, Dict[str, np.ndarray],
                                  Dict[str, Any]]:
    """(uri, inputs, meta): like :func:`decode_record` but keeps the
    record's side-channel metadata (the client's ``trace`` stamp; ``{}``
    when absent — Arrow-format reference records carry none)."""
    body = base64.b64decode(payload_b64)
    if cipher is not None:
        body = cipher[1](body)
    obj = json.loads(body)
    if "data" in obj and "inputs" not in obj:
        # reference-client record shape: {"uri", "data": b64(arrow)}
        # (ref client.py:144-147 enqueue)
        return obj["uri"], decode_arrow_inputs(obj["data"]), {}
    meta = obj.get("trace")
    return (obj["uri"],
            {k: decode_tensor(v) for k, v in obj["inputs"].items()},
            meta if isinstance(meta, dict) else {})


def decode_record(payload_b64: str, cipher: Cipher = None
                  ) -> Tuple[str, Dict[str, np.ndarray]]:
    uri, inputs, _ = decode_record_meta(payload_b64, cipher)
    return uri, inputs


# ------------------------- reference Arrow wire encoding ----------------
# The reference client serializes records as ONE Arrow RecordBatch stream,
# b64-wrapped (ref pyzoo/zoo/serving/client.py:149 data_to_b64 over
# schema.py get_field_and_data): a dense tensor is a
# struct{indiceData:list<i32>, indiceShape:list<i32>, data:list<f32>,
# shape:list<i32>} column holding 4 one-field rows; a string column is
# either b64 image bytes or '|'-joined string values. Producing/consuming
# that exact layout lets reference-client record payloads ride this
# broker (the TRANSPORT still differs: zbroker line protocol, not Redis
# RESP — see PARITY.md).

def encode_record_arrow(uri: str, inputs: Dict[str, Any],
                        cipher: Cipher = None) -> str:
    import pyarrow as pa
    fields, arrays = [], []
    for key, value in inputs.items():
        if isinstance(value, ImageBytes):
            fields.append(pa.field(key, pa.string()))
            arrays.append(pa.array(
                [base64.b64encode(value.data).decode()]))
            continue
        if isinstance(value, (list, tuple)) and value and \
                isinstance(value[0], str):
            fields.append(pa.field(key, pa.string()))
            arrays.append(pa.array(["|".join(value)]))
            continue
        arr = np.asarray(value)
        if arr.dtype.kind in ("U", "S"):      # string tensor -> '|' join
            fields.append(pa.field(key, pa.string()))
            arrays.append(pa.array(
                ["|".join(str(v) for v in arr.ravel())]))
            continue
        t = pa.struct([pa.field("indiceData", pa.list_(pa.int32())),
                       pa.field("indiceShape", pa.list_(pa.int32())),
                       pa.field("data", pa.list_(pa.float32())),
                       pa.field("shape", pa.list_(pa.int32()))])
        arrays.append(pa.array(
            [{"indiceData": []}, {"indiceShape": []},
             {"data": arr.astype("float32").ravel()},
             {"shape": np.asarray(arr.shape)}], type=t))
        fields.append(pa.field(key, t))
    # string/image columns are 1 row, tensor struct columns are 4 rows
    # (the reference's quirky layout) — RecordBatch requires EQUAL column
    # lengths, so short columns are null-padded; decoders read row 0
    n_rows = max(len(a) for a in arrays)
    arrays = [a if len(a) == n_rows else
              pa.concat_arrays([a, pa.nulls(n_rows - len(a), a.type)])
              for a in arrays]
    sink = pa.BufferOutputStream()
    batch = pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))
    with pa.RecordBatchStreamWriter(sink, batch.schema) as w:
        w.write_batch(batch)
    arrow_b64 = base64.b64encode(sink.getvalue().to_pybytes()).decode()
    body = json.dumps({"uri": uri, "data": arrow_b64}).encode()
    if cipher is not None:
        body = cipher[0](body)
    return base64.b64encode(body).decode()


# STRONG magics only: a '|'-joined string tensor that happens to be valid
# b64 must not be misread as an image, so short/ambiguous prefixes (BM,
# bare RIFF) are excluded
_IMAGE_MAGIC = (b"\xff\xd8\xff", b"\x89PNG\r\n\x1a\n",
                b"GIF87a", b"GIF89a", b"II*\x00", b"MM\x00*")


def _looks_like_image(raw: bytes) -> bool:
    if raw.startswith(_IMAGE_MAGIC):
        return True
    return raw[:4] == b"RIFF" and raw[8:12] == b"WEBP"


def decode_arrow_inputs(arrow_b64: str) -> Dict[str, Any]:
    import pyarrow as pa
    buf = base64.b64decode(arrow_b64)
    with pa.ipc.open_stream(pa.py_buffer(buf)) as reader:
        batch = reader.read_next_batch()
    out: Dict[str, Any] = {}
    for name, col in zip(batch.schema.names, batch.columns):
        if pa.types.is_string(col.type):
            s = col[0].as_py()
            try:
                raw = base64.b64decode(s, validate=True)
            except Exception:
                raw = None
            if raw is not None and _looks_like_image(raw):
                out[name] = ImageBytes(raw)       # ref encode_image
            else:
                out[name] = np.asarray(s.split("|"))
            continue
        rows = col.to_pylist()                    # 4 one-field rows
        merged: Dict[str, Any] = {}
        for row in rows:
            for k, v in (row or {}).items():
                if v not in (None, []):
                    merged.setdefault(k, v)
        data = np.asarray(merged.get("data", []), np.float32)
        shape = [int(v) for v in merged.get("shape", [])]
        if merged.get("indiceData"):
            # sparse: indices [nnz, ndim] + values + dense shape
            idx = np.asarray(merged["indiceData"], np.int64).reshape(
                [int(v) for v in merged["indiceShape"]])
            dense = np.zeros(shape, np.float32)
            dense[tuple(idx.T)] = data
            out[name] = dense
        else:
            out[name] = data.reshape(shape)
    return out


def encode_result(arr: np.ndarray, cipher: Cipher = None) -> str:
    body = json.dumps(encode_tensor(np.asarray(arr))).encode()
    if cipher is not None:
        body = cipher[0](body)
    return base64.b64encode(body).decode()


def encode_error(message: str, cipher: Cipher = None,
                 code: Optional[str] = None) -> str:
    """``code`` types the error for the decoding client (additive field):
    ``"expired"`` marks a deadline-expired record and decodes into
    :class:`DeadlineExpiredError` instead of plain :class:`ServingError`."""
    obj: Dict[str, Any] = {"error": str(message)[:2000]}
    if code:
        obj["code"] = code
    body = json.dumps(obj).encode()
    if cipher is not None:
        body = cipher[0](body)
    return base64.b64encode(body).decode()


def decode_result(payload_b64: str, cipher: Cipher = None) -> np.ndarray:
    body = base64.b64decode(payload_b64)
    if cipher is not None:
        body = cipher[1](body)
    obj = json.loads(body)
    if "error" in obj:
        if obj.get("code") == "expired":
            raise DeadlineExpiredError(obj["error"])
        raise ServingError(obj["error"])
    return decode_tensor(obj)
