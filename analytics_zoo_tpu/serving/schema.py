"""Serving wire format — how tensors travel through the data plane.

The reference's client b64-encodes either Arrow-serialized ndarrays or raw
image bytes into Redis stream fields (pyzoo/zoo/serving/client.py:144
``enqueue``; JVM decode in serving/preprocessing/PreProcessing.scala:67-90).
Here a record is one JSON object — ``{"uri", "inputs": {name: tensor}}`` —
where each tensor carries dtype/shape plus b64 raw bytes (C-order), the
whole record b64-wrapped for the line protocol. Arrow adds nothing for
fixed-dtype dense tensors and this keeps the broker payloads opaque ASCII.

Optional record encryption (the reference's PPML ``recordEncrypted`` flag,
FlinkInference.scala:55) plugs in as an (encrypt, decrypt) byte-callable
pair.
"""

from __future__ import annotations

import base64
import json
import re
from typing import Callable, Dict, Optional, Tuple

import numpy as np

Cipher = Optional[Tuple[Callable[[bytes], bytes], Callable[[bytes], bytes]]]

# uris become fields of the space/newline-delimited broker protocol: a
# permissive uri would corrupt the framing (or inject commands), so the
# charset is locked down at the schema boundary.
_URI_RE = re.compile(r"^[A-Za-z0-9._:-]{1,256}$")


class ServingError(RuntimeError):
    """An error result stored in place of a prediction."""


class ImageBytes:
    """Raw encoded image (JPEG/PNG) riding a record — decoded and run
    through the engine-side preprocessing chain, exactly the reference's
    serving flow (client.py:144 enqueues b64 image bytes; the JVM decodes
    and preprocesses in PreProcessing.scala:67-90)."""

    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = bytes(data)


def validate_uri(uri: str) -> str:
    if not _URI_RE.match(uri or ""):
        raise ValueError(
            f"bad uri {uri!r}: use 1-256 chars of [A-Za-z0-9._:-]")
    return uri


def encode_tensor(arr) -> dict:
    if isinstance(arr, ImageBytes):
        return {"image": base64.b64encode(arr.data).decode()}
    arr = np.ascontiguousarray(arr)
    return {"dtype": arr.dtype.str, "shape": list(arr.shape),
            "data": base64.b64encode(arr.tobytes()).decode()}


def decode_tensor(obj: dict):
    if "image" in obj:
        return ImageBytes(base64.b64decode(obj["image"]))
    raw = base64.b64decode(obj["data"])
    return np.frombuffer(raw, dtype=np.dtype(obj["dtype"])).reshape(
        obj["shape"]).copy()


def encode_record(uri: str, inputs: Dict[str, np.ndarray],
                  cipher: Cipher = None) -> str:
    body = json.dumps(
        {"uri": uri,
         "inputs": {k: encode_tensor(v if isinstance(v, ImageBytes)
                                     else np.asarray(v))
                    for k, v in inputs.items()}}).encode()
    if cipher is not None:
        body = cipher[0](body)
    return base64.b64encode(body).decode()


def decode_record(payload_b64: str, cipher: Cipher = None
                  ) -> Tuple[str, Dict[str, np.ndarray]]:
    body = base64.b64decode(payload_b64)
    if cipher is not None:
        body = cipher[1](body)
    obj = json.loads(body)
    return obj["uri"], {k: decode_tensor(v)
                        for k, v in obj["inputs"].items()}


def encode_result(arr: np.ndarray, cipher: Cipher = None) -> str:
    body = json.dumps(encode_tensor(np.asarray(arr))).encode()
    if cipher is not None:
        body = cipher[0](body)
    return base64.b64encode(body).decode()


def encode_error(message: str, cipher: Cipher = None) -> str:
    body = json.dumps({"error": str(message)[:2000]}).encode()
    if cipher is not None:
        body = cipher[0](body)
    return base64.b64encode(body).decode()


def decode_result(payload_b64: str, cipher: Cipher = None) -> np.ndarray:
    body = base64.b64decode(payload_b64)
    if cipher is not None:
        body = cipher[1](body)
    obj = json.loads(body)
    if "error" in obj:
        raise ServingError(obj["error"])
    return decode_tensor(obj)
