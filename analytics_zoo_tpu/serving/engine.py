"""ClusterServing engine — stream in → batch → TPU inference → result store.

TPU-native replacement for the reference's Flink job (SURVEY.md §3.4):
``FlinkRedisSource`` (XREADGROUP consumer-group batches,
FlinkRedisSource.scala:81) → ``FlinkInference.map`` (decode, batch predict
through InferenceModel, FlinkInference.scala:67-81) → ``FlinkRedisSink``
(HSET results). The Flink ``RichMapFunction`` parallelism becomes host
threads feeding ONE compiled executable: on TPU the model replica count of
the reference ("parallelism = model parallelism", ClusterServing.scala:54-67)
is the wrong knob — a single jitted forward at a fixed batch bucket keeps
the MXU saturated, so the engine pads each dequeued batch up to
``batch_size`` and masks the tail (same trick the reference applies per-core
via its batch slicing, tf_dataset.py:117).

Per-stage latency stats mirror serving ``Timer.scala:26``.

The serve loop is a produce → staged-dispatch → drain pipeline
(common/pipeline_io.py): dequeue/decode/preprocess of batch N+1 overlaps
batch N's device compute through a bounded in-flight window, and results
are only fetched when the window is full or the stream idles — round-5
on-chip profiling showed the synchronous loop left the accelerator idle
during every broker round-trip (VERDICT.md weak #5/#7).
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

# StageTimer moved to the shared pipeline layer; re-exported here because
# the engine is its historical home.
from analytics_zoo_tpu.common import compile_ahead, fleet, resilience, \
    slo, telemetry, timeseries
from analytics_zoo_tpu.common.pipeline_io import (  # noqa: F401
    Completed,
    DevicePipeline,
    StageTimer,
)
from analytics_zoo_tpu.inference import decode_scheduler, generation
from analytics_zoo_tpu.serving import schema
from analytics_zoo_tpu.serving.broker import Broker, BrokerClient
from analytics_zoo_tpu.serving.client import INPUT_STREAM, RESULT_HASH

logger = logging.getLogger(__name__)


def _parse_lane_map(raw: str, defaults: Dict[str, float]) -> Dict[str, float]:
    """Per-lane float knob: ``"40"`` applies to every lane,
    ``"interactive=5,batch=250"`` sets named lanes (unnamed lanes keep
    their default). Malformed parts raise — a silently-ignored scheduling
    knob is worse than a crash at construction."""
    out = dict(defaults)
    raw = (raw or "").strip()
    if not raw:
        return out
    if "=" not in raw:
        v = float(raw)
        return {k: v for k in out}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        out[k.strip()] = float(v)
    return out


def ndarray_chain(pipe):
    """Wrap a ChainedPreprocessing over ImageFeature dicts as a plain
    ndarray -> ndarray callable (the engine's ``image_preprocess``
    contract). One definition — the config-driven and preset-driven paths
    must not drift."""
    def run(arr):
        return pipe.transform({"image": np.asarray(arr, np.float32)}
                              )["image"]
    return run


def image_pipeline(model_name: str, source: str = "imagenet"):
    """ndarray -> ndarray preprocessing chain from the model zoo's
    per-model presets (ref ImagenetConfig preprocessors feeding
    PreProcessing.scala) — pass as ``ClusterServing(image_preprocess=)``.
    ``source="torchvision"`` selects the normalization trained into
    torchvision checkpoints (use with ``ImageClassifier(pretrained=)``)."""
    from analytics_zoo_tpu.models.image.imageclassification. \
        image_classifier import preprocessor
    return ndarray_chain(preprocessor(model_name, source=source))


class _GenBatch:
    """One assembled autoregressive-generate batch riding the dispatch
    pipeline: the encoder prefill tensor, the decoder start sign, and the
    request's decode parameters (schema.validate_generate wire form).
    ``_dispatch`` routes it onto the model's decode loop instead of the
    one-shot predict; the host-side result rides the pipeline window the
    way the CPU-failover result does."""

    __slots__ = ("enc", "start", "params", "trace_uris")

    def __init__(self, enc, start, params, trace_uris=()):
        self.enc = enc
        self.start = start
        self.params = dict(params)
        self.trace_uris = tuple(trace_uris)


class ClusterServing:
    """The serving job (ref ClusterServing.scala:31).

    ``model``: an InferenceModel (already loaded). ``input_cols``: the order
    in which record tensors feed the model's inputs (single-input models
    take the record's only tensor).

    ``image_preprocess``: ndarray -> ndarray chain applied to records that
    arrive as raw encoded images (``InputQueue.enqueue(uri, image=bytes)``)
    after the engine decodes them — the reference's server-side
    decode-and-preprocess flow (PreProcessing.scala:36,67-90). Build one
    from a preset with ``image_pipeline("resnet-50", source=...)`` or wire
    it from config.yaml's ``preprocessing:`` section.

    ``pipeline_window``: how many dispatched batches may be in flight on
    the device while the loop dequeues/preprocesses the next ones (0 =
    fully synchronous dispatch, the pre-pipeline behavior — kept as the
    measured baseline for bench.py's sync-vs-pipelined comparison).

    ``max_batch_size``: cap for adaptive batch growth. Under sustained
    backlog (every dequeue returns a full batch) the engine steps its
    batch bucket up the ladder to this cap — fewer, bigger dispatches win
    when the per-dispatch cost dominates. ``None`` defaults to 4×
    ``batch_size``; set it equal to ``batch_size`` to pin the bucket.

    ``min_batch_size``: the bottom rung the bucket may shrink back to
    after sustained idle (defaults to ``batch_size``: no shrinking).

    ``warmup``: AOT-compile the whole bucket ladder on a background
    thread at ``start()`` (and wire the persistent compile cache), so a
    backlog-driven bucket change is a stall-free swap to an
    already-compiled rung instead of an in-band XLA compile on the serve
    thread. On by default for models that support it (InferenceModel);
    ``ZOO_WARMUP_BUCKETS=0`` disables it process-wide, any other integer
    caps how many rungs (smallest first) are warmed.

    Multi-replica fan-out: ``consumer`` defaults to this replica's fleet
    id, so N engines sharing one ``group`` split the stream with
    at-least-once delivery — each delivered entry carries a per-consumer
    lease (``claim_min_idle_ms``, env ``ZOO_SERVING_LEASE_MS``), and a
    periodic reclaim sweep (env ``ZOO_SERVING_RECLAIM_S``) claims peers'
    expired leases so a crashed replica's entries are re-served with zero
    loss (docs/observability.md "Multi-replica deployment").

    SLO-aware scheduling: records carry a priority lane
    (``schema.PRIORITIES``) and an optional ``deadline_ms``. Reads are
    lane-ordered by a weighted-deficit schedule
    (``ZOO_SERVING_LANE_WEIGHTS``) with starvation protection; a
    partially-filled batch bucket accumulates up to
    ``ZOO_SERVING_MAX_WAIT_MS`` per lane before dispatching (continuous
    batching; default 0 keeps the legacy dispatch-every-read behavior);
    deadline-lapsed records get an explicit typed expired result; and an
    admission-control tick (``ZOO_SERVING_ADMISSION_S``) sheds NEW
    batch-lane enqueues at the broker while per-lane p99 burn says the
    path is saturated (docs/observability.md "Priority lanes & admission
    control").

    Autoregressive generate: a record enqueued with ``generate={...}``
    (InputQueue/frontend) carries its decode parameters on the trace
    side channel. On a scheduler-capable model (``decode_step_fn``, i.e.
    an InferenceModel) assembled generate records are handed to a
    persistent **step-level scheduler**
    (inference/decode_scheduler.py): live sequences advance one wide
    step per serve-loop turn over a shared paged KV pool, newly-arrived
    records admit mid-flight (chunked prefill), heterogeneous decode
    params share the wide step, and interactive encode batches
    interleave BETWEEN decode steps — a step is preempted
    (``zoo_decode_preemptions_total``) whenever a waiting encode lane
    outranks the live decode lanes on the weighted-deficit schedule,
    with a starvation floor so decode always advances. ``draft_model``
    adds speculative decoding (greedy output bitwise unchanged). Duck-
    typed models keep the legacy whole-batch decode loop: generate
    records batch with identical decode params only and run to
    completion in one dispatch.
    """

    #: consecutive full dequeues that count as "sustained backlog"
    BACKLOG_GROW_AFTER = 8
    #: consecutive under-half-full dequeues before stepping DOWN one rung
    #: (bounds pad waste after a burst; empty polls count as idle too)
    IDLE_SHRINK_AFTER = 32
    #: max entries one reclaim sweep claims — a crashed replica's whole
    #: pending set transfers in ONE XCLAIM (overflow feeds _claim_backlog)
    RECLAIM_BATCH = 256
    #: finished-entry-id ring size for the redelivery dedupe
    DEDUPE_WINDOW = 65536
    #: safety margin subtracted from a record's deadline when computing
    #: the partial-bucket dispatch trigger — dispatch BEFORE the deadline,
    #: not at it
    SLACK_MARGIN_S = 0.005
    #: the lane admission control sheds when per-lane SLO burn says the
    #: serving path is saturated; interactive/default always keep flowing
    ADMISSION_LANE = "batch"
    #: consecutive preempted decode ticks before a step runs regardless —
    #: encode pressure may slow decode, never starve it
    DECODE_STARVATION_FLOOR = 4
    #: count-shaped buckets for the step/page cost histograms (the
    #: latency default buckets top out at 30 — useless for step counts)
    COST_COUNT_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                          256.0, 512.0, 1024.0, 4096.0)

    def __init__(self, model, broker_port: int, batch_size: int = 8,
                 stream: str = INPUT_STREAM, result_key: str = RESULT_HASH,
                 group: str = "serving", consumer: Optional[str] = None,
                 input_cols: Optional[List[str]] = None,
                 cipher: schema.Cipher = None,
                 postprocess=None, block_ms: int = 50,
                 claim_min_idle_ms: Optional[int] = None,
                 reclaim_interval_s: Optional[float] = None,
                 broker_host: str = "127.0.0.1",
                 image_preprocess=None,
                 pipeline_window: int = 2,
                 max_batch_size: Optional[int] = None,
                 min_batch_size: Optional[int] = None,
                 warmup: bool = True,
                 replica_id: Optional[str] = None,
                 draft_model=None, spec_k: int = 4):
        self.model = model
        self.batch_size = int(batch_size)
        self.pipeline_window = int(pipeline_window)
        self.max_batch_size = int(max_batch_size) if max_batch_size \
            else 4 * self.batch_size
        self.min_batch_size = int(min_batch_size) if min_batch_size \
            else self.batch_size
        # the bucket ladder spans shrink floor → growth cap; the starting
        # bucket snaps to a rung so every dispatch shape is a ladder shape
        self.ladder = compile_ahead.BucketLadder(
            min(self.min_batch_size, self.batch_size),
            max(self.max_batch_size, self.batch_size))
        self.batch_size = self.ladder.rung_for(self.batch_size)
        self._full_streak = 0
        self._idle_streak = 0
        # ZOO_WARMUP_BUCKETS: 0 disables compile-ahead warmup, N caps the
        # rung count (smallest first), unset warms the full ladder
        raw = os.environ.get("ZOO_WARMUP_BUCKETS", "").strip()
        self._warmup_enabled = bool(warmup) and raw != "0"
        limit = int(raw) if raw.isdigit() and int(raw) > 0 else None
        self._warm_rungs = self.ladder.rungs if limit is None \
            else self.ladder.rungs[:limit]
        self._warm_kicked = False
        self.broker_host = broker_host
        self.broker_port = broker_port
        self.stream, self.result_key = stream, result_key
        # fleet identity first: the default consumer id IS the replica id,
        # so N replicas sharing one group fan out with per-consumer leases
        # instead of all reading as "c0" (single-consumer legacy)
        self.replica_id = replica_id or fleet.default_replica_id(stream)
        self.group = group
        self.consumer = consumer or self.replica_id
        self.input_cols = input_cols
        self.cipher = cipher
        self.postprocess = postprocess
        self.image_preprocess = image_preprocess
        self.block_ms = block_ms
        # --- SLO-aware scheduling (priority lanes, continuous batching) —
        # ZOO_SERVING_MAX_WAIT_MS: how long a partially-filled batch
        # bucket may accumulate before it dispatches anyway, per lane
        # ("40" for all lanes, "interactive=5,batch=250" per-lane; default
        # 0 = dispatch every read immediately, the legacy behavior).
        self.max_wait_ms = _parse_lane_map(
            os.environ.get("ZOO_SERVING_MAX_WAIT_MS", ""),
            {lane: 0.0 for lane in schema.PRIORITIES})
        # ZOO_SERVING_LANE_WEIGHTS: weighted-deficit shares per lane —
        # the lane with the lowest served-records/weight ratio reads
        # first, so batch work always drains (starvation protection)
        # while interactive gets the biggest share under contention
        self.lane_weights = _parse_lane_map(
            os.environ.get("ZOO_SERVING_LANE_WEIGHTS", ""),
            {"interactive": 4.0, "default": 2.0, "batch": 1.0})
        self._lane_credit: Dict[str, float] = {
            lane: 0.0 for lane in schema.PRIORITIES}
        self._lanes_priority = ",".join(schema.PRIORITIES)
        # the assembly bucket: decoded records waiting to fill a batch —
        # (entry_id, uri, inputs, queue_meta, lane, t_arrive, t_deadline,
        #  gen) where gen is the normalized generate request or None
        self._asm: List[tuple] = []
        # ZOO_SERVING_DECODE_MAX_SEQ: when > 0 and the model supports
        # warm_decode, ladder warmup ALSO AOT-compiles the autoregressive
        # decode shapes — every (batch rung × seq-length rung up to this
        # many positions) pair — so a generate request's growing decoder
        # buffer swaps rungs without an in-band compile. 0 (default)
        # leaves decode shapes to compile on first use.
        raw = os.environ.get("ZOO_SERVING_DECODE_MAX_SEQ", "").strip()
        self._decode_max_seq = int(raw) if raw else 0
        # --- step-level decode (inference/decode_scheduler.py): built
        # lazily at the first generate admission on a scheduler-capable
        # model; duck-typed models keep the whole-batch _GenBatch path
        self._decode_sched: Optional[decode_scheduler.DecodeScheduler] = \
            None
        self._draft_model = draft_model
        self._spec_k = int(spec_k)
        # live sequence -> (uri, ack_cmd, queue-wait meta, lane, conn_gen)
        self._gen_live: Dict = {}
        self._decode_yield_streak = 0
        # ZOO_SERVING_ADMISSION_S: cadence of the admission-control tick
        # (SLO burn check + broker XSHED flip + lane depth gauges);
        # 0 disables admission control entirely
        raw = os.environ.get("ZOO_SERVING_ADMISSION_S", "").strip()
        self._admission_interval_s = float(raw) if raw else 1.0
        self._last_admission = 0.0
        # mirrors for /healthz and tests (read cross-thread under lock)
        self.admission_shedding = False
        self._admission_dirty = False
        self.records_expired = 0
        # the delivery lease: entries idle past this are claimable by any
        # OTHER consumer (at-least-once redelivery after a replica crash)
        if claim_min_idle_ms is None:
            raw = os.environ.get("ZOO_SERVING_LEASE_MS", "").strip()
            claim_min_idle_ms = int(raw) if raw else 30000
        self.claim_min_idle_ms = int(claim_min_idle_ms)
        # claim at most ~1/s by default — recovery is a rare path, the hot
        # read loop must not pay a broker round-trip per poll
        if reclaim_interval_s is None:
            raw = os.environ.get("ZOO_SERVING_RECLAIM_S", "").strip()
            reclaim_interval_s = float(raw) if raw \
                else max(0.5, self.claim_min_idle_ms / 2000.0)
        self._claim_interval_s = float(reclaim_interval_s)
        self._last_claim = 0.0
        # supervisor-thread → serve-thread "sweep now" signal (Event: the
        # rate-limiter clock itself stays serve-thread-confined)
        self._reclaim_asap = threading.Event()
        # one reclaim sweep claims every expired lease in a single XCLAIM
        # (up to RECLAIM_BATCH); beyond-batch entries queue here and feed
        # subsequent dispatches, so "sweeps fired" stays 1 per crash
        self._claim_backlog: Deque[Tuple[int, str, str]] = \
            collections.deque()
        # entry-id dedupe ring: ids in flight or already finished by THIS
        # consumer are dropped on re-arrival, making result writes
        # idempotent under at-least-once redelivery. Serve-thread only.
        self._inflight_ids: set = set()
        self._done_ids: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        # broker connection generation: a redial invalidates the dedupe
        # ring (a restarted broker reuses entry ids from 1)
        self._conn_gen = 0
        self._seen_client_gen = 0
        self.timer = StageTimer()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # records_out is bumped on the serving thread and read from
        # metrics() on arbitrary caller threads; += is not atomic
        self._state_lock = threading.Lock()
        self.records_out = 0
        # process-wide telemetry: the registry counters feed the Prometheus
        # /metrics exposition; traces are keyed by record uri so one
        # record's latency decomposes into engine stages (sampled per
        # batch at the tracer's rate, default ZOO_TELEMETRY_SAMPLE=1.0)
        self._tracer = telemetry.get_tracer()
        reg = telemetry.get_registry()
        self._rec_counter = reg.counter(
            "zoo_serving_records_total",
            "Records with a flushed result", ("stream",)).labels(stream)
        self._err_counter = reg.counter(
            "zoo_serving_record_errors_total",
            "Records that got an error result", ("stream",)).labels(stream)
        self._batch_gauge = reg.gauge(
            "zoo_serving_batch_bucket",
            "Current adaptive compile-bucket batch size",
            ("stream",)).labels(stream)
        self._batch_gauge.set(self.batch_size)
        # first-class queue wait + end-to-end latency (ISSUE 6): stamped
        # client-side (schema trace meta), measured here — the fleet's
        # backlog signal and the SLO monitor's p99 source
        self._wait_hist = reg.histogram(
            "zoo_queue_wait_seconds",
            "Broker queue wait: client enqueue to engine dequeue",
            ("stream",)).labels(stream)
        # per-PRIORITY end-to-end latency: the per-lane SLOs in
        # common/slo.py filter on the priority label, and the admission
        # tick sheds the batch lane off these very histograms
        lat = reg.histogram(
            "zoo_serving_latency_seconds",
            "End-to-end record latency: client enqueue to result flush",
            ("stream", "priority"))
        self._latency_hist = {lane: lat.labels(stream, lane)
                              for lane in schema.PRIORITIES}
        # zero-silent-drops ledger, expired leg (shed is counted client-
        # side in InputQueue — a refused XADD never reaches the engine)
        exp = reg.counter(
            "zoo_serving_expired_total",
            "Records whose deadline_ms lapsed before inference; each got "
            "an explicit expired result", ("stream", "priority"))
        self._expired_counter = {lane: exp.labels(stream, lane)
                                 for lane in schema.PRIORITIES}
        depth = reg.gauge(
            "zoo_serving_lane_depth",
            "Broker queue depth per priority lane",
            ("stream", "priority"))
        self._lane_depth_gauge = {lane: depth.labels(stream, lane)
                                  for lane in schema.PRIORITIES}
        self._admission_gauge = reg.gauge(
            "zoo_serving_admission_state",
            "1 while admission control is shedding the batch lane",
            ("stream", "priority")).labels(stream, self.ADMISSION_LANE)
        # at-least-once delivery observability: redeliveries received via
        # XCLAIM and the reclaim sweeps that produced them
        self._redeliver_counter = reg.counter(
            "zoo_serving_redelivered_total",
            "Entries re-delivered via lease reclaim (XCLAIM)",
            ("stream",)).labels(stream)
        self._reclaim_counter = reg.counter(
            "zoo_serving_lease_reclaims_total",
            "Reclaim sweeps that claimed at least one expired lease",
            ("stream",)).labels(stream)
        self._preempt_counter = reg.counter(
            "zoo_decode_preemptions_total",
            "Decode scheduler steps deferred because a waiting encode "
            "lane outranked the live decode lanes on the weighted-"
            "deficit schedule", ("stream",)).labels(stream)
        # per-request cost attribution (ISSUE 17): settled when a record's
        # result flushes — an encode record is billed its share of the
        # batch's device time; a generate record its accumulated share of
        # every wide decode step it rode, plus steps and KV pages held —
        # so each lane gets a measured unit cost
        cost_dev = reg.histogram(
            "zoo_request_cost_device_seconds",
            "Device-seconds attributed to one record at settlement",
            ("stream", "priority", "kind"))
        cost_steps = reg.histogram(
            "zoo_request_cost_decode_steps",
            "Decode steps one generate record consumed",
            ("stream", "priority", "kind"), buckets=self.COST_COUNT_BUCKETS)
        cost_pages = reg.histogram(
            "zoo_request_cost_kv_pages",
            "KV cache pages one generate record held at retirement",
            ("stream", "priority", "kind"), buckets=self.COST_COUNT_BUCKETS)
        self._cost_device_hist = {
            (lane, kind): cost_dev.labels(stream, lane, kind)
            for lane in schema.PRIORITIES
            for kind in ("encode", "generate")}
        self._cost_steps_hist = {
            lane: cost_steps.labels(stream, lane, "generate")
            for lane in schema.PRIORITIES}
        self._cost_pages_hist = {
            lane: cost_pages.labels(stream, lane, "generate")
            for lane in schema.PRIORITIES}
        # cross-thread-readable mirrors for /healthz and tests
        self.records_redelivered = 0
        self.lease_reclaims = 0
        # fleet identity heartbeats ride the broker hash so any frontend
        # can enumerate live replicas (common/fleet.py); the frontend
        # fills in the advertised metrics host/port at start()
        self._advertise = ("127.0.0.1", 0)
        self._started_wall = 0.0
        self._heartbeater: Optional[fleet.Heartbeater] = None
        self._replica_supervisor: Optional[fleet.ReplicaSupervisor] = None
        # wedge failover (ISSUE 7): with ZOO_CPU_FALLBACK=1 a backend-loss
        # error drains the window onto pre-built CPU executables and keeps
        # serving degraded until the supervisor reports recovered. The
        # flag/t0/seconds are written on the serve thread and read from
        # frontend/bench threads — all under _state_lock.
        self._cpu_fallback = resilience.cpu_fallback_enabled()
        self._supervisor: Optional[resilience.BackendSupervisor] = None
        self._failover = False
        self._failover_t0: Optional[float] = None
        self.failover_seconds: List[float] = []

    def _decode_images(self, inputs):
        """Decode any raw-image entries and run the preprocessing chain
        (ref PreProcessing.scala:67-90: bytes -> mat -> configured
        resize/crop/normalize -> tensor)."""
        out = {}
        for k, v in inputs.items():
            if isinstance(v, schema.ImageBytes):
                import io

                from PIL import Image
                arr = np.asarray(
                    Image.open(io.BytesIO(v.data)).convert("RGB"),
                    np.float32)
                if self.image_preprocess is not None:
                    arr = self.image_preprocess(arr)
                v = np.asarray(arr, np.float32)
            out[k] = v
        return out

    # --------------------------------------------------- lane scheduling
    def _lane_order(self) -> str:
        """Comma-joined lane preference for the next read — weighted-
        deficit scheduling. Each lane accrues one credit per record it got
        served; the lane with the lowest credit/weight ratio reads first.
        Under sustained contention lanes converge on their weight shares
        (default 4:2:1), and a lane that has been skipped drifts to the
        lowest ratio and MUST read next — batch work always drains."""
        ratios = {lane: self._lane_credit.get(lane, 0.0)
                  / max(self.lane_weights.get(lane, 1.0), 1e-9)
                  for lane in schema.PRIORITIES}
        base = min(ratios.values())
        if base > 0:
            # renormalize so the minimum ratio is 0 — credits stay bounded
            # over long runs without changing the relative order
            for lane in self._lane_credit:
                self._lane_credit[lane] = max(
                    0.0, self._lane_credit[lane] - base
                    * max(self.lane_weights.get(lane, 1.0), 1e-9))
        order = sorted(schema.PRIORITIES,
                       key=lambda l: (ratios[l],
                                      schema.PRIORITIES.index(l)))
        return ",".join(order)

    def _asm_trigger(self) -> float:
        """perf_counter time at which the assembly bucket must dispatch
        even partially filled: the oldest member's lane max-wait cap,
        tightened by any member whose deadline slack is about to run
        out. With the default max_wait of 0 this is the arrival time
        itself — every read dispatches immediately (legacy behavior)."""
        t = float("inf")
        for _eid, _uri, _inputs, _m, lane, t_arr, t_deadline, _g \
                in self._asm:
            t = min(t, t_arr + self.max_wait_ms.get(lane, 0.0) / 1000.0)
            if t_deadline is not None:
                t = min(t, max(t_arr, t_deadline - self.SLACK_MARGIN_S))
        return t

    def _expire_record(self, uri: str, lane: str, cmds: list):
        """A record's ``deadline_ms`` lapsed before inference: store an
        explicit typed expired result — never a silent drop; the client's
        poll raises DeadlineExpiredError instead of timing out — and
        count it per lane, disjoint from the error counter."""
        cmds.append(("HSET", self.result_key, uri, schema.encode_error(
            "deadline_ms expired before the engine served the record",
            self.cipher, code="expired")))
        self._expired_counter.get(
            lane, self._expired_counter[schema.DEFAULT_PRIORITY]).inc()
        with self._state_lock:
            self.records_expired += 1

    # --------------------------------------------------------------- loop
    def _produce(self, client: BrokerClient, block_ms: int):
        """Host stage: dequeue + decode + preprocess + stack/pad ONE batch.
        Returns ``(x, ctx)`` ready for dispatch, or None when nothing
        servable arrived (per-record errors are flushed here).

        Continuous batching: decoded records accumulate in the assembly
        bucket ``_asm``; the bucket dispatches when it fills, when the
        oldest member has waited out its lane's ``ZOO_SERVING_MAX_WAIT_MS``
        (default 0 — every read dispatches immediately), or when any
        member's deadline slack runs out (``_asm_trigger``). Reads and
        reclaims are lane-ordered by the weighted-deficit schedule."""
        t_dq0 = time.perf_counter()
        # recover entries a dead/crashed consumer never acked (ref: the
        # Redis-streams recovery path the reference LACKS an analog of —
        # XPENDING counts them but they were lost forever; here XCLAIM
        # re-delivers another consumer's entries once their delivery lease
        # has been idle claim_min_idle_ms). Rate-limited: recovery polling
        # must not tax the hot read loop. One sweep claims EVERY expired
        # lease (up to RECLAIM_BATCH); the overflow queues in
        # _claim_backlog and feeds the next dispatches.
        # All stage timing is on the monotonic perf_counter clock — wall-
        # clock stamps let NTP slew corrupt stage stats AND the claim-
        # interval rate limiter.
        entries = []
        room = max(0, self.batch_size - len(self._asm))
        if self._claim_backlog:
            while self._claim_backlog and len(entries) < room:
                entries.append(self._claim_backlog.popleft())
        elif self._reclaim_asap.is_set() or \
                t_dq0 - self._last_claim >= self._claim_interval_s:
            self._reclaim_asap.clear()
            self._last_claim = t_dq0
            # lane-ordered reclaim: a dead peer's INTERACTIVE pending
            # entries re-deliver before its batch-lane entries
            claimed = client.xclaim(self.stream, self.group, self.consumer,
                                    self.claim_min_idle_ms,
                                    self.RECLAIM_BATCH,
                                    lanes=self._lanes_priority)
            if claimed:
                self._redeliver_counter.inc(len(claimed))
                self._reclaim_counter.inc()
                with self._state_lock:
                    self.records_redelivered += len(claimed)
                    self.lease_reclaims += 1
                logger.warning("lease reclaim: %d orphaned entries "
                               "re-delivered to %s", len(claimed),
                               self.consumer)
                entries = claimed[:room]
                self._claim_backlog.extend(claimed[room:])
        if not entries and room > 0:
            eff_block = block_ms
            if self._asm:
                # an armed bucket bounds the blocking read: never sleep
                # past the dispatch trigger of records already waiting
                left_ms = (self._asm_trigger() - t_dq0) * 1000.0
                eff_block = int(min(block_ms, max(0.0, left_ms)))
            entries = client.xreadgroup(self.group, self.consumer,
                                        self.stream, room, eff_block,
                                        lanes=self._lane_order())
        # the client may have transparently redialed inside xclaim/
        # xreadgroup (BrokerClient retry): the peer could be a RESTARTED
        # broker reusing entry ids from 1, so the dedupe ring must reset
        # BEFORE it classifies this read's ids
        cgen = getattr(client, "generation", 0)
        if cgen != self._seen_client_gen:
            self._seen_client_gen = cgen
            self._conn_gen += 1
            self._inflight_ids.clear()
            self._done_ids.clear()
            self._claim_backlog.clear()
            # the bucket's entry ids describe the dead connection too; its
            # records re-deliver via their lease like any unacked entry
            self._asm.clear()
            self._abort_decode()
        # idempotence under redelivery: an id this consumer already has in
        # flight (or has finished this connection) is dropped, so a
        # double-delivered record can never double-count or double-write.
        # Already-done ids get their (lost) ack replayed instead.
        if entries:
            fresh, stale_acks = [], []
            for eid, lane, payload in entries:
                if eid in self._done_ids:
                    stale_acks.append(
                        ("XACK", self.stream, self.group, str(eid)))
                elif eid not in self._inflight_ids:
                    self._inflight_ids.add(eid)
                    fresh.append((eid, lane, payload))
            if stale_acks:
                client.pipeline(stale_acks)
            entries = fresh
        read_n = len(entries)
        t_dq1 = time.perf_counter()
        if read_n:
            self.timer.record("dequeue", t_dq1 - t_dq0)

        t0 = time.perf_counter()
        # intake: decode each fresh entry. Records that terminate HERE
        # (undecodable / image-decode failure / deadline already lapsed)
        # flush their result+ack NOW instead of riding the bucket; the
        # rest join the assembly bucket and bump their lane's deficit
        # credit. Pipelined flush — per-record round-trips dominated host
        # time at large batch sizes.
        term_cmds: list = []
        term_acks: list = []
        for eid, lane, payload in entries:
            ack = ("XACK", self.stream, self.group, str(eid))
            # one bad record (corrupt b64, wrong cipher, bad uri) must not
            # take the batch or the serve loop down: store an error result
            # for it and continue
            try:
                uri, inputs, meta = schema.decode_record_meta(
                    payload, self.cipher)
                schema.validate_uri(uri)
            except Exception as e:
                logger.warning("dropping undecodable record %s: %s", eid, e)
                term_acks.append(ack)
                continue
            try:
                inputs = self._decode_images(inputs)
            except Exception as e:
                # the uri is known: the client gets a real error result
                # (ref stores per-record errors the same way)
                term_cmds.append((
                    "HSET", self.result_key, uri,
                    schema.encode_error(
                        f"image decode failed: {e}", self.cipher)))
                self._err_counter.inc()
                term_acks.append(ack)
                continue
            # from here to the bucket append the eid is in _inflight_ids
            # but not yet settled: an exception escaping to _run's
            # catch-all would strand it — redeliveries of the id are
            # dropped by the dedupe ring while the entry itself is never
            # acked or served, re-pending until a reconnect. Terminate
            # the record instead: typed error + ack, like any bad record.
            try:
                m = self._queue_wait(meta, t_dq1)
                t_deadline = None
                d = meta.get("d") if isinstance(meta, dict) else None
                if isinstance(d, (int, float)) and d > 0 and m is not None:
                    # deadline is relative to the client's enqueue stamp,
                    # already mapped onto this clock by _queue_wait
                    t_deadline = m[0] + d / 1000.0
                if t_deadline is not None and t_dq1 >= t_deadline:
                    self._expire_record(uri, lane, term_cmds)
                    term_acks.append(ack)
                    continue
                # generate side channel: re-validated at intake so a hand-
                # rolled record with junk decode params errors HERE, typed,
                # instead of blowing up the device batch
                try:
                    g = schema.validate_generate(
                        meta.get("g") if isinstance(meta, dict) else None)
                except ValueError as e:
                    term_cmds.append((
                        "HSET", self.result_key, uri, schema.encode_error(
                            f"bad generate request: {e}", self.cipher)))
                    self._err_counter.inc()
                    term_acks.append(ack)
                    continue
                self._lane_credit[lane] = \
                    self._lane_credit.get(lane, 0.0) + 1.0
                self._asm.append((eid, uri, inputs, m, lane, t_dq1,
                                  t_deadline, g))
            except Exception as e:
                logger.exception("record intake failed for %s", eid)
                term_cmds.append((
                    "HSET", self.result_key, uri, schema.encode_error(
                        f"record intake failed: {e}", self.cipher)))
                self._err_counter.inc()
                term_acks.append(ack)
                continue
        if term_acks or term_cmds:
            client.pipeline(term_cmds + term_acks)
            self._mark_done(term_acks, self._conn_gen)

        # dispatch decision: full bucket, or the max-wait/deadline trigger
        # of the waiting members has passed
        now = time.perf_counter()
        if not self._asm:
            if read_n == 0:
                # an empty poll with an empty bucket is the strongest idle
                # signal there is — it feeds the same streak accounting as
                # an under-half-full batch
                self._grow_batch_on_backlog(0)
            return None
        if len(self._asm) < self.batch_size and now < self._asm_trigger():
            return None                          # keep accumulating
        take = self._asm[:self.batch_size]
        self._asm = self._asm[self.batch_size:]
        self._grow_batch_on_backlog(len(take))

        # step-level decode handoff: on a scheduler-capable model the
        # assembled generate records go straight to the persistent
        # scheduler (heterogeneous decode params welcome — they share
        # the wide step) and only the plain-predict remainder dispatches
        # as a device batch. Page-pool admission control may bounce a
        # record back to the bucket's head, still un-acked, to retry
        # once a live sequence retires.
        if getattr(self.model, "decode_step_fn", None) is not None:
            gen_take = [e for e in take if e[7] is not None]
            if gen_take:
                take = [e for e in take if e[7] is None]
                self._admit_generate(client, gen_take)
                if not take:
                    return None

        # generate and plain-predict records never share a device batch
        # (different executables, different result shapes), and generate
        # records only batch with identical decode params. Dispatch the
        # largest kind now; the rest go back to the bucket's head — still
        # un-acked, keeping their lease and arrival stamps, so progress
        # is guaranteed (every turn serves at least one kind)
        kinds: Dict = {}
        for e in take:
            key = tuple(sorted(e[7].items())) if e[7] is not None else None
            kinds.setdefault(key, []).append(e)
        best_kind = max(kinds, key=lambda k: len(kinds[k]))
        if len(kinds) > 1:
            self._asm = [e for k, members in kinds.items()
                         if k != best_kind for e in members] + self._asm
            take = kinds[best_kind]
        gen_params = dict(best_kind) if best_kind is not None else None

        err_cmds: list = []
        ack_cmds = []
        uris, rows, metas = [], [], []
        for eid, uri, inputs, m, lane, _t_arr, t_deadline, _g in take:
            ack_cmds.append(("XACK", self.stream, self.group, str(eid)))
            if t_deadline is not None and now >= t_deadline:
                # expired while waiting in the bucket
                self._expire_record(uri, lane, err_cmds)
                continue
            uris.append(uri)
            rows.append(inputs)
            metas.append((m, lane))
        if rows:
            # batch by the MAJORITY shape signature — a single malformed
            # leading record must not reject the whole batch
            sig = lambda r: tuple(sorted(  # noqa: E731
                (k, np.shape(v)) for k, v in r.items()))
            counts: Dict = {}
            for r in rows:
                counts[sig(r)] = counts.get(sig(r), 0) + 1
            best = max(counts, key=lambda s: counts[s])
            kept_uris, kept, kept_metas = [], [], []
            for uri, r, m in zip(uris, rows, metas):
                if sig(r) == best:
                    kept_uris.append(uri)
                    kept.append(r)
                    kept_metas.append(m)
                else:
                    err_cmds.append((
                        "HSET", self.result_key, uri, schema.encode_error(
                            f"tensor shapes {dict(best)} expected, got "
                            f"{ {k: np.shape(v) for k, v in r.items()} }",
                            self.cipher)))
                    self._err_counter.inc()
            uris, rows, metas = kept_uris, kept, kept_metas
        if not rows:
            client.pipeline(err_cmds + ack_cmds)
            self._mark_done(ack_cmds, self._conn_gen)
            return None
        n = len(rows)
        sampled = self._tracer.should_sample()
        if gen_params is not None:
            # generate batch: the record's "start" tensor seeds the
            # decoder, its remaining tensor feeds the encoder prefill;
            # both pad to the batch rung so prefill rides the same
            # pre-compiled (sharded) rungs as plain predicts
            bad = None
            if "start" not in rows[0]:
                bad = "generate records need a 'start' input tensor"
            elif len(rows[0]) != 2:
                bad = ("generate records carry exactly two inputs: the "
                       "encoder tensor and 'start'")
            if bad is not None:
                for uri in uris:
                    err_cmds.append((
                        "HSET", self.result_key, uri,
                        schema.encode_error(bad, self.cipher)))
                    self._err_counter.inc()
                client.pipeline(err_cmds + ack_cmds)
                self._mark_done(ack_cmds, self._conn_gen)
                return None
            enc_col = next(k for k in sorted(rows[0]) if k != "start")
            rung = min(self.ladder.rung_for(n), self.batch_size)
            enc, start = list(compile_ahead.pad_to_rung(
                [np.stack([r[enc_col] for r in rows]),
                 np.stack([r["start"] for r in rows])],
                rung, site="serving"))
            x = _GenBatch(enc, start, gen_params,
                          tuple(uris) if sampled else ())
        else:
            cols = self.input_cols or sorted(rows[0].keys())
            batch = [np.stack([r[c] for r in rows]) for c in cols]
            # pad to the nearest ladder rung at or below the current
            # bucket — a short dequeue rides a smaller pre-compiled
            # executable instead of padding all the way up
            # (zoo_bucket_pad_fraction is the waste)
            rung = min(self.ladder.rung_for(n), self.batch_size)
            batch = list(compile_ahead.pad_to_rung(batch, rung,
                                                   site="serving"))
            x = batch[0] if len(batch) == 1 else tuple(batch)
        t_pp1 = time.perf_counter()
        self.timer.record("preprocess", t_pp1 - t0)
        # trace=(dequeue start/end, preprocess start/end) when this batch
        # is sampled — _finish turns the stamps plus the Completed's
        # dispatch/device timing into per-uri spans
        trace = (t_dq0, t_dq1, t0, t_pp1) if sampled else None
        # x rides the ctx too so a backend-lost batch can be re-dispatched
        # on the CPU fallback at retire time (_failover_redispatch); the
        # connection generation gates the dedupe bookkeeping in _finish
        return x, (uris, err_cmds, ack_cmds, n, trace, metas, x,
                   self._conn_gen)

    def _mark_done(self, ack_cmds, gen: int):
        """Move a flushed batch's entry ids from in-flight to the bounded
        done ring (serve-thread only). ``gen`` guards against a batch that
        straddled a broker reconnect poisoning the fresh ring — a
        restarted broker reuses entry ids from 1."""
        if gen != self._conn_gen:
            return
        for c in ack_cmds:
            eid = int(c[3])
            self._inflight_ids.discard(eid)
            self._done_ids[eid] = None
        while len(self._done_ids) > self.DEDUPE_WINDOW:
            self._done_ids.popitem(last=False)

    def _queue_wait(self, meta, t_dq1: float):
        """Measure one record's broker queue wait from its client stamp.
        Returns ``(t_enqueue_on_this_clock, wait_s)`` or None (no stamp).

        The stamp is dual-clock: ``t_pc`` (perf_counter, CLOCK_MONOTONIC —
        directly comparable across processes on one Linux host) is used
        when the delta is plausible (0..1h); otherwise the wall-clock
        stamp covers cross-host clients, clamped at 0 so NTP slew can
        only blur a wait, never fabricate a negative one."""
        if not isinstance(meta, dict) or not meta:
            return None
        wait = None
        t_pc = meta.get("t_pc")
        if isinstance(t_pc, (int, float)):
            d = t_dq1 - float(t_pc)
            if 0.0 <= d < 3600.0:
                wait = d
        if wait is None:
            t_wall = meta.get("t_wall")
            if isinstance(t_wall, (int, float)):
                now = time.time()  # zoolint: disable=wallclock-hotpath
                wait = min(max(0.0, now - float(t_wall)), 3600.0)
        if wait is None:
            return None
        self._wait_hist.observe(wait)
        return (t_dq1 - wait, wait)

    def _grow_batch_on_backlog(self, dequeued: int):
        """Adaptive batch-bucket stepping, both directions. Every dequeue
        coming back full means the stream is producing faster than we
        drain — step up one ladder rung (capped at ``max_batch_size``).
        With warmup on, growth is gated on the next rung's executable
        being built already: the swap is stall-free, and an unready rung
        pins the streak and (re-)kicks its background compile instead of
        compiling in-band on the serve thread. Sustained under-half-full
        dequeues (empty polls included) step back DOWN one rung after
        ``IDLE_SHRINK_AFTER`` turns, bounding pad waste after a burst."""
        if dequeued >= self.batch_size:
            self._full_streak += 1
            self._idle_streak = 0
        elif dequeued * 2 < self.batch_size:
            self._full_streak = 0
            self._idle_streak += 1
        else:
            self._full_streak = 0
            self._idle_streak = 0
        if (self._full_streak >= self.BACKLOG_GROW_AFTER
                and self.batch_size < self.max_batch_size):
            nxt = self.ladder.up(self.batch_size)
            if not self._rung_ready(nxt):
                # hold the current rung until the background compile
                # lands — swapping now would stall the serve thread on an
                # XLA compile exactly when backlog is highest
                self._full_streak = self.BACKLOG_GROW_AFTER
                self._warm_rung(nxt)
                return
            self._set_bucket(nxt, "sustained backlog")
        elif (self._idle_streak >= self.IDLE_SHRINK_AFTER
                and self.batch_size > self.min_batch_size):
            self._set_bucket(self.ladder.down(self.batch_size),
                             "sustained idle")

    def _set_bucket(self, rung: int, why: str):
        """One bucket transition: reset both streaks, record the new size
        on the ``batch_size`` timer series and the serving gauge."""
        self.batch_size = int(rung)
        self._full_streak = 0
        self._idle_streak = 0
        self.timer.record_value("batch_size", self.batch_size)
        self._batch_gauge.set(self.batch_size)
        logger.info("%s: batch bucket -> %d", why, self.batch_size)

    def _rung_ready(self, rung: int) -> bool:
        """Whether switching to ``rung`` is a stall-free swap. Duck-typed
        models (no AOT cache) and warmup-disabled engines always read
        ready — that is the legacy in-band-recompile behavior."""
        fn = getattr(self.model, "rung_ready", None)
        if fn is None or not self._warmup_enabled:
            return True
        try:
            return bool(fn(rung))
        except Exception:
            return True

    def _warm_rung(self, rung: int):
        """Kick a background AOT compile of one rung (growth found it
        cold — e.g. ``ZOO_WARMUP_BUCKETS`` capped the initial warmup)."""
        fn = getattr(self.model, "warm_up", None)
        if fn is not None:
            try:
                fn(rungs=(rung,))
            except Exception:
                logger.debug("rung %d warmup kick failed", rung,
                             exc_info=True)

    def _kick_warmup(self) -> bool:
        """Attach the ladder to the model and start the background AOT
        warmup over ``self._warm_rungs``. Returns False (and stays
        re-kickable from the serve loop) only when the model supports
        warmup but cannot describe its input shapes yet."""
        set_ladder = getattr(self.model, "set_ladder", None)
        warm_up = getattr(self.model, "warm_up", None)
        if set_ladder is None or warm_up is None:
            self._warm_kicked = True   # duck-typed model: nothing to warm
            return False
        try:
            set_ladder(self.ladder)
            has_spec = getattr(self.model, "has_warm_spec", None)
            if has_spec is not None and not has_spec():
                return False           # retry once the model is loaded
            warm_up(rungs=list(self._warm_rungs))
            self._kick_decode_warmup()
            self._warm_kicked = True
            return True
        except Exception:
            logger.exception("ladder warmup failed; serving continues "
                             "with in-band compiles")
            self._warm_kicked = True
            return False

    def _kick_decode_warmup(self):
        """AOT-warm the autoregressive decode rungs too
        (``ZOO_SERVING_DECODE_MAX_SEQ`` > 0 and the model supports
        ``warm_decode``): every batch-rung × seq-length-rung pair
        compiles in the background, so a generate request's growing
        decoder buffer swaps rungs without an in-band compile."""
        if self._decode_max_seq <= 0:
            return
        fn = getattr(self.model, "warm_decode", None)
        if fn is None:
            return
        kw = {}
        if hasattr(self.model, "paged_decode_step_fn"):
            # warm the paged step executables on the same grid, sized the
            # way the scheduler's lazily-built allocator will size the
            # pool — the first live paged dispatch then hits a built shape
            kw["paged_pool"] = (
                decode_scheduler.default_pool_pages(
                    self.max_batch_size,
                    self._decode_max_seq or generation.DEFAULT_SEQ_RUNGS[1],
                    spec_k=self._spec_k),
                generation.DEFAULT_SEQ_RUNGS[0])
        try:
            # a configured draft model means verify steps run k positions
            # past the live length — warm those taller rungs too
            fn(self._decode_max_seq, rungs=list(self._warm_rungs),
               verify_k=(self._spec_k if self._draft_model is not None
                         else 0), **kw)
        except TypeError:
            fn(self._decode_max_seq, rungs=list(self._warm_rungs))
        except Exception:
            logger.debug("decode warmup kick failed", exc_info=True)

    def wait_warm(self, timeout: Optional[float] = None
                  ) -> "ClusterServing":
        """Block until the background ladder compiles finish (tests and
        bench cold-start timing; no-op for duck-typed models)."""
        fn = getattr(self.model, "wait_warm", None)
        if fn is not None:
            fn(timeout=timeout)
        return self

    def _dispatch(self, x):
        """Device stage: non-blocking when the model supports it (an
        InferenceModel dispatches the jitted executable and returns device
        futures); duck-typed models fall back to their blocking predict.
        While failover is active, dispatch routes to the pre-built CPU
        rung instead — synchronous by nature, the host result rides the
        pipeline window as-is."""
        if isinstance(x, _GenBatch):
            return self._dispatch_generate(x)
        if self.failover_active:
            cpu_predict = getattr(self.model, "predict_cpu", None)
            if cpu_predict is not None:
                return cpu_predict(x)
        fn = getattr(self.model, "predict_async", None)
        return fn(x) if fn is not None else self.model.predict(x)

    def _dispatch_generate(self, gb: "_GenBatch"):
        """Run one generate batch's decode loop: (sharded) AOT prefill
        plus ``n`` bucketed decode steps (inference/generation.py).
        Synchronous by nature — every step feeds the previous step's
        output back — so the host ``[batch, steps, dim]`` result rides
        the pipeline window as-is, like the CPU-failover path. Sampled
        batches pass their uris through as decode-span trace ids."""
        p = gb.params
        n = int(p.get("n", 16))
        kw = dict(mode=p.get("m", "greedy"),
                  temperature=float(p.get("t", 1.0)), seed=p.get("s"))
        fn = getattr(self.model, "generate", None)
        if fn is not None:
            return fn(gb.enc, gb.start, n, trace_ids=gb.trace_uris, **kw)
        fn = getattr(self.model, "infer", None)
        if fn is not None:       # duck-typed zoo model (e.g. Seq2Seq)
            return fn(gb.enc, gb.start, n + 1, **kw)
        raise TypeError("model supports neither generate() nor infer() — "
                        "generate records need an autoregressive model")

    def _fetch(self, pending):
        fn = getattr(self.model, "predict_fetch", None)
        return np.asarray(fn(pending) if fn is not None else pending)

    # --------------------------------------------- step-level decode
    def _ensure_scheduler(self) -> decode_scheduler.DecodeScheduler:
        """The persistent step scheduler, built at the first generate
        admission: the page pool sizes off this engine's batch ladder ×
        the decode seq grid (``ZOO_SERVING_DECODE_MAX_SEQ``, falling back
        to the default seq-ladder top)."""
        if self._decode_sched is None:
            draft_fn = None
            if self._draft_model is not None:
                draft_fn = (self._draft_model.decode_step_fn()
                            if hasattr(self._draft_model, "decode_step_fn")
                            else self._draft_model)
            paged_fn = None
            make_paged = getattr(self.model, "paged_decode_step_fn", None)
            if make_paged is not None:
                try:
                    paged_fn = make_paged()
                except Exception:
                    logger.debug("paged decode seam unavailable",
                                 exc_info=True)
            sched = decode_scheduler.DecodeScheduler(
                self.model.decode_step_fn(),
                max_batch=self.max_batch_size,
                max_seq=(self._decode_max_seq
                         or generation.DEFAULT_SEQ_RUNGS[1]),
                batch_ladder=self.ladder,
                draft_fn=draft_fn, spec_k=self._spec_k,
                paged_step_fn=paged_fn)
            # published under the state lock: /healthz's decode_state()
            # reads the attribute from the HTTP thread
            with self._state_lock:
                self._decode_sched = sched
        return self._decode_sched

    def _admit_generate(self, client: BrokerClient, entries: List[tuple]):
        """Hand assembled generate records to the step scheduler. Each
        entry settles right here: expired/malformed records flush a typed
        result + ack now; admitted ones park their ack in ``_gen_live``
        until the sequence retires (``_finish_decode``); a record the
        page pool cannot hold yet goes back to the bucket's head,
        un-acked, to retry after the next retirement."""
        sched = self._ensure_scheduler()
        now = time.perf_counter()
        term_cmds: list = []
        term_acks: list = []
        back: list = []
        for entry in entries:
            eid, uri, inputs, m, lane, _t_arr, t_deadline, g = entry
            ack = ("XACK", self.stream, self.group, str(eid))
            if t_deadline is not None and now >= t_deadline:
                self._expire_record(uri, lane, term_cmds)
                term_acks.append(ack)
                continue
            bad = None
            if "start" not in inputs:
                bad = "generate records need a 'start' input tensor"
            elif len(inputs) != 2:
                bad = ("generate records carry exactly two inputs: the "
                       "encoder tensor and 'start'")
            if bad is not None:
                term_cmds.append((
                    "HSET", self.result_key, uri,
                    schema.encode_error(bad, self.cipher)))
                self._err_counter.inc()
                term_acks.append(ack)
                continue
            enc_col = next(k for k in sorted(inputs) if k != "start")
            try:
                seq = sched.admit(
                    np.asarray(inputs[enc_col]),
                    np.asarray(inputs["start"], np.float32),
                    int(g.get("n", 16)), mode=g.get("m", "greedy"),
                    temperature=float(g.get("t", 1.0)), seed=g.get("s"),
                    tag=uri, lane=lane,
                    trace_uri=(uri if self._tracer.should_sample()
                               else None))
            except decode_scheduler.PagePoolExhausted:
                back.append(entry)
                continue
            except Exception as e:
                term_cmds.append((
                    "HSET", self.result_key, uri, schema.encode_error(
                        f"generate admission failed: {e}", self.cipher)))
                self._err_counter.inc()
                term_acks.append(ack)
                continue
            self._gen_live[seq] = (uri, ack, m, lane, self._conn_gen)
        if back:
            self._asm = back + self._asm
        if term_acks or term_cmds:
            client.pipeline(term_cmds + term_acks)
            self._mark_done(term_acks, self._conn_gen)

    def _decode_should_yield(self) -> bool:
        """Per-step lane preemption, honoring the same weighted-deficit
        order reads use: defer this decode step when records WAITING in
        the assembly bucket belong to a lane with a strictly lower
        credit/weight ratio than every lane currently decoding — the
        device stays free for the imminent encode dispatch. The
        starvation floor guarantees a step runs after
        ``DECODE_STARVATION_FLOOR`` consecutive deferrals."""
        if self._decode_yield_streak >= self.DECODE_STARVATION_FLOOR:
            return False
        if not self._asm or not self._gen_live:
            return False

        def ratio(lane):
            return (self._lane_credit.get(lane, 0.0)
                    / max(self.lane_weights.get(lane, 1.0), 1e-9))

        waiting = min(ratio(e[4]) for e in self._asm)
        live = min(ratio(info[3]) for info in self._gen_live.values())
        return waiting < live

    def _decode_tick(self, client: BrokerClient) -> int:
        """One serve-loop turn's decode slice: run (or preempt) exactly
        one scheduler step and flush whatever finished. Encode batches
        interleave between these steps instead of behind whole
        generations."""
        sched = self._decode_sched
        if sched is None or not sched.live:
            return 0
        if self._decode_should_yield():
            self._decode_yield_streak += 1
            self._preempt_counter.inc()
            return 0
        self._decode_yield_streak = 0
        return self._finish_decode(client, sched.step())

    def _finish_decode(self, client: BrokerClient, finished) -> int:
        """Flush retired sequences: postprocess + typed result + held-back
        ack, end-to-end latency on the record's own lane series. Pages
        are already back in the pool (the scheduler freed them at
        retirement)."""
        if not finished:
            return 0
        cmds: list = []
        acks: list = []
        lanes_meta = []
        t1 = time.perf_counter()
        for seq in finished:
            info = self._gen_live.pop(seq, None)
            if info is None:
                continue
            uri, ack, m, lane, gen = info
            if gen != self._conn_gen:
                # admitted before a broker reconnect: the entry id means
                # nothing to the new connection — the record re-delivers
                # via its lease and is deduped by result idempotence
                continue
            try:
                pred = seq.result
                if self.postprocess is not None:
                    pred = self.postprocess(pred)
                val = schema.encode_result(pred, self.cipher)
            except Exception as e:
                logger.warning("postprocess failed for %s: %s", uri, e)
                val = schema.encode_error(
                    f"postprocess failed: {e}", self.cipher)
            cmds.append(("HSET", self.result_key, uri, val))
            acks.append(ack)
            lanes_meta.append((m, lane, uri, seq))
        if not acks and not cmds:
            return 0
        n = len(acks)
        with self._state_lock:
            self.records_out += n
        self._rec_counter.inc(n)
        for m, lane, uri, seq in lanes_meta:
            # trace-sampled sequences stamp their uri as the exemplar —
            # the same id the scheduler recorded decode_step spans under
            ex = uri if seq.trace_uri is not None else None
            if m is not None:
                self._latency_hist.get(
                    lane, self._latency_hist[schema.DEFAULT_PRIORITY]
                ).observe(max(0.0, t1 - m[0]), exemplar=ex)
            # cost settlement: the scheduler accumulated this sequence's
            # share of every wide step it rode and its page high water
            lane_key = lane if lane in self._cost_steps_hist \
                else schema.DEFAULT_PRIORITY
            self._cost_device_hist[(lane_key, "generate")].observe(
                max(0.0, seq.device_s), exemplar=ex)
            self._cost_steps_hist[lane_key].observe(seq.generated)
            self._cost_pages_hist[lane_key].observe(seq.pages_held)
        client.pipeline(cmds + acks)
        self._mark_done(acks, self._conn_gen)
        return n

    def _abort_decode(self):
        """Broker reconnect / shutdown: drop every live sequence — pages
        free immediately, held-back acks are discarded, and the un-acked
        entries re-deliver via their lease (at-least-once, never a
        double ack)."""
        if self._decode_sched is not None and self._decode_sched.live:
            self._decode_sched.abort_all()
        self._gen_live.clear()
        self._decode_yield_streak = 0

    # ----------------------------------------------------------- failover
    @property
    def failover_active(self) -> bool:
        """True while dispatch is swapped onto the CPU fallback rungs —
        /healthz reports degraded-but-serving (never 503) in this mode."""
        with self._state_lock:
            return self._failover

    def _enter_failover(self, err):
        with self._state_lock:
            if self._failover:
                return
            self._failover = True
            self._failover_t0 = time.perf_counter()
        logger.warning("backend loss (%s); draining onto the CPU "
                       "fallback rungs", err)
        if self._supervisor is not None:
            self._supervisor.report_failure(err)

    def _exit_failover(self):
        with self._state_lock:
            if not self._failover:
                return
            self._failover = False
            self._failover_t0 = None
        logger.warning("backend recovered; dispatch swapped back to the "
                       "accelerator rungs")

    def _failover_redispatch(self, client: BrokerClient,
                             comp: Completed) -> Optional[int]:
        """Re-run one backend-lost batch through the pre-built CPU
        executable and flush its real results — the drain half of
        failover. Returns the flushed record count, or None when this
        batch cannot fail over (no CPU predict on the model, a ctx that
        predates the wiring, or the CPU path failing too) — the caller
        then falls through to the normal error-result path."""
        x = comp.ctx[6] if len(comp.ctx) > 6 else None
        cpu_predict = getattr(self.model, "predict_cpu", None)
        if x is None or cpu_predict is None or isinstance(x, _GenBatch):
            # a generate batch has no one-shot CPU rung to fail over to —
            # its records take the normal error-result path
            return None
        self._enter_failover(comp.error)
        try:
            preds = np.asarray(cpu_predict(x))
        except Exception:
            logger.exception("CPU failover redispatch failed; falling "
                             "back to error results")
            return None
        with self._state_lock:
            t0, self._failover_t0 = self._failover_t0, None
        if t0 is not None:
            # drain → first CPU result: serving_failover_seconds in bench
            dt = time.perf_counter() - t0
            with self._state_lock:
                self.failover_seconds.append(dt)
            self.timer.record("failover", dt)
        return self._finish(client, comp._replace(result=preds, error=None))

    def _finish(self, client: BrokerClient, comp: Completed) -> int:
        """Drain stage: postprocess + result/ack flush for one retired
        batch. A batch lost to the *backend* (not a model bug) first gets
        one shot at the CPU failover path — only when that is off or also
        fails do its records get error results."""
        if comp.error is not None and self._cpu_fallback \
                and resilience.is_backend_loss(comp.error):
            served = self._failover_redispatch(client, comp)
            if served is not None:
                return served
        uris, err_cmds, ack_cmds, n, trace, metas = comp.ctx[:6]
        gen = comp.ctx[7] if len(comp.ctx) > 7 else self._conn_gen
        # err_cmds are already counted where they were created (_produce):
        # expired results ride the same flush but belong to the expired
        # counter, never the error counter
        if comp.error is not None:
            # model incompatibility: every record gets an error result and
            # the entries are acked — losing them silently would hang the
            # clients AND pin the broker's GC low-water mark forever
            logger.error("inference failed for batch of %d: %s",
                         n, comp.error)
            err = schema.encode_error(f"inference failed: {comp.error}",
                                      self.cipher)
            client.pipeline(
                err_cmds
                + [("HSET", self.result_key, uri, err) for uri in uris]
                + ack_cmds)
            self._mark_done(ack_cmds, gen)
            self.timer.record("inference_error", comp.inflight_s)
            self._err_counter.inc(n)
            return 0
        self.timer.record("inference", comp.inflight_s)
        preds = np.asarray(comp.result)[:n]
        t0 = time.perf_counter()
        cmds = list(err_cmds)
        for uri, pred in zip(uris, preds):
            # a postprocess/encode failure on ONE record must not discard
            # the whole batch's results and acks (the batch would XCLAIM-
            # redeliver and fail deterministically forever)
            try:
                if self.postprocess is not None:
                    pred = self.postprocess(pred)
                val = schema.encode_result(pred, self.cipher)
            except Exception as e:
                logger.warning("postprocess failed for %s: %s", uri, e)
                val = schema.encode_error(
                    f"postprocess failed: {e}", self.cipher)
            cmds.append(("HSET", self.result_key, uri, val))
        # count BEFORE the flush: the broker makes the HSETs visible to
        # polling clients before it answers the pipelined write, so a
        # client that sees its result and immediately reads /metrics must
        # find the batch already counted
        t_pp_end = time.perf_counter()
        self.timer.record("postprocess", t_pp_end - t0)
        with self._state_lock:
            self.records_out += n
        self._rec_counter.inc(n)
        # end-to-end latency per stamped record: client enqueue (mapped
        # onto this clock by _queue_wait) → results about to flush, on
        # the record's own priority series. Sampled batches stamp the
        # record uri as the latency exemplar — the /trace link for this
        # very observation. Cost settlement: each record is billed an
        # equal share of the batch's device time.
        dev_share = max(0.0, comp.inflight_s) / max(1, n)
        for (m, lane), uri in zip(metas, uris):
            ex = uri if trace is not None else None
            if m is not None:
                self._latency_hist.get(
                    lane, self._latency_hist[schema.DEFAULT_PRIORITY]
                ).observe(max(0.0, t_pp_end - m[0]), exemplar=ex)
            self._cost_device_hist.get(
                (lane, "encode"),
                self._cost_device_hist[(schema.DEFAULT_PRIORITY, "encode")]
            ).observe(dev_share, exemplar=ex)
        if trace is not None:
            self._record_batch_trace(uris, trace, comp, t0, t_pp_end,
                                     metas)
        client.pipeline(cmds + ack_cmds)
        self._mark_done(ack_cmds, gen)
        return n

    def _record_batch_trace(self, uris, trace, comp: Completed,
                            t_post0: float, t_post1: float, metas=()):
        """Turn the sampled batch's stage stamps into per-uri spans. The
        record's uri is the trace id, so ``observability.trace(uri)`` (or a
        frontend caller that kept its uri) gets the full decomposition:
        ``serve`` (root, dequeue start → postprocess end) over contiguous
        ``dequeue``/``preprocess``/``device``/``postprocess`` children,
        with ``dispatch`` a sub-span of ``device``. Batch-level stages are
        shared verbatim by every uri in the batch. Records that carried a
        client stamp additionally get the measured ``queue_wait`` span
        (enqueue → dequeue-return) ahead of the engine stages — parentless
        like ``client_enqueue``, because both cross the process boundary."""
        t_dq0, t_dq1, t_pp0, t_pp1 = trace
        tr = self._tracer
        for uri, ml in zip(uris, list(metas) or [None] * len(uris)):
            m = ml[0] if ml else None
            if m is not None:
                tr.record(uri, "queue_wait", m[0], t_dq1)
            tr.record(uri, "dequeue", t_dq0, t_dq1, parent="serve")
            tr.record(uri, "preprocess", t_pp0, t_pp1, parent="serve")
            tr.record(uri, "dispatch", comp.t_submit,
                      comp.t_submit + comp.dispatch_s, parent="device")
            tr.record(uri, "device", comp.t_submit,
                      comp.t_submit + comp.inflight_s, parent="serve")
            tr.record(uri, "postprocess", t_post0, t_post1, parent="serve")
            tr.record(uri, "serve", t_dq0, t_post1)

    def _serve_once(self, client: BrokerClient,
                    pipe: Optional[DevicePipeline] = None) -> int:
        """One loop turn: produce a batch and stage its dispatch; retire
        batches the window pushed out (or everything, when the stream
        idles — a lone request must not wait for the window to fill)."""
        self._admission_tick(client)
        if pipe is None:                         # direct-call compatibility
            pipe = self._make_pipe()
            done = []
            produced = self._produce(client, self.block_ms)
            if produced is not None:
                done = pipe.submit(*produced)
            done += pipe.drain()
            return (sum(self._finish(client, c) for c in done)
                    + self._decode_tick(client))
        # while batches are in flight — or the decode scheduler holds
        # live sequences — poll instead of blocking in the broker read:
        # there is work ready to advance right now
        decode_live = (self._decode_sched is not None
                       and self._decode_sched.live > 0)
        block_ms = 0 if (pipe.in_flight or decode_live) else self.block_ms
        produced = self._produce(client, block_ms)
        if produced is not None:
            done = pipe.submit(*produced)
            if self.pipeline_window == 0:        # measured sync baseline
                done += pipe.drain()
        else:
            done = pipe.drain()
        served = sum(self._finish(client, c) for c in done)
        # decode advances AFTER the encode work of this turn was staged:
        # one wide step per turn, preempted when a waiting encode lane
        # outranks the decoding lanes
        return served + self._decode_tick(client)

    # ------------------------------------------------- admission control
    def _admission_tick(self, client: BrokerClient):
        """Periodic (``ZOO_SERVING_ADMISSION_S``) control step on the
        serve thread: when any per-lane p99 burn is past the shed
        threshold (the per-priority SLOs in common/slo.py — ``shed=False``
        there, so they drive admission, never the /healthz 503), flip the
        broker's batch-lane XSHED flag so NEW batch enqueues fast-fail at
        XADD while interactive keeps flowing; un-flip once the burn
        clears. The per-lane queue-depth gauges refresh on the same
        cadence."""
        if self._admission_interval_s <= 0:
            return
        now = time.perf_counter()
        if now - self._last_admission < self._admission_interval_s:
            return
        self._last_admission = now
        mon = slo.get_monitor()
        try:
            mon.tick_if_stale()
        except Exception:
            logger.debug("slo sample failed", exc_info=True)
        want = any(mon.burning(f"serving_p99_latency_{lane}")
                   for lane in schema.PRIORITIES)
        with self._state_lock:
            flip = want != self.admission_shedding or self._admission_dirty
        if flip:
            # dirty forces a re-assert after a reconnect: a RESTARTED
            # broker lost its shed flags
            client.xshed_set(self.stream, self.ADMISSION_LANE, want)
            with self._state_lock:
                self.admission_shedding = want
                self._admission_dirty = False
            self._admission_gauge.set(1.0 if want else 0.0)
            logger.warning("admission control: %s lane %s",
                           self.ADMISSION_LANE,
                           "SHEDDING" if want else "accepting")
        for lane in schema.PRIORITIES:
            self._lane_depth_gauge[lane].set(
                client.xlen(self.stream, lane))

    def _make_pipe(self) -> DevicePipeline:
        return DevicePipeline(self._dispatch,
                              window=max(1, self.pipeline_window),
                              fetch_fn=self._fetch, timer=self.timer)

    def _run(self):
        logger.info("serving started: stream=%s batch=%d window=%d",
                    self.stream, self.batch_size, self.pipeline_window)
        client: Optional[BrokerClient] = None
        # the pipeline outlives broker reconnects: in-flight device work is
        # finished against the redialed client, so results are never lost
        # to a socket failure between dispatch and drain
        pipe = self._make_pipe()
        while not self._stop.is_set():
            try:
                if client is None:
                    client = BrokerClient(host=self.broker_host,
                                          port=self.broker_port)
                if self._warmup_enabled and not self._warm_kicked:
                    # the model had no input spec at start() (nothing
                    # loaded yet) — kick the ladder warmup the moment it
                    # can describe its shapes
                    self._kick_warmup()
                if self._supervisor is not None and self.failover_active \
                        and self._supervisor.state == \
                        resilience.BackendSupervisor.OK:
                    # the supervisor's probe streak says the backend is
                    # back: swap dispatch off the CPU rungs
                    self._exit_failover()
                self._serve_once(client, pipe)
            except (ConnectionError, OSError):
                # broker died or the socket went bad: DROP the client and
                # redial next round (keeping a dead client would loop
                # forever on bad-fd errors)
                if self._stop.is_set():
                    break
                logger.warning("broker connection lost; reconnecting")
                if client is not None:
                    client.close()
                    client = None
                # a restarted broker reuses entry ids from 1: the dedupe
                # ring and claim backlog describe a dead connection
                self._conn_gen += 1
                self._seen_client_gen = 0   # fresh client starts at gen 0
                self._inflight_ids.clear()
                self._done_ids.clear()
                self._claim_backlog.clear()
                self._asm.clear()
                self._abort_decode()
                with self._state_lock:
                    # re-assert the shed flag on the next admission tick —
                    # a restarted broker came up accepting everything
                    self._admission_dirty = True
                time.sleep(0.2)
            except Exception:
                # the loop is the service — survive anything per-batch
                logger.exception("serve step failed; continuing")
                time.sleep(0.05)
        # drain-on-stop: in-flight batches still flush their results/acks
        # so a clean shutdown never strands dispatched work
        try:
            for c in pipe.drain():
                if client is not None:
                    self._finish(client, c)
        except Exception:
            logger.exception("final drain failed; pending entries will be "
                             "re-delivered via XCLAIM")
        # live decode sequences don't run to completion on stop: their
        # entries were never acked, so another replica (or a restart)
        # re-serves them from the lease — bounded shutdown wins
        self._abort_decode()
        if client is not None:
            client.close()

    # -------------------------------------------------------------- fleet
    def set_advertise(self, host: str, port: int):
        """Where peers can scrape this replica's ``/metrics`` — filled in
        by the FrontEnd that owns this engine (port 0 = headless)."""
        with self._state_lock:   # heartbeater reads it from its thread
            self._advertise = (host, int(port))

    def _replica_info(self) -> fleet.ReplicaInfo:
        with self._state_lock:
            n = self.records_out
            host, port = self._advertise
            started = self._started_wall
        # wall clock by design: heartbeat ages are compared across
        # processes/hosts (see common/fleet.py module docstring)
        now = time.time()  # zoolint: disable=wallclock-hotpath
        return fleet.ReplicaInfo(
            replica_id=self.replica_id, host=host, port=port,
            started_at=started, last_heartbeat=now,
            records_total=n, stream=self.stream)

    # ---------------------------------------------------------------- api
    def start(self) -> "ClusterServing":
        if self._thread is not None:
            return self
        # ZOO_FLIGHT_RECORDER=1: ring-buffer the serve-loop spans and dump
        # a postmortem to zoo_tpu_logs/ on SIGTERM — a killed serving
        # replica leaves evidence of what its pipeline was doing
        from analytics_zoo_tpu.common import profiling
        profiling.maybe_arm_from_env()
        # retain windowed metric history while serving (ISSUE 17): the
        # background sampler feeds /metrics/history, /query and the SLO
        # monitor's burn windows (idempotent; ZOO_TS_TICK_S=0 opts out)
        timeseries.get_store().start()
        # supervise the backend only when failover can act on its verdicts
        # (or a fault drill wants to observe them) — plain deployments get
        # no extra thread
        if self._cpu_fallback or resilience.fault_plan_active():
            sup = resilience.get_supervisor()
            with self._state_lock:
                self._supervisor = sup
            sup.ensure_started()
        if self._warmup_enabled:
            # persistent XLA cache + background AOT over the whole ladder:
            # the serve thread then swaps buckets without ever compiling
            compile_ahead.configure_persistent_cache()
            self._kick_warmup()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        # join the fleet: periodic heartbeats through the broker hash so
        # any frontend can enumerate/scrape this replica
        # (ZOO_FLEET_HEARTBEAT_S=0 opts out)
        if self._heartbeater is None and fleet.heartbeat_interval_s() > 0:
            with self._state_lock:
                self._started_wall = \
                    time.time()  # zoolint: disable=wallclock-hotpath
            registry = fleet.ReplicaRegistry(self.broker_host,
                                             self.broker_port)
            self._heartbeater = fleet.Heartbeater(registry,
                                                  self._replica_info)
            self._heartbeater.start()
            # watch the fleet for crashed peers: on orphaned pending
            # entries the supervisor expedites this replica's next reclaim
            # sweep instead of waiting out the rate limiter
            self._replica_supervisor = fleet.ReplicaSupervisor(
                registry, self.stream, self.group,
                broker_host=self.broker_host, broker_port=self.broker_port,
                own_replica_id=self.replica_id,
                on_orphans=self._expedite_reclaim)
            self._replica_supervisor.start()
        return self

    def _expedite_reclaim(self, n_orphans: int):
        """ReplicaSupervisor callback: a stale peer left ``n_orphans``
        unacked entries — run the next reclaim sweep immediately (the
        entries still wait out their lease inside the broker)."""
        self._reclaim_asap.set()

    def stop(self):
        """Graceful drain: stop reading → flush in-flight → ack →
        deregister. The serve thread joins BEFORE the heartbeater
        deregisters — deregistering first would mark this replica's
        pending entries orphaned while the final drain is still about to
        ack them, handing peers a double-processing window."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        rsup, self._replica_supervisor = self._replica_supervisor, None
        if rsup is not None:
            rsup.stop()
        hb, self._heartbeater = self._heartbeater, None
        if hb is not None:
            hb.stop()   # deregisters only now, after the final drain acked
        # the supervisor is a process singleton, but the engine is the
        # process's deployment unit — stop the probe loop with the serving
        with self._state_lock:
            sup, self._supervisor = self._supervisor, None
        if sup is not None:
            sup.stop()

    def decode_state(self) -> Dict:
        """Decode occupancy at a glance — the /healthz ``decode`` block:
        live sequences, page-pool pages in use/free, preemptions since
        start. Counts are read without the serve thread's cooperation
        (int/len reads of scheduler state — point-in-time, never exact
        mid-step), which is the health endpoint's contract everywhere."""
        sched = self._decode_sched
        out = {"live_sequences": int(sched.live) if sched else 0,
               "steps_run": int(sched.steps_run) if sched else 0,
               "preemptions": int(self._preempt_counter.value),
               "pages_in_use": 0, "pages_free": 0}
        alloc = sched.allocator if sched else None
        if alloc is not None:
            out["pages_in_use"] = int(alloc.n_in_use)
            out["pages_free"] = int(alloc.n_free)
        return out

    def metrics(self) -> Dict:
        """Throughput + stage latencies (ref Flink numRecordsOutPerSecond +
        Timer stats)."""
        with self._state_lock:
            out = {"records_out": self.records_out,
                   "records_redelivered": self.records_redelivered,
                   "lease_reclaims": self.lease_reclaims,
                   "records_expired": self.records_expired,
                   "admission_shedding": self.admission_shedding}
        out.update(self.timer.summary())
        # model-parallel placement: strategy, shard count and per-shard
        # HBM bytes when the model was sharded (InferenceModel.shard)
        fn = getattr(self.model, "shard_info", None)
        if fn is not None:
            try:
                info = fn()
            except Exception:
                info = None
            if info:
                out["sharding"] = info
        return out

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
