"""Broker management — build, launch and talk to the serving data plane.

The reference's data plane is a Redis server: streams in, hash out
(SURVEY.md §3.4). Here the equivalent is ``zbroker``, a native C++ broker
(serving/native/zbroker.cpp) compiled on first use with g++ and launched as
a subprocess — same process model as Redis, no external dependency. A
pure-Python broker with the identical wire protocol backs environments
without a toolchain (and doubles as the protocol's executable spec).

Protocol: newline-delimited text; payloads are opaque base64 (see
zbroker.cpp header for the command set). Entries carry a *lane* tag
(priority class) so the engine can dequeue interactive traffic ahead of
batch work, and per-lane XSHED flags let admission control reject new
enqueues at the broker instead of letting them rot in the queue.
"""

from __future__ import annotations

import errno
import os
import socket
import socketserver
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

_NATIVE_SRC = os.path.join(os.path.dirname(__file__), "native", "zbroker.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "native", "build")

# lane of entries enqueued without an explicit priority — mirrors
# schema.DEFAULT_PRIORITY (broker must stay importable standalone)
DEFAULT_LANE = "default"


class ShedError(RuntimeError):
    """XADD rejected because the target lane is shedding (admission
    control). Typed so enqueueing clients fail fast instead of burning
    their poll timeout waiting for a result that will never exist."""


def build_native_broker(force: bool = False) -> Optional[str]:
    """Compile zbroker.cpp → build/zbroker. Returns binary path or None if
    no toolchain. Rebuilds when the source is newer than the binary."""
    binary = os.path.join(_BUILD_DIR, "zbroker")
    if not force and os.path.exists(binary) and \
            os.path.getmtime(binary) >= os.path.getmtime(_NATIVE_SRC):
        return binary
    os.makedirs(_BUILD_DIR, exist_ok=True)
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-pthread", "-o", binary,
             _NATIVE_SRC],
            check=True, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        err = getattr(e, "stderr", "")
        import logging
        logging.getLogger(__name__).warning(
            "native broker build failed (%s); falling back to python broker",
            err or e)
        return None
    return binary


def _reconnects_total():
    # lazy import: broker must stay importable without the telemetry stack
    from analytics_zoo_tpu.common import telemetry
    return telemetry.get_registry().counter(
        "zoo_broker_reconnects_total",
        "transparent client reconnects after transient socket errors")


class BrokerClient:
    """One TCP connection to the broker. Thread-compatible: callers must
    not share one client across threads (make one per thread — connects
    are cheap; matches redis-py usage in the reference client)."""

    # commands safe to transparently resend after a transient socket
    # error: pure reads plus XACK (double-ack is a no-op returning 0).
    # XADD/HSET/HDEL/DEL are NOT here — resending them after an ambiguous
    # failure could duplicate a record or clobber a newer write.
    _IDEMPOTENT = frozenset({
        "PING", "XLEN", "XREADGROUP", "XCLAIM", "XPENDING", "XACK",
        "HGET", "HKEYS", "XSHED",  # XSHED writes an absolute flag value
    })
    RECONNECT_TRIES = 3
    RECONNECT_BACKOFF_S = 0.05

    def __init__(self, host: str = "127.0.0.1", port: int = 6399,
                 timeout: float = 30.0):
        self.addr = (host, port)
        self._timeout = timeout
        self.sock = socket.create_connection(self.addr, timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = b""
        # bumped on every transparent _reconnect: callers holding state
        # keyed by broker entry ids (the engine's dedupe ring) watch this
        # to learn the peer may be a RESTARTED broker with fresh ids
        self.generation = 0

    # --- wire ---
    def _send(self, *parts: str):
        self.sock.sendall((" ".join(parts) + "\n").encode())

    def _readline(self) -> str:
        while b"\n" not in self._buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("broker closed connection")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        return line.decode()

    def _reply(self, raise_on_error: bool = True):
        line = self._readline()
        kind, rest = line[0], line[1:]
        if kind == "+":
            return rest
        if kind == ":":
            return int(rest)
        if kind == "$":
            return None if rest == "-1" else rest
        if kind == "*":
            return [self._readline() for _ in range(int(rest))]
        if kind == "-":
            # -SHED is a typed refusal (lane admission control), not a
            # protocol failure — callers catch ShedError specifically
            if rest.startswith("SHED"):
                err: RuntimeError = ShedError(rest)
            else:
                err = RuntimeError(f"broker error: {rest}")
            if raise_on_error:
                raise err
            return err
        raise RuntimeError(f"bad reply line: {line!r}")

    @staticmethod
    def _transient(e: BaseException) -> bool:
        """ECONNRESET/EPIPE-class errors worth one transparent retry.
        A clean peer close (empty recv → ConnectionError in _readline)
        counts: that is how a broker restart looks to this client.
        Timeouts do NOT — the command may still be executing."""
        if isinstance(e, (socket.timeout, TimeoutError)):
            return False
        if isinstance(e, (ConnectionResetError, BrokenPipeError,
                          ConnectionError)):
            return True
        return getattr(e, "errno", None) in (errno.ECONNRESET, errno.EPIPE)

    def _reconnect(self):
        """Redial self.addr with bounded exponential backoff and count the
        reconnect (zoo_broker_reconnects_total)."""
        try:
            self.sock.close()
        except OSError:
            pass
        self._buf = b""
        delay = self.RECONNECT_BACKOFF_S
        last: Optional[BaseException] = None
        for _ in range(self.RECONNECT_TRIES):
            try:
                self.sock = socket.create_connection(
                    self.addr, timeout=self._timeout)
                self.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self.generation += 1
                _reconnects_total().inc()
                return
            except OSError as e:
                last = e
                time.sleep(delay)
                delay *= 2
        raise ConnectionError(
            f"broker reconnect to {self.addr} failed: {last}")

    def _cmd(self, *parts: str):
        try:
            self._send(*parts)
            return self._reply()
        except (ConnectionError, OSError) as e:
            if parts[0] not in self._IDEMPOTENT or not self._transient(e):
                raise
            # reconnect once, resend once: at-most-one transparent retry
            # per command keeps the backoff bounded under a dead broker
            self._reconnect()
            self._send(*parts)
            return self._reply()

    # writes are chunked so the broker can drain its send buffer between
    # chunks — one giant sendall can deadlock both peers once the replies
    # fill the kernel buffers while the client is still writing
    PIPELINE_CHUNK = 512

    def pipeline(self, cmds) -> list:
        """Send commands in chunked batches, reading each chunk's replies
        before the next write (same contract as redis-py pipelines in the
        reference client). ``cmds`` is an iterable of argument tuples.
        ALL replies are read before an error is raised, so the connection
        stays in sync even when a command fails."""
        cmds = list(cmds)
        out: list = []
        for start in range(0, len(cmds), self.PIPELINE_CHUNK):
            chunk = cmds[start:start + self.PIPELINE_CHUNK]
            blob = "".join(" ".join(parts) + "\n" for parts in chunk)
            self.sock.sendall(blob.encode())
            out.extend(self._reply(raise_on_error=False) for _ in chunk)
        for r in out:
            if isinstance(r, RuntimeError):
                raise r
        return out

    # --- commands ---
    def ping(self) -> bool:
        return self._cmd("PING") == "PONG"

    def xadd(self, stream: str, payload_b64: str,
             lane: Optional[str] = None) -> int:
        """Append to the stream, tagged with ``lane`` (priority class).
        Raises ShedError when the lane's shed flag is set (XSHED)."""
        if lane is None:
            return int(self._cmd("XADD", stream, payload_b64))
        return int(self._cmd("XADD", stream, payload_b64, lane))

    def xlen(self, stream: str, lane: Optional[str] = None) -> int:
        if lane is None:
            return self._cmd("XLEN", stream)
        return self._cmd("XLEN", stream, lane)

    def xreadgroup(self, group: str, consumer: str, stream: str,
                   count: int, block_ms: int = 0,
                   lanes: Optional[str] = None) -> List[tuple]:
        """Read up to ``count`` new entries for the group. With ``lanes``
        (comma-separated priority order, e.g. "interactive,default,batch")
        delivery drains lanes in that order and each result is an
        ``(id, lane, payload)`` 3-tuple; the legacy laneless form returns
        ``(id, payload)`` and delivers all lanes in id order."""
        old = self.sock.gettimeout()
        if block_ms:
            self.sock.settimeout(max(old or 0, block_ms / 1000.0 + 10))
        try:
            parts = ["XREADGROUP", group, consumer, stream,
                     str(count), str(block_ms)]
            if lanes:
                parts.append(lanes)
            lines = self._cmd(*parts)
        finally:
            self.sock.settimeout(old)
        out: List[tuple] = []
        for ln in lines:
            if lanes:
                i, lane, payload = ln.split(" ", 2)
                out.append((int(i), lane, payload))
            else:
                i, payload = ln.split(" ", 1)
                out.append((int(i), payload))
        return out

    def xclaim(self, stream: str, group: str, consumer: str,
               min_idle_ms: int, count: int,
               lanes: Optional[str] = None) -> List[tuple]:
        """Re-deliver pending entries idle >= min_idle_ms that belong to
        OTHER consumers, transferring ownership to ``consumer`` (dead-
        consumer recovery; Redis XAUTOCLAIM analog). A consumer's own
        in-flight entries are never handed back to it — idle time is a
        lease, and you cannot steal your own lease. With ``lanes`` the
        claim drains lanes in the given order (a dead replica's
        interactive entries come back before its batch backlog) and each
        result is ``(id, lane, payload)``."""
        parts = ["XCLAIM", stream, group, consumer,
                 str(min_idle_ms), str(count)]
        if lanes:
            parts.append(lanes)
        lines = self._cmd(*parts)
        out: List[tuple] = []
        for ln in lines:
            if lanes:
                i, lane, payload = ln.split(" ", 2)
                out.append((int(i), lane, payload))
            else:
                i, payload = ln.split(" ", 1)
                out.append((int(i), payload))
        return out

    def xshed_set(self, stream: str, lane: str, shedding: bool) -> str:
        """Set/clear the shed flag on one lane: while set, XADDs to that
        lane are rejected with -SHED (absolute write — safe to repeat)."""
        return self._cmd("XSHED", stream, lane, "1" if shedding else "0")

    def xshed(self, stream: str) -> List[str]:
        """Names of lanes currently shedding on this stream."""
        return self._cmd("XSHED", stream)

    def xack(self, stream: str, group: str, entry_id: int) -> int:
        return self._cmd("XACK", stream, group, str(entry_id))

    def xpending(self, stream: str, group: str) -> int:
        return self._cmd("XPENDING", stream, group)

    def xpending_detail(self, stream: str, group: str) -> Dict[str, int]:
        """Per-consumer pending breakdown: consumer id -> count of
        delivered-but-unacked entries it currently owns (Redis
        ``XPENDING <key> <group>`` summary analog)."""
        out: Dict[str, int] = {}
        for ln in self._cmd("XPENDING", stream, group, "DETAIL"):
            consumer, n = ln.rsplit(" ", 1)
            out[consumer] = int(n)
        return out

    def hset(self, key: str, field: str, value_b64: str):
        return self._cmd("HSET", key, field, value_b64)

    def hget(self, key: str, field: str) -> Optional[str]:
        return self._cmd("HGET", key, field)

    def hkeys(self, key: str) -> List[str]:
        return self._cmd("HKEYS", key)

    def hdel(self, key: str, field: str) -> int:
        return self._cmd("HDEL", key, field)

    def delete(self, key: str):
        return self._cmd("DEL", key)

    def shutdown_broker(self):
        try:
            self._cmd("SHUTDOWN")
        except (ConnectionError, OSError):
            pass

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------- python impl
class _PyState:
    def __init__(self, hash_ttl_ms: int = 600_000):
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.streams: Dict[str, dict] = {}
        # stream -> set of lane names whose XADDs are being rejected
        # (admission control; set by the engine via XSHED)
        self.shed: Dict[str, set] = {}
        self.hashes: Dict[str, Dict[str, str]] = {}
        # last-write ms per hash field — uncollected results expire so the
        # broker's memory stays bounded (native zbroker.cpp does the same;
        # the reference relied on Redis EXPIRE for this)
        self.hash_times: Dict[str, Dict[str, float]] = {}
        self.hash_ttl_ms = int(hash_ttl_ms)

    def evict_expired(self, key: str):
        """Drop expired fields of one hash key. Caller holds the lock.
        Monotonic clock: TTL math must not jump with NTP steps."""
        if self.hash_ttl_ms <= 0:
            return
        now_ms = time.monotonic() * 1000
        times = self.hash_times.get(key)
        if not times:
            return
        h = self.hashes.get(key, {})
        for field in [f for f, t in times.items()
                      if now_ms - t >= self.hash_ttl_ms]:
            times.pop(field, None)
            h.pop(field, None)
        if not times:
            self.hash_times.pop(key, None)
        if not h:
            self.hashes.pop(key, None)

    def evict_some(self, key: str, limit: int = 8):
        """Amortized eviction for the HSET hot path: check only the
        oldest `limit` fields (dict order = write order, so the head of
        hash_times is the oldest). A full-key scan here would make every
        write O(live fields) exactly when the consumer is slow — the
        scenario TTL exists for; the periodic sweeper keeps the overall
        memory bound. Caller holds the lock."""
        if self.hash_ttl_ms <= 0:
            return
        times = self.hash_times.get(key)
        if not times:
            return
        now_ms = time.monotonic() * 1000
        h = self.hashes.get(key, {})
        expired = []
        for field, t in times.items():
            if len(expired) >= limit or now_ms - t < self.hash_ttl_ms:
                break  # ordered by write time: first live field ends it
            expired.append(field)
        for field in expired:
            times.pop(field, None)
            h.pop(field, None)
        if not times:
            self.hash_times.pop(key, None)
        if not h:
            self.hashes.pop(key, None)

    def field_expired(self, key: str, field: str) -> bool:
        """O(1) single-field expiry check (the HGET hot path must not scan
        the whole key). Deletes the field when expired. Caller holds the
        lock."""
        if self.hash_ttl_ms <= 0:
            return False
        t = self.hash_times.get(key, {}).get(field)
        if t is None or time.monotonic() * 1000 - t < self.hash_ttl_ms:
            return False
        self.hash_times.get(key, {}).pop(field, None)
        self.hashes.get(key, {}).pop(field, None)
        return True

    def sweep(self):
        """Evict every key's expired fields (periodic memory bound even
        when no client touches a key again)."""
        with self.lock:
            for key in list(self.hash_times):
                self.evict_expired(key)

    def stream(self, name):
        # entries: (id, payload, lane) — one id space across lanes so
        # lease/ack/GC semantics stay unified while delivery partitions
        return self.streams.setdefault(
            name, {"entries": [], "next_id": 1, "groups": {}})

    def group(self, st, name):
        # pending: entry id -> [owner consumer, last delivery ms, delivery
        # count, lane]. The owner+timestamp pair is the delivery lease
        # XCLAIM arbitrates on; the count makes redelivery observable; the
        # lane lets XCLAIM hand back high-priority entries first.
        # cursor: lane -> last-delivered id (per-lane so draining one lane
        # never marks another lane's entries as seen).
        return st["groups"].setdefault(name, {"cursor": {}, "pending": {}})


class _PyHandler(socketserver.StreamRequestHandler):
    def handle(self):
        state: _PyState = self.server.state  # type: ignore[attr-defined]
        while True:
            raw = self.rfile.readline()
            if not raw:
                return
            line = raw.decode().rstrip("\r\n")
            if not line:
                continue
            p = line.split(" ")
            cmd = p[0]
            w = self.wfile
            if cmd == "PING":
                w.write(b"+PONG\n")
            elif cmd == "SHUTDOWN":
                w.write(b"+BYE\n")
                threading.Thread(target=self.server.shutdown,
                                 daemon=True).start()
                return
            elif cmd == "XADD" and len(p) >= 3:
                lane = p[3] if len(p) >= 4 else DEFAULT_LANE
                shed = False
                with state.cv:
                    if lane in state.shed.get(p[1], ()):
                        shed = True
                    else:
                        st = state.stream(p[1])
                        eid = st["next_id"]
                        st["next_id"] += 1
                        st["entries"].append((eid, p[2], lane))
                        state.cv.notify_all()
                if shed:
                    w.write(f"-SHED lane {lane} is shedding\n".encode())
                else:
                    w.write(f"+{eid}\n".encode())
            elif cmd == "XLEN" and len(p) >= 2:
                with state.lock:
                    entries = state.stream(p[1])["entries"]
                    if len(p) >= 3:
                        n = sum(1 for e in entries if e[2] == p[2])
                    else:
                        n = len(entries)
                w.write(f":{n}\n".encode())
            elif cmd == "XREADGROUP" and len(p) >= 6:
                group, consumer, stream = p[1], p[2], p[3]
                count, block_ms = int(p[4]), int(p[5])
                # optional lanes arg: comma-separated delivery order —
                # all undelivered entries of lanes[0] go first, then
                # lanes[1], ... The laneless form delivers every lane in
                # id order (legacy parity).
                lanes = p[6].split(",") if len(p) >= 7 and p[6] else None

                def deliver():
                    st = state.stream(stream)
                    gr = state.group(st, group)
                    cur = gr["cursor"]
                    got = []
                    now_ms = int(time.monotonic() * 1000)
                    for want in (lanes if lanes is not None else [None]):
                        for eid, payload, elane in st["entries"]:
                            if want is not None and elane != want:
                                continue
                            if eid <= cur.get(elane, 0):
                                continue
                            got.append((eid, elane, payload))
                            cur[elane] = eid
                            gr["pending"][eid] = [consumer, now_ms, 1,
                                                  elane]
                            if len(got) >= count:
                                return got
                    return got
                with state.cv:
                    got = deliver()
                    if not got and block_ms > 0:
                        deadline = time.monotonic() + block_ms / 1000.0
                        while not got:
                            left = deadline - time.monotonic()
                            if left <= 0:
                                break
                            state.cv.wait(left)
                            got = deliver()
                out = [f"*{len(got)}\n"]
                if lanes is not None:
                    out += [f"{eid} {elane} {payload}\n"
                            for eid, elane, payload in got]
                else:
                    out += [f"{eid} {payload}\n"
                            for eid, _, payload in got]
                w.write("".join(out).encode())
            elif cmd == "XACK" and len(p) >= 4:
                with state.lock:
                    st = state.stream(p[1])
                    gr = state.group(st, p[2])
                    n = 1 if gr["pending"].pop(int(p[3]), None) is not None \
                        else 0
                    # GC entries delivered+acked by every group (see
                    # zbroker.cpp XACK). Cursors are per-lane, so an
                    # entry is collectible only when every group has
                    # passed it ON ITS LANE and nobody holds it pending;
                    # prefix-drop stops at the first keeper.
                    if st["groups"]:
                        drop = 0
                        entries = st["entries"]
                        while drop < len(entries):
                            eid, _, lane = entries[drop]
                            if any(g["cursor"].get(lane, 0) < eid
                                   or eid in g["pending"]
                                   for g in st["groups"].values()):
                                break
                            drop += 1
                        if drop:
                            st["entries"] = entries[drop:]
                w.write(f":{n}\n".encode())
            elif cmd == "XCLAIM" and len(p) >= 6:
                # XCLAIM <stream> <group> <consumer> <min_idle_ms> <count>:
                # re-deliver pending entries whose lease expired — idle
                # >= min_idle_ms AND owned by a DIFFERENT consumer (the
                # recovery path for entries a dead consumer never acked —
                # Redis XAUTOCLAIM analog). Claiming transfers ownership,
                # refreshes the lease clock and bumps the delivery count.
                # Optional trailing lanes arg: claim in that lane order
                # (a dead replica's interactive leases are recovered
                # before its batch backlog), replying with the lane field.
                claimer = p[3]
                min_idle, cnt = int(p[4]), int(p[5])
                lanes = p[6].split(",") if len(p) >= 7 and p[6] else None
                with state.lock:
                    st = state.stream(p[1])
                    gr = state.group(st, p[2])
                    now_ms = int(time.monotonic() * 1000)
                    eligible = sorted(
                        eid for eid, rec in gr["pending"].items()
                        if rec[0] != claimer and now_ms - rec[1] >= min_idle)
                    payloads = {eid: payload
                                for eid, payload, _ in st["entries"]}
                    got = []
                    for want in (lanes if lanes is not None else [None]):
                        for eid in eligible:
                            if len(got) >= cnt:
                                break
                            rec = gr["pending"][eid]
                            if rec[0] == claimer:
                                continue  # claimed earlier this sweep
                            elane = rec[3]
                            if want is not None and elane != want:
                                continue
                            if eid in payloads:
                                gr["pending"][eid] = [claimer, now_ms,
                                                      rec[2] + 1, elane]
                                got.append((eid, elane, payloads[eid]))
                        if len(got) >= cnt:
                            break
                out = [f"*{len(got)}\n"]
                if lanes is not None:
                    out += [f"{eid} {elane} {payload}\n"
                            for eid, elane, payload in got]
                else:
                    out += [f"{eid} {payload}\n" for eid, _, payload in got]
                w.write("".join(out).encode())
            elif cmd == "XPENDING" and len(p) >= 4:
                # XPENDING <stream> <group> DETAIL: per-consumer breakdown
                # (consumer id -> owned pending count), the fleet
                # supervisor's view of who is holding which leases
                with state.lock:
                    gr = state.group(state.stream(p[1]), p[2])
                    per: Dict[str, int] = {}
                    for rec in gr["pending"].values():
                        per[rec[0]] = per.get(rec[0], 0) + 1
                out = [f"*{len(per)}\n"]
                out += [f"{c} {n}\n" for c, n in sorted(per.items())]
                w.write("".join(out).encode())
            elif cmd == "XPENDING" and len(p) >= 3:
                with state.lock:
                    gr = state.group(state.stream(p[1]), p[2])
                    n = len(gr["pending"])
                w.write(f":{n}\n".encode())
            elif cmd == "XSHED" and len(p) >= 4:
                # XSHED <stream> <lane> <0|1>: set/clear a lane's shed
                # flag (admission control valve, written by the engine)
                with state.lock:
                    lanes_shed = state.shed.setdefault(p[1], set())
                    if p[3] == "0":
                        lanes_shed.discard(p[2])
                    else:
                        lanes_shed.add(p[2])
                w.write(b"+OK\n")
            elif cmd == "XSHED" and len(p) >= 2:
                # XSHED <stream>: query — multi-line list of shedding lanes
                with state.lock:
                    names = sorted(state.shed.get(p[1], ()))
                w.write(("".join([f"*{len(names)}\n"] +
                                 [ln + "\n" for ln in names])).encode())
            elif cmd == "HSET" and len(p) >= 4:
                with state.cv:
                    # bounded amortized cleanup (full scan would be O(live
                    # fields) per write under a slow consumer)
                    state.evict_some(p[1])
                    state.hashes.setdefault(p[1], {})[p[2]] = p[3]
                    if state.hash_ttl_ms > 0:
                        ht = state.hash_times.setdefault(p[1], {})
                        # move-to-end on rewrite: evict_some's head scan
                        # relies on dict order == write order, but a plain
                        # assignment keeps a rewritten key at its ORIGINAL
                        # position, where its fresh timestamp would block
                        # eviction of everything behind it forever
                        ht.pop(p[2], None)
                        ht[p[2]] = time.monotonic() * 1000
                    state.cv.notify_all()
                w.write(b"+OK\n")
            elif cmd == "HGET" and len(p) >= 3:
                with state.lock:
                    if state.field_expired(p[1], p[2]):
                        val = None
                    else:
                        val = state.hashes.get(p[1], {}).get(p[2])
                w.write(f"${val}\n".encode() if val is not None else b"$-1\n")
            elif cmd == "HKEYS" and len(p) >= 2:
                with state.lock:
                    state.evict_expired(p[1])
                    keys = list(state.hashes.get(p[1], {}).keys())
                w.write(("".join([f"*{len(keys)}\n"] +
                                 [k + "\n" for k in keys])).encode())
            elif cmd == "HDEL" and len(p) >= 3:
                with state.lock:
                    n = 1 if state.hashes.get(p[1], {}).pop(p[2], None) \
                        is not None else 0
                    state.hash_times.get(p[1], {}).pop(p[2], None)
                w.write(f":{n}\n".encode())
            elif cmd == "DEL" and len(p) >= 2:
                with state.lock:
                    state.streams.pop(p[1], None)
                    state.shed.pop(p[1], None)
                    state.hashes.pop(p[1], None)
                    state.hash_times.pop(p[1], None)
                w.write(b"+OK\n")
            else:
                w.write(b"-ERR unknown command\n")
            w.flush()


class _PyBrokerServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._conns = set()
        self._conns_lock = threading.Lock()

    def process_request(self, request, client_address):
        with self._conns_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conns_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def close_all_connections(self):
        """Sever live client sockets so clients observe the broker's death
        (the native broker gets this for free when its process exits)."""
        with self._conns_lock:
            for s in self._conns:
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
            self._conns.clear()


class Broker:
    """Owns a broker process (native) or thread (python fallback).

    ``Broker.launch()`` prefers the native binary; ``backend="python"``
    forces the in-process fallback (used by tests for both parities)."""

    def __init__(self, port: int, proc=None, server=None):
        self.port = port
        self._proc = proc
        self._server = server

    @property
    def backend(self) -> str:
        return "native" if self._proc is not None else "python"

    @classmethod
    def launch(cls, port: int = 0, backend: str = "auto",
               hash_ttl_ms: int = 600_000) -> "Broker":
        """``hash_ttl_ms``: result-hash fields a client never collects
        expire after this long, bounding broker memory (0 disables)."""
        if port == 0:
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
        if backend in ("auto", "native"):
            binary = build_native_broker()
            if binary is not None:
                proc = subprocess.Popen(
                    [binary, str(port), str(int(hash_ttl_ms))],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.DEVNULL, text=True)
                line = proc.stdout.readline()
                if line.startswith("READY"):
                    return cls(port, proc=proc)
                proc.kill()
            if backend == "native":
                raise RuntimeError("native broker unavailable")
        server = _PyBrokerServer(("127.0.0.1", port), _PyHandler)
        state = _PyState(hash_ttl_ms)
        server.state = state  # type: ignore[attr-defined]
        # serve_forever's default 0.5s poll makes every shutdown() wait
        # out the poll loop — a tax paid on each launch/stop cycle
        threading.Thread(target=server.serve_forever,
                         kwargs={"poll_interval": 0.02},
                         daemon=True).start()
        broker = cls(port, server=server)
        if hash_ttl_ms > 0:
            # periodic sweeper (the native broker's SweeperLoop analog):
            # abandoned keys expire even if never touched again
            stop = threading.Event()
            broker._sweep_stop = stop

            def sweeper():
                while not stop.wait(max(hash_ttl_ms / 4000.0, 0.05)):
                    state.sweep()

            threading.Thread(target=sweeper, daemon=True).start()
        return broker

    def client(self, timeout: float = 30.0) -> BrokerClient:
        return BrokerClient(port=self.port, timeout=timeout)

    def stop(self):
        if self._proc is not None:
            try:
                self.client(timeout=5.0).shutdown_broker()
                self._proc.wait(timeout=5)
            except Exception:
                self._proc.kill()
            self._proc = None
        if self._server is not None:
            if getattr(self, "_sweep_stop", None) is not None:
                self._sweep_stop.set()
            self._server.shutdown()
            self._server.close_all_connections()
            self._server.server_close()
            self._server = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
