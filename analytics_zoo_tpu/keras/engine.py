"""Keras-style graph engine on flax.

TPU-native rebuild of the zoo Keras API core (ref
``zoo/src/main/scala/com/intel/analytics/zoo/pipeline/api/keras/models/Topology.scala:67-609``
``KerasNet``/``Model``/``Sequential`` and the Python mirror
``pyzoo/zoo/pipeline/api/keras/engine/topology.py``): users compose layer
objects — ``Sequential().add(...)`` or the functional ``Input``/``Model``
graph — and the engine lowers the whole graph to ONE flax module, so the
entire model jits into a single XLA computation (no per-layer dispatch).

Weight sharing follows linen semantics: calling the same layer object on two
nodes reuses one flax submodule (ref KerasLayer sharing via node graphs).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import flax.linen as nn

_id_counter = itertools.count()
_name_counters: Dict[str, itertools.count] = {}


def fresh_name(prefix: str) -> str:
    c = _name_counters.setdefault(prefix, itertools.count(1))
    return f"{prefix}_{next(c)}"


class Node:
    """One tensor in the symbolic graph."""

    __slots__ = ("id", "layer", "inputs", "shape", "name")

    def __init__(self, layer: Optional["KerasLayer"], inputs: List["Node"],
                 shape: Optional[Tuple], name: str = ""):
        self.id = next(_id_counter)
        self.layer = layer
        self.inputs = inputs
        self.shape = shape  # without batch dim, may be None
        self.name = name

    # ---- autograd-style operator sugar (ref pyzoo/zoo/pipeline/api/autograd.py
    # Variable operators: +,-,*,/ on symbolic tensors) ----
    def __add__(self, other):
        from analytics_zoo_tpu.keras.layers import merge_op
        return merge_op("add")([self, _const(other, self)])

    __radd__ = __add__

    def __sub__(self, other):
        from analytics_zoo_tpu.keras.layers import merge_op
        return merge_op("sub")([self, _const(other, self)])

    def __rsub__(self, other):
        from analytics_zoo_tpu.keras.layers import merge_op
        return merge_op("sub")([_const(other, self), self])

    def __mul__(self, other):
        from analytics_zoo_tpu.keras.layers import merge_op
        return merge_op("mul")([self, _const(other, self)])

    __rmul__ = __mul__

    def __truediv__(self, other):
        from analytics_zoo_tpu.keras.layers import merge_op
        return merge_op("div")([self, _const(other, self)])

    def __rtruediv__(self, other):
        from analytics_zoo_tpu.keras.layers import merge_op
        return merge_op("div")([_const(other, self), self])

    def __neg__(self):
        return self * -1.0


def _const(v, like: Node) -> Node:
    if isinstance(v, Node):
        return v
    from analytics_zoo_tpu.keras.layers import Constant
    return Constant(v)([])


def Input(shape: Sequence[int], name: str = "") -> Node:
    """Symbolic input (ref pyzoo keras topology Input; shape excludes batch)."""
    return Node(None, [], tuple(shape), name or fresh_name("input"))


class KerasLayer:
    """Base layer: a config object that (a) can be called on Node(s) to build
    the graph, (b) knows how to run via flax inside the graph module."""

    def __init__(self, name: Optional[str] = None):
        self._auto_named = name is None
        self.name = name or fresh_name(type(self).__name__.lower())

    # -- graph building --
    def __call__(self, x: Union[Node, List[Node]]) -> Node:
        inputs = x if isinstance(x, list) else [x]
        for i in inputs:
            assert isinstance(i, Node), f"{self.name} called on non-Node {type(i)}"
        shape = self._infer_shape([i.shape for i in inputs])
        return Node(self, inputs, shape)

    def _infer_shape(self, in_shapes):
        return None

    # -- execution: override one of these --
    def make_module(self) -> Optional[nn.Module]:
        """Return a flax module if the layer has params/state, else None."""
        return None

    def apply(self, module: Optional[nn.Module], args: List[Any],
              train: bool):
        """Run the layer. ``module`` is the memoized flax submodule."""
        raise NotImplementedError


def topo_sort(outputs: List[Node]) -> List[Node]:
    seen: Dict[int, Node] = {}
    order: List[Node] = []

    def visit(node: Node):
        if node.id in seen:
            return
        seen[node.id] = node
        for i in node.inputs:
            visit(i)
        order.append(node)

    for o in outputs:
        visit(o)
    return order


class GraphModule(nn.Module):
    """The ONE flax module executing the whole Keras graph."""

    graph_inputs: Tuple[int, ...]      # node ids
    graph_outputs: Tuple[int, ...]
    order: Tuple[Node, ...]            # topo order (static pytree-aux data)

    @nn.compact
    def __call__(self, *xs, train: bool = False):
        assert len(xs) == len(self.graph_inputs), \
            f"model takes {len(self.graph_inputs)} inputs, got {len(xs)}"
        env: Dict[int, Any] = dict(zip(self.graph_inputs, xs))
        modules: Dict[str, Optional[nn.Module]] = {}
        for node in self.order:
            if node.id in env:
                continue
            layer = node.layer
            if layer.name not in modules:
                modules[layer.name] = layer.make_module()
            args = [env[i.id] for i in node.inputs]
            env[node.id] = layer.apply(modules[layer.name], args, train)
        outs = [env[i] for i in self.graph_outputs]
        return outs[0] if len(outs) == 1 else tuple(outs)
