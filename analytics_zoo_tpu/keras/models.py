"""Sequential / Model topologies with compile/fit/evaluate/predict.

Ref: ``zoo/.../pipeline/api/keras/models/Topology.scala:67-609`` (KerasNet:
``compile:139``, ``fit:347``, ``evaluate``, ``predict``, ``Model:631``,
``Sequential:854``) and the Python mirror
``pyzoo/zoo/pipeline/api/keras/models.py``. Training delegates to the
JaxEstimator engine — one jitted sharded train step instead of the
reference's InternalDistriOptimizer.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as np

from analytics_zoo_tpu.keras.engine import (GraphModule, Input, KerasLayer,
                                            Node, topo_sort)

import pickle as _pickle


def _activation_ids():
    from analytics_zoo_tpu.keras.layers import _ACTIVATIONS
    return {id(fn): name for name, fn in _ACTIVATIONS.items()}


class _TopologyPickler(_pickle.Pickler):
    """Reduces the two unpicklable callable kinds layers hold — registry
    activations (incl. module-level lambdas) and flax initializer closures
    — to symbolic persistent ids; everything else pickles normally."""

    _MISSING = object()

    def persistent_id(self, obj):
        if callable(obj) and not isinstance(obj, type):
            name = _activation_ids().get(id(obj), self._MISSING)
            if name is not self._MISSING:
                return ("activation", name)
            mod = getattr(obj, "__module__", "") or ""
            if "initializers" in mod:
                # only used to INIT params; load_weights overwrites them,
                # so a canonical default loses nothing after restore
                return ("initializer", None)
        return None


class _TopologyUnpickler(_pickle.Unpickler):
    def persistent_load(self, pid):
        kind, name = pid
        if kind == "activation":
            from analytics_zoo_tpu.keras.layers import get_activation
            return get_activation(name)
        if kind == "initializer":
            import flax.linen as nn
            return nn.initializers.glorot_uniform()
        raise _pickle.UnpicklingError(f"unknown persistent id {pid!r}")


class KerasNet:
    """Shared compile/fit surface (ref Topology.scala KerasNet)."""

    def __init__(self):
        self._estimator = None
        self._compile_args = None
        self._strategy = "dp"
        self._param_rules = None
        self.model_dir = None

    # -- to be provided by subclass --
    def _graph(self) -> Tuple[List[Node], List[Node]]:
        raise NotImplementedError

    def input_shapes(self) -> List[Tuple]:
        inputs, _ = self._graph()
        shapes = [n.shape for n in inputs]
        assert all(s is not None for s in shapes), \
            "input shapes unknown; give input_shape to the first layer or use Input()"
        return shapes

    def to_flax(self) -> GraphModule:
        inputs, outputs = self._graph()
        order = tuple(topo_sort(outputs))
        self._canonicalize_names(order)
        return GraphModule(graph_inputs=tuple(n.id for n in inputs),
                           graph_outputs=tuple(n.id for n in outputs),
                           order=order)

    @staticmethod
    def _canonicalize_names(order):
        """Auto-generated layer names are rewritten to a deterministic
        per-model scheme (type_index in topo order) so two builds of the same
        architecture produce identical parameter trees — required for
        checkpoint/save_model round-trips across processes. Canonical names
        never collide with user-chosen names (the graph executor memoizes
        flax submodules by name, so a collision would silently run the wrong
        layer), and duplicate user names are rejected."""
        layers, user_names = [], set()
        seen: set = set()
        for node in order:
            layer = node.layer
            if layer is None or id(layer) in seen:
                continue
            seen.add(id(layer))
            layers.append(layer)
            if not getattr(layer, "_auto_named", False):
                if layer.name in user_names:
                    raise ValueError(
                        f"duplicate layer name {layer.name!r}; layer names "
                        "must be unique within a model")
                user_names.add(layer.name)
        counters: dict = {}
        for layer in layers:
            if getattr(layer, "_auto_named", False):
                prefix = type(layer).__name__.lower()
                while True:
                    counters[prefix] = counters.get(prefix, 0) + 1
                    cand = f"{prefix}_{counters[prefix]}"
                    if cand not in user_names:
                        break
                layer.name = cand

    def sample_input(self, batch: int = 2):
        shapes = self.input_shapes()
        arrs = tuple(np.zeros((batch,) + tuple(s), np.float32) for s in shapes)
        return arrs[0] if len(arrs) == 1 else arrs

    # -- reference API --
    def set_strategy(self, strategy: str, param_rules=None):
        """TPU extension: parallelism for this model ("dp", "dp2,tp4"...).

        ``param_rules=None`` keeps any previously set rules. Existing
        parameters (loaded weights, training progress) survive the change —
        the rebuilt estimator re-shards them under the new layout."""
        self._strategy = strategy
        if param_rules is not None:
            self._param_rules = param_rules
        self._stash_adapter()
        self._estimator = None
        return self

    def compile(self, optimizer, loss, metrics: Optional[List] = None):
        """(ref Topology.scala compile:139). Compiling after weights were
        loaded (or after a placeholder inference estimator was built) keeps
        the existing parameters."""
        self._compile_args = dict(optimizer=optimizer, loss=loss,
                                  metrics=metrics)
        self._stash_adapter()
        self._estimator = None
        return self

    def _stash_adapter(self):
        """Keep current weights across an estimator rebuild. The latest
        parameters live in the estimator STATE (the adapter's originals may
        be donated/deleted buffers after the first train step), so sync
        them back host-side before handing the adapter over."""
        est = self._estimator
        if est is None:
            return
        if est._state is not None:
            import jax
            est.adapter.params = jax.device_get(est._state["params"])
            est.adapter.model_state = jax.device_get(
                est._state["model_state"])
        self._reuse_adapter = est.adapter

    def set_tensorboard(self, log_dir: str, app_name: str):
        self._ensure_estimator().set_tensorboard(log_dir, app_name)

    def set_checkpoint(self, path: str):
        self._ensure_estimator().model_dir = path

    def set_constant_gradient_clipping(self, min_value, max_value):
        self._ensure_estimator().set_constant_gradient_clipping(min_value, max_value)

    def set_gradient_clipping_by_l2_norm(self, clip_norm):
        self._ensure_estimator().set_l2_norm_gradient_clipping(clip_norm)

    def _ensure_estimator(self, for_training: bool = False):
        if self._estimator is None:
            args = self._compile_args
            if args is None:
                # inference/weights-only use (predict, load_weights) is legal
                # before compile (ref KerasNet.predict works uncompiled)
                assert not for_training, \
                    "call compile(optimizer, loss) before fit/evaluate"
                args = dict(optimizer="adam", loss="mse", metrics=None)
            from analytics_zoo_tpu.learn.estimator import Estimator
            module = self.to_flax()  # canonicalizes layer names first
            self._estimator = Estimator.from_flax(
                model=module,
                loss=args["loss"],
                optimizer=args["optimizer"],
                metrics=args["metrics"],
                sample_input=self.sample_input(),
                model_dir=self.model_dir,
                strategy=self._strategy,
                param_rules=self._param_rules,
                param_penalty=self._param_penalty_fn(module.order))
            reuse = getattr(self, "_reuse_adapter", None)
            if reuse is not None:
                self._estimator.adapter.params = reuse.params
                self._estimator.adapter.model_state = reuse.model_state
                self._reuse_adapter = None
        return self._estimator

    def _param_penalty_fn(self, order):
        """Assemble the layers' W/b regularizers into one pure
        ``params → scalar`` penalty for the train step (ref BigDL applies
        w/bRegularizer inside the optimizer; here XLA fuses the penalty
        into the backward pass). ``order`` is the already-computed,
        name-canonicalized topo order from ``to_flax``. Returns None when
        no layer regularizes."""
        regs, seen = [], set()
        for node in order:
            layer = node.layer
            if layer is None or id(layer) in seen:
                continue
            seen.add(id(layer))
            if getattr(layer, "param_regularizers", None):
                regs.append(layer)
        if not regs:
            return None
        pairs = [(layer.name, layer) for layer in regs]

        def penalty(params):
            total = 0.0
            for name, layer in pairs:
                if name in params:
                    total += layer.penalty(params[name])
            return total

        return penalty

    @property
    def estimator(self):
        return self._ensure_estimator()

    @staticmethod
    def _as_x(x):
        return tuple(x) if isinstance(x, (list, tuple)) else x

    def fit(self, x, y=None, batch_size: int = 32, nb_epoch: int = 1,
            validation_data=None, distributed: bool = True, shuffle=True,
            feature_cols=None, label_cols=None, **kwargs):
        """(ref Topology.scala fit:347; py keras fit(x, y, batch_size,
        nb_epoch, validation_data))"""
        est = self._ensure_estimator(for_training=True)
        data = self._as_x(x) if y is None else (self._as_x(x), y)
        if validation_data is not None and isinstance(validation_data, tuple) \
                and len(validation_data) == 2:
            validation_data = (self._as_x(validation_data[0]), validation_data[1])
        return est.fit(data, epochs=nb_epoch, batch_size=batch_size,
                       validation_data=validation_data, shuffle=shuffle,
                       feature_cols=feature_cols, label_cols=label_cols,
                       **kwargs)

    def evaluate(self, x, y=None, batch_size: int = 32, **kwargs):
        est = self._ensure_estimator(for_training=True)
        data = self._as_x(x) if y is None else (self._as_x(x), y)
        return est.evaluate(data, batch_size=batch_size, **kwargs)

    def predict(self, x, batch_size: int = 256, distributed: bool = True):
        return self._ensure_estimator().predict(self._as_x(x),
                                                batch_size=batch_size)

    def predict_classes(self, x, batch_size: int = 256,
                        zero_based_label: bool = True):
        """(ref pyzoo keras predict_classes)"""
        probs = self.predict(x, batch_size=batch_size)
        classes = np.argmax(np.asarray(probs), axis=-1)
        return classes if zero_based_label else classes + 1

    # -- persistence --
    def save_weights(self, path: str):
        self._ensure_estimator().save(path)

    def load_weights(self, path: str):
        self._ensure_estimator().load(path)

    def save(self, path: str):
        """Full model save: pickled topology (the layer/Node graph — layer
        objects are plain config holders) + weights checkpoint
        (ref Topology.scala saveModule: architecture + weights in one
        artifact). Activation/initializer callables are reduced to registry
        names; ``Lambda`` layers with unpicklable closures are the one
        documented exception — use named functions there."""
        import os
        import pickle

        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "topology.pkl"), "wb") as fh:
            _TopologyPickler(fh, protocol=pickle.HIGHEST_PROTOCOL).dump(self)
        self.save_weights(os.path.join(path, "weights"))
        return path

    @staticmethod
    def load(path: str) -> "KerasNet":
        """(ref Net.load for keras models)"""
        import os

        with open(os.path.join(path, "topology.pkl"), "rb") as fh:
            model = _TopologyUnpickler(fh).load()
        model.load_weights(os.path.join(path, "weights"))
        return model

    def __getstate__(self):
        # topology + compile/strategy config only: the estimator (device
        # arrays, jitted callables, writers) rebuilds lazily on load
        state = dict(self.__dict__)
        state["_estimator"] = None
        return state

    def get_weights(self):
        return self._ensure_estimator().get_model()

    # -- introspection --
    def summary(self):
        """(ref Topology.scala summary / KerasNet.summary)"""
        import jax
        module = self.to_flax()
        sample = self.sample_input()
        args = sample if isinstance(sample, tuple) else (sample,)
        shapes = jax.eval_shape(
            lambda *a: module.init(jax.random.PRNGKey(0), *a), *args)
        total = 0
        lines = ["_" * 64]
        lines.append(f"{'Layer (type)':<34}{'Param #':>12}")
        lines.append("=" * 64)
        params = shapes.get("params", {}) if isinstance(shapes, dict) else {}
        for name, tree in params.items():
            n = sum(int(np.prod(l.shape))
                    for l in jax.tree_util.tree_leaves(tree))
            total += n
            lines.append(f"{name:<34}{n:>12,}")
        lines.append("=" * 64)
        lines.append(f"Total params: {total:,}")
        text = "\n".join(lines)
        print(text)
        return text


class Sequential(KerasNet):
    """(ref Topology.scala Sequential:854; py Sequential().add(...))"""

    def __init__(self):
        super().__init__()
        self.layers: List[KerasLayer] = []
        self._built: Optional[Tuple[List[Node], List[Node]]] = None

    def add(self, layer: KerasLayer) -> "Sequential":
        assert isinstance(layer, (KerasLayer, KerasNet)), \
            f"cannot add {type(layer)}"
        self.layers.append(layer)
        self._built = None
        self._estimator = None
        return self

    def _graph(self):
        if self._built is None:
            assert self.layers, "empty Sequential"
            first = self.layers[0]
            in_shape = getattr(first, "input_shape", None)
            assert in_shape is not None, \
                "first layer of a Sequential needs input_shape=..."
            node = Input(shape=in_shape)
            inputs = [node]
            for layer in self.layers:
                if isinstance(layer, KerasNet):  # nested model
                    sub_in, sub_out = layer._graph()
                    raise NotImplementedError(
                        "nesting models inside Sequential is not supported yet")
                node = layer(node)
            self._built = (inputs, [node])
        return self._built


class Model(KerasNet):
    """Functional graph model (ref Topology.scala Model:631;
    py Model(input=..., output=...))."""

    def __init__(self, input, output, **kwargs):
        super().__init__()
        self._inputs = input if isinstance(input, (list, tuple)) else [input]
        self._outputs = output if isinstance(output, (list, tuple)) else [output]
        for n in list(self._inputs) + list(self._outputs):
            assert isinstance(n, Node), "Model(input=, output=) takes Input()/layer nodes"

    def _graph(self):
        return list(self._inputs), list(self._outputs)
