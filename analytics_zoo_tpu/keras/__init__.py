from analytics_zoo_tpu.keras import layers  # noqa: F401
from analytics_zoo_tpu.keras import regularizers  # noqa: F401
from analytics_zoo_tpu.keras.engine import Input  # noqa: F401
from analytics_zoo_tpu.keras.models import Sequential, Model  # noqa: F401
from analytics_zoo_tpu.keras import policy  # noqa: F401
