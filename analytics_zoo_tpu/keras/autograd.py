"""Autograd — symbolic tensor math for custom layers and losses.

Parity with the reference's autograd surface
(pyzoo/zoo/pipeline/api/autograd.py:32-568: module-level math functions,
``Variable:369`` operator overloads, ``Lambda:393``, ``CustomLoss``; Scala
lowering in zoo/.../pipeline/api/autograd/math.scala). There every
expression becomes a BigDL layer graph; here every expression is a
``keras.engine.Node`` whose op is a param-free jax lambda — the same graph
machinery the Keras API compiles, so autograd expressions mix freely with
zoo layers and everything fuses under jit.

Usage (matches ref examples, e.g. KNRM's custom loss / variable math):

    from analytics_zoo_tpu.keras import autograd as A
    v = A.Variable(input_shape=(3,))
    out = A.mean(A.abs(v1 - v2), axis=1)
    loss = CustomLoss(lambda yt, yp: A.mean(A.square(yt - yp)), (3,))
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple, Union

import numpy as np

from analytics_zoo_tpu.keras.engine import (
    Input, KerasLayer, Node, topo_sort,
)


class LambdaLayer(KerasLayer):
    """A param-free op node: applies ``fn(*jax_arrays)``
    (ref autograd.Lambda:393 / LambdaLayer). ``out_shape``: shape without
    batch dim, or a callable of the input shapes."""

    def __init__(self, fn: Callable, out_shape=None, name=None):
        super().__init__(name)
        self.fn = fn
        self.out_shape = out_shape

    def _infer_shape(self, in_shapes):
        if callable(self.out_shape):
            return self.out_shape(in_shapes)
        if self.out_shape is not None:
            return tuple(self.out_shape)
        return in_shapes[0]

    def apply(self, module, args, train):
        return self.fn(*args)


# public alias matching the reference spelling
Lambda = LambdaLayer


def Variable(input_shape: Sequence[int], name: str = "") -> Node:
    """A symbolic tensor (ref autograd.Variable:369; batch dim excluded)."""
    return Input(shape=input_shape, name=name)


def _unary(fname: str, jfn, shape=None):
    def op(x: Node, **kw) -> Node:
        fn = (lambda a: jfn(a, **kw)) if kw else jfn
        return LambdaLayer(fn, out_shape=shape, name=None)(x)
    op.__name__ = fname
    return op


def _import_jnp():
    import jax.numpy as jnp
    return jnp


# ---- elementwise unary (ref autograd.py abs/exp/log/sqrt/square/...) ----
def abs(x: Node) -> Node:  # noqa: A001 — reference API name
    return LambdaLayer(lambda a: _import_jnp().abs(a))(x)


def exp(x: Node) -> Node:
    return LambdaLayer(lambda a: _import_jnp().exp(a))(x)


def log(x: Node) -> Node:
    return LambdaLayer(lambda a: _import_jnp().log(a))(x)


def sqrt(x: Node) -> Node:
    return LambdaLayer(lambda a: _import_jnp().sqrt(a))(x)


def square(x: Node) -> Node:
    return LambdaLayer(lambda a: _import_jnp().square(a))(x)


def neg(x: Node) -> Node:
    return LambdaLayer(lambda a: -a)(x)


def softsign(x: Node) -> Node:
    return LambdaLayer(lambda a: a / (1 + _import_jnp().abs(a)))(x)


def softplus(x: Node) -> Node:
    def f(a):
        import jax
        return jax.nn.softplus(a)
    return LambdaLayer(f)(x)


def clip(x: Node, min: float, max: float) -> Node:  # noqa: A002
    return LambdaLayer(
        lambda a: _import_jnp().clip(a, min, max))(x)


def pow(x: Node, a: float) -> Node:  # noqa: A001
    return LambdaLayer(lambda v: v ** a)(x)


def epsilon() -> float:
    return 1e-7


# ---- axis reductions (axis counts the batch dim, as in the reference) ----
def _reduce_shape(axis, keepdims):
    def infer(in_shapes):
        s = in_shapes[0]
        if s is None:
            return None
        full = (None,) + tuple(s)  # batch-dim placeholder
        ax = axis % len(full) if axis is not None else None
        if ax is None:
            return ()
        out = [d for i, d in enumerate(full) if i != ax or keepdims]
        if keepdims:
            out[ax] = 1
        return tuple(out[1:])
    return infer


def mean(x: Node, axis: int = None, keepDims: bool = False) -> Node:
    return LambdaLayer(
        lambda a: _import_jnp().mean(a, axis=axis, keepdims=keepDims),
        out_shape=_reduce_shape(axis, keepDims))(x)


def sum(x: Node, axis: int = None, keepDims: bool = False) -> Node:  # noqa: A001
    return LambdaLayer(
        lambda a: _import_jnp().sum(a, axis=axis, keepdims=keepDims),
        out_shape=_reduce_shape(axis, keepDims))(x)


def max(x: Node, axis: int = None, keepDims: bool = False) -> Node:  # noqa: A001
    return LambdaLayer(
        lambda a: _import_jnp().max(a, axis=axis, keepdims=keepDims),
        out_shape=_reduce_shape(axis, keepDims))(x)


def min(x: Node, axis: int = None, keepDims: bool = False) -> Node:  # noqa: A001
    return LambdaLayer(
        lambda a: _import_jnp().min(a, axis=axis, keepdims=keepDims),
        out_shape=_reduce_shape(axis, keepDims))(x)


# ---- binary ----
def maximum(x: Node, y: Union[Node, float]) -> Node:
    if isinstance(y, Node):
        return LambdaLayer(lambda a, b: _import_jnp().maximum(a, b))([x, y])
    return LambdaLayer(lambda a: _import_jnp().maximum(a, y))(x)


def minimum(x: Node, y: Union[Node, float]) -> Node:
    if isinstance(y, Node):
        return LambdaLayer(lambda a, b: _import_jnp().minimum(a, b))([x, y])
    return LambdaLayer(lambda a: _import_jnp().minimum(a, y))(x)


def batch_dot(x: Node, y: Node, axes: Tuple[int, int] = (2, 1)) -> Node:
    """Per-sample matmul (ref autograd.batch_dot; axes as in keras-1)."""
    def f(a, b):
        jnp = _import_jnp()
        # keras batch_dot with default axes == batched matmul
        if axes == (2, 1):
            return jnp.einsum("bij,bjk->bik", a, b)
        if axes == (1, 1):
            return jnp.einsum("bi,bi->b", a, b)[:, None]
        if axes == (2, 2):
            return jnp.einsum("bij,bkj->bik", a, b)
        raise ValueError(f"unsupported batch_dot axes {axes}")
    return LambdaLayer(f)([x, y])


def dot(x: Node, y: Node) -> Node:
    return LambdaLayer(lambda a, b: a @ b)([x, y])


def l2_normalize(x: Node, axis: int = -1) -> Node:
    def f(a):
        jnp = _import_jnp()
        return a / jnp.maximum(
            jnp.linalg.norm(a, axis=axis, keepdims=True), 1e-12)
    return LambdaLayer(f)(x)


# ---- shape ops ----
def expand_dims(x: Node, axis: int) -> Node:
    return LambdaLayer(
        lambda a: _import_jnp().expand_dims(a, axis))(x)


def squeeze(x: Node, axis: int) -> Node:
    return LambdaLayer(lambda a: _import_jnp().squeeze(a, axis))(x)


def stack(nodes: List[Node], axis: int = 1) -> Node:
    return LambdaLayer(
        lambda *xs: _import_jnp().stack(xs, axis=axis))(list(nodes))


def concatenate(nodes: List[Node], axis: int = -1) -> Node:
    return LambdaLayer(
        lambda *xs: _import_jnp().concatenate(xs, axis=axis))(list(nodes))


def contiguous(x: Node) -> Node:
    return x


# ------------------------------------------------------------- evaluation
def to_function(inputs: List[Node], output: Node) -> Callable:
    """Compile a param-free autograd graph into a plain jax function
    ``fn(*arrays)``. Raises if the graph contains parameterized layers
    (those need the full Keras compile path)."""
    order = topo_sort([output])
    for node in order:
        if node.layer is not None and node.layer.make_module() is not None:
            raise ValueError(
                f"graph contains parameterized layer {node.layer.name!r}; "
                "use the Keras Model API instead of to_function")
    input_ids = [n.id for n in inputs]

    def fn(*xs):
        env = dict(zip(input_ids, xs))
        for node in order:
            if node.id in env:
                continue
            if node.layer is None:
                raise ValueError(
                    "graph references an Input that was not passed in")
            env[node.id] = node.layer.apply(
                None, [env[i.id] for i in node.inputs], False)
        return env[output.id]

    return fn


class CustomLoss:
    """A loss defined as an autograd expression over (y_true, y_pred)
    (ref autograd.CustomLoss / CustomLossWithVariable). Usable anywhere a
    loss is accepted: ``model.compile(loss=CustomLoss(fn, y_shape))``."""

    def __init__(self, loss_func: Callable[[Node, Node], Node],
                 y_shape: Sequence[int]):
        y_true = Variable(input_shape=tuple(y_shape), name="y_true")
        y_pred = Variable(input_shape=tuple(y_shape), name="y_pred")
        out = loss_func(y_true, y_pred)
        self._fn = to_function([y_true, y_pred], out)

    def __call__(self, y_true, y_pred):
        return self._fn(y_true, y_pred)

    # reference spelling: loss.forward(y_true, y_pred) for spot-checking
    def forward(self, y_true, y_pred):
        import jax
        return np.asarray(jax.device_get(
            self._fn(np.asarray(y_true), np.asarray(y_pred))))
