"""zoo-Keras layer library on flax/XLA.

Rebuild of the reference's Keras-1-style layer surface
(ref ``zoo/src/main/scala/com/intel/analytics/zoo/pipeline/api/keras/layers/``
~120 layer files and the Python mirror
``pyzoo/zoo/pipeline/api/keras/layers/``). Layers are config objects
(``KerasLayer``); execution happens inside one fused ``GraphModule``
(engine.py). Channels-last layout throughout (the TPU-friendly layout — the
reference's "th"/"tf" dim_ordering split collapses to "tf").
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.keras.engine import KerasLayer as _KerasLayerBase
from analytics_zoo_tpu.keras.engine import Node, fresh_name


class KerasLayer(_KerasLayerBase):
    """Layer base that records ``input_shape`` (used when a layer opens a
    Sequential, ref pyzoo keras layers' input_shape kwarg)."""

    def __init__(self, name=None, input_shape=None):
        super().__init__(name)
        self.input_shape = tuple(input_shape) if input_shape is not None else None

# ---------------- activations ----------------

_ACTIVATIONS = {
    "relu": nn.relu, "sigmoid": nn.sigmoid, "tanh": jnp.tanh,
    "softmax": nn.softmax, "log_softmax": nn.log_softmax,
    "softplus": nn.softplus, "softsign": nn.soft_sign, "gelu": nn.gelu,
    "elu": nn.elu, "selu": nn.selu, "swish": nn.swish, "silu": nn.silu,
    "leaky_relu": nn.leaky_relu, "relu6": lambda x: jnp.clip(x, 0, 6),
    "hard_sigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
    "linear": lambda x: x, "identity": lambda x: x, None: lambda x: x,
}


def get_activation(act):
    if callable(act):
        return act
    if act in _ACTIVATIONS:
        return _ACTIVATIONS[act]
    raise ValueError(f"unknown activation {act!r}")


# ---------------- init helpers (ref keras init strings) ----------------

def get_init(init: str):
    table = {
        "glorot_uniform": nn.initializers.glorot_uniform(),
        "glorot_normal": nn.initializers.glorot_normal(),
        "he_normal": nn.initializers.he_normal(),
        "he_uniform": nn.initializers.he_uniform(),
        "lecun_normal": nn.initializers.lecun_normal(),
        "normal": nn.initializers.normal(0.05),
        "uniform": nn.initializers.uniform(0.05),
        "zero": nn.initializers.zeros, "zeros": nn.initializers.zeros,
        "one": nn.initializers.ones, "ones": nn.initializers.ones,
    }
    if callable(init):
        return init
    if init in table:
        return table[init]
    raise ValueError(f"unknown init {init!r}")


# ---------------- core layers ----------------

class Dense(KerasLayer):
    """(ref keras/layers/core.py Dense / Scala Dense.scala)"""

    def __init__(self, output_dim: int, activation=None, init="glorot_uniform",
                 bias: bool = True, W_regularizer=None, b_regularizer=None,
                 input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.output_dim = output_dim
        self.activation = get_activation(activation)
        self.init = get_init(init)
        self.bias = bias
        self.input_shape = input_shape

    def make_module(self):
        return nn.Dense(self.output_dim, use_bias=self.bias,
                        kernel_init=self.init, name=self.name)

    def apply(self, module, args, train):
        return self.activation(module(args[0]))

    def _infer_shape(self, in_shapes):
        s = in_shapes[0]
        return (s[:-1] + (self.output_dim,)) if s else None


class Activation(KerasLayer):
    def __init__(self, activation, input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.fn = get_activation(activation)

    def apply(self, module, args, train):
        return self.fn(args[0])

    def _infer_shape(self, in_shapes):
        return in_shapes[0]


class Dropout(KerasLayer):
    def __init__(self, p: float, input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.p = p

    def make_module(self):
        return nn.Dropout(rate=self.p, name=self.name)

    def apply(self, module, args, train):
        return module(args[0], deterministic=not train)

    def _infer_shape(self, in_shapes):
        return in_shapes[0]


class Flatten(KerasLayer):
    def apply(self, module, args, train):
        x = args[0]
        return x.reshape(x.shape[0], -1)

    def _infer_shape(self, in_shapes):
        s = in_shapes[0]
        return (int(np.prod(s)),) if s else None


class Reshape(KerasLayer):
    def __init__(self, target_shape, input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.target_shape = tuple(target_shape)

    def apply(self, module, args, train):
        x = args[0]
        return x.reshape((x.shape[0],) + self.target_shape)

    def _infer_shape(self, in_shapes):
        return self.target_shape


class Permute(KerasLayer):
    def __init__(self, dims, input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.dims = tuple(dims)  # 1-based over non-batch dims (keras conv.)

    def apply(self, module, args, train):
        return jnp.transpose(args[0], (0,) + self.dims)


class RepeatVector(KerasLayer):
    def __init__(self, n: int, input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.n = n

    def apply(self, module, args, train):
        return jnp.repeat(args[0][:, None, :], self.n, axis=1)

    def _infer_shape(self, in_shapes):
        s = in_shapes[0]
        return (self.n,) + tuple(s) if s else None


class Squeeze(KerasLayer):
    def __init__(self, dim: int, input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.dim = dim

    def apply(self, module, args, train):
        return jnp.squeeze(args[0], axis=self.dim)


class ExpandDim(KerasLayer):
    def __init__(self, dim: int, input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.dim = dim

    def apply(self, module, args, train):
        return jnp.expand_dims(args[0], axis=self.dim)


class Select(KerasLayer):
    """Select one index along a dim (ref Scala Select.scala)."""

    def __init__(self, dim: int, index: int, input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.dim, self.index = dim, index

    def apply(self, module, args, train):
        return jnp.take(args[0], self.index, axis=self.dim)


class Narrow(KerasLayer):
    """Slice length elements from offset along dim (ref Narrow.scala)."""

    def __init__(self, dim: int, offset: int, length: int = 1,
                 input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.dim, self.offset, self.length = dim, offset, length

    def apply(self, module, args, train):
        return jax.lax.slice_in_dim(args[0], self.offset,
                                    self.offset + self.length, axis=self.dim)


class Lambda(KerasLayer):
    """Wrap an arbitrary jax function (ref autograd.py Lambda:393)."""

    def __init__(self, function: Callable, input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.function = function

    def apply(self, module, args, train):
        return self.function(*args)


class Constant(KerasLayer):
    def __init__(self, value, name=None):
        super().__init__(name)
        self.value = value

    def apply(self, module, args, train):
        return jnp.asarray(self.value)


class Masking(KerasLayer):
    def __init__(self, mask_value: float = 0.0, input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.mask_value = mask_value

    def apply(self, module, args, train):
        x = args[0]
        keep = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return x * keep


# ---------------- embeddings ----------------

class Embedding(KerasLayer):
    """(ref keras/layers/embeddings.py; Scala Embedding.scala). On TPU the
    lookup lowers to a one-hot matmul/gather on the MXU; the table can be
    model-parallel via param_rules matching 'embedding'."""

    def __init__(self, input_dim: int, output_dim: int, init="uniform",
                 input_length=None, input_shape=None, name=None,
                 zero_based_id: bool = True):
        super().__init__(name, input_shape)
        self.input_dim, self.output_dim = input_dim, output_dim
        self.init = get_init(init)
        self.zero_based_id = zero_based_id

    def make_module(self):
        return nn.Embed(self.input_dim, self.output_dim,
                        embedding_init=self.init, name=self.name)

    def apply(self, module, args, train):
        ids = args[0].astype(jnp.int32)
        if not self.zero_based_id:
            ids = ids - 1  # ref WordEmbedding 1-based vocab ids
        return module(ids)

    def _infer_shape(self, in_shapes):
        s = in_shapes[0]
        return tuple(s) + (self.output_dim,) if s is not None else None


# ---------------- normalization ----------------

class BatchNormalization(KerasLayer):
    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99,
                 input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.epsilon, self.momentum = epsilon, momentum

    def make_module(self):
        return nn.BatchNorm(use_running_average=None, momentum=self.momentum,
                            epsilon=self.epsilon, name=self.name,
                            axis_name=None)

    def apply(self, module, args, train):
        return module(args[0], use_running_average=not train)


class LayerNormalization(KerasLayer):
    def __init__(self, epsilon: float = 1e-6, input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.epsilon = epsilon

    def make_module(self):
        return nn.LayerNorm(epsilon=self.epsilon, name=self.name)

    def apply(self, module, args, train):
        return module(args[0])


# ---------------- convolutions / pooling ----------------

def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


class Conv1D(KerasLayer):
    """(ref Convolution1D) input [batch, steps, channels]."""

    def __init__(self, nb_filter: int, filter_length: int, activation=None,
                 border_mode: str = "valid", subsample_length: int = 1,
                 init="glorot_uniform", bias: bool = True, dilation_rate: int = 1,
                 input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.nb_filter, self.filter_length = nb_filter, filter_length
        self.activation = get_activation(activation)
        self.padding = border_mode.upper()
        self.stride = subsample_length
        self.init = get_init(init)
        self.bias = bias
        self.dilation = dilation_rate

    def make_module(self):
        return nn.Conv(self.nb_filter, (self.filter_length,),
                       strides=(self.stride,), padding=self.padding,
                       kernel_dilation=(self.dilation,), use_bias=self.bias,
                       kernel_init=self.init, name=self.name)

    def apply(self, module, args, train):
        return self.activation(module(args[0]))


Convolution1D = Conv1D


class Conv2D(KerasLayer):
    """(ref Convolution2D) input [batch, h, w, channels] (channels-last)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, border_mode: str = "valid",
                 subsample=(1, 1), init="glorot_uniform", bias: bool = True,
                 input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.nb_filter = nb_filter
        self.kernel = (nb_row, nb_col)
        self.activation = get_activation(activation)
        self.padding = border_mode.upper()
        self.strides = _pair(subsample)
        self.init = get_init(init)
        self.bias = bias

    def make_module(self):
        return nn.Conv(self.nb_filter, self.kernel, strides=self.strides,
                       padding=self.padding, use_bias=self.bias,
                       kernel_init=self.init, name=self.name)

    def apply(self, module, args, train):
        return self.activation(module(args[0]))


Convolution2D = Conv2D


class SeparableConv2D(KerasLayer):
    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, border_mode="valid", subsample=(1, 1),
                 input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.nb_filter, self.kernel = nb_filter, (nb_row, nb_col)
        self.activation = get_activation(activation)
        self.padding = border_mode.upper()
        self.strides = _pair(subsample)

    def make_module(self):
        # depthwise (feature_group_count) + pointwise
        class _Sep(nn.Module):
            nb_filter: int
            kernel: tuple
            strides: tuple
            padding: str

            @nn.compact
            def __call__(self, x):
                c = x.shape[-1]
                x = nn.Conv(c, self.kernel, strides=self.strides,
                            padding=self.padding, feature_group_count=c,
                            name="depthwise")(x)
                return nn.Conv(self.nb_filter, (1, 1), name="pointwise")(x)

        return _Sep(self.nb_filter, self.kernel, self.strides, self.padding,
                    name=self.name)

    def apply(self, module, args, train):
        return self.activation(module(args[0]))


class _Pool(KerasLayer):
    reducer = None
    init_val = None

    def __init__(self, pool_size, strides=None, border_mode="valid",
                 input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.pool_size = pool_size
        self.strides = strides or pool_size
        self.padding = border_mode.upper()


class MaxPooling1D(_Pool):
    def __init__(self, pool_length: int = 2, stride=None, border_mode="valid",
                 input_shape=None, name=None):
        super().__init__((pool_length,), (stride or pool_length,),
                         border_mode, input_shape=input_shape, name=name)

    def apply(self, module, args, train):
        return nn.max_pool(args[0], self.pool_size, self.strides, self.padding)


class AveragePooling1D(MaxPooling1D):
    def apply(self, module, args, train):
        return nn.avg_pool(args[0], self.pool_size, self.strides, self.padding)


class MaxPooling2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, border_mode="valid",
                 input_shape=None, name=None):
        super().__init__(_pair(pool_size), _pair(strides or pool_size),
                         border_mode, input_shape=input_shape, name=name)

    def apply(self, module, args, train):
        return nn.max_pool(args[0], self.pool_size, self.strides, self.padding)


class AveragePooling2D(MaxPooling2D):
    def apply(self, module, args, train):
        return nn.avg_pool(args[0], self.pool_size, self.strides, self.padding)


class GlobalMaxPooling1D(KerasLayer):
    def apply(self, module, args, train):
        return jnp.max(args[0], axis=1)


class GlobalAveragePooling1D(KerasLayer):
    def apply(self, module, args, train):
        return jnp.mean(args[0], axis=1)


class GlobalMaxPooling2D(KerasLayer):
    def apply(self, module, args, train):
        return jnp.max(args[0], axis=(1, 2))


class GlobalAveragePooling2D(KerasLayer):
    def apply(self, module, args, train):
        return jnp.mean(args[0], axis=(1, 2))


class ZeroPadding1D(KerasLayer):
    def __init__(self, padding: int = 1, input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.padding = _pair(padding) if not isinstance(padding, int) else (padding, padding)

    def apply(self, module, args, train):
        return jnp.pad(args[0], ((0, 0), self.padding, (0, 0)))


class ZeroPadding2D(KerasLayer):
    def __init__(self, padding=(1, 1), input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.padding = _pair(padding)

    def apply(self, module, args, train):
        p = self.padding
        return jnp.pad(args[0], ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0)))


class UpSampling2D(KerasLayer):
    def __init__(self, size=(2, 2), input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.size = _pair(size)

    def apply(self, module, args, train):
        x = args[0]
        x = jnp.repeat(x, self.size[0], axis=1)
        return jnp.repeat(x, self.size[1], axis=2)


# ---------------- recurrent ----------------

class _RNNBase(KerasLayer):
    cell_cls = None

    def __init__(self, output_dim: int, activation="tanh",
                 return_sequences: bool = False, go_backwards: bool = False,
                 input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.output_dim = output_dim
        self.activation = activation
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards

    def _make_cell(self):
        kwargs = {}
        # activation=None means linear, like every other layer here
        if self.activation != "tanh":
            kwargs["activation_fn"] = get_activation(self.activation)
        return self.cell_cls(features=self.output_dim, **kwargs)

    def make_module(self):
        return nn.RNN(self._make_cell(), reverse=self.go_backwards,
                      name=self.name)

    def apply(self, module, args, train):
        out = module(args[0])
        return out if self.return_sequences else out[:, -1, :]

    def _infer_shape(self, in_shapes):
        s = in_shapes[0]
        if s is None:
            return None
        return (s[0], self.output_dim) if self.return_sequences else (self.output_dim,)


class LSTM(_RNNBase):
    """(ref keras/layers/recurrent LSTM; lowers to lax.scan over an
    OptimizedLSTMCell — XLA fuses the gates into MXU matmuls)."""
    cell_cls = nn.OptimizedLSTMCell


class GRU(_RNNBase):
    cell_cls = nn.GRUCell


class SimpleRNN(_RNNBase):
    cell_cls = nn.SimpleCell


class Bidirectional(KerasLayer):
    """(ref keras Bidirectional wrapper)"""

    def __init__(self, layer: _RNNBase, merge_mode: str = "concat", name=None):
        super().__init__(name)
        self.layer = layer
        self.merge_mode = merge_mode

    def make_module(self):
        inner = self.layer

        class _BiDi(nn.Module):
            @nn.compact
            def __call__(self, x):
                fwd = nn.RNN(inner._make_cell(), name="forward")(x)
                bwd = nn.RNN(inner._make_cell(), reverse=True,
                             keep_order=True, name="backward")(x)
                return fwd, bwd

        return _BiDi(name=self.name)

    def apply(self, module, args, train):
        fwd, bwd = module(args[0])
        if not self.layer.return_sequences:
            fwd, bwd = fwd[:, -1, :], bwd[:, 0, :]
        if self.merge_mode == "concat":
            return jnp.concatenate([fwd, bwd], axis=-1)
        if self.merge_mode == "sum":
            return fwd + bwd
        if self.merge_mode == "mul":
            return fwd * bwd
        if self.merge_mode == "ave":
            return (fwd + bwd) / 2
        raise ValueError(f"bad merge_mode {self.merge_mode}")


# ---------------- attention / transformer ----------------

class MultiHeadAttention(KerasLayer):
    """Dot-product multi-head attention (ref pyzoo self_attention.py /
    Scala TransformerLayer.scala:56). Uses the fused attention op from
    ops/attention.py (pallas flash attention on TPU)."""

    def __init__(self, num_heads: int, head_dim: int, dropout: float = 0.0,
                 causal: bool = False, input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.num_heads, self.head_dim = num_heads, head_dim
        self.dropout, self.causal = dropout, causal

    def make_module(self):
        from analytics_zoo_tpu.ops.attention import AttentionModule
        return AttentionModule(num_heads=self.num_heads,
                               head_dim=self.head_dim,
                               dropout=self.dropout, causal=self.causal,
                               name=self.name)

    def apply(self, module, args, train):
        q = args[0]
        kv = args[1] if len(args) > 1 else q
        mask = args[2] if len(args) > 2 else None
        return module(q, kv, mask=mask, train=train)


# ---------------- merge ----------------

class Merge(KerasLayer):
    """(ref keras/layers Merge mode=sum/mul/concat/ave/dot/max...)"""

    def __init__(self, layers=None, mode: str = "sum", concat_axis: int = -1,
                 input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.mode = mode
        self.concat_axis = concat_axis

    def apply(self, module, args, train):
        m = self.mode
        if m in ("sum", "add"):
            out = args[0]
            for a in args[1:]:
                out = out + a
            return out
        if m == "sub":
            return args[0] - args[1]
        if m == "mul":
            out = args[0]
            for a in args[1:]:
                out = out * a
            return out
        if m == "div":
            return args[0] / args[1]
        if m in ("ave", "avg"):
            return sum(args) / len(args)
        if m == "max":
            return jnp.stack(args).max(0)
        if m == "min":
            return jnp.stack(args).min(0)
        if m == "concat":
            return jnp.concatenate(args, axis=self.concat_axis)
        if m == "dot":
            return jnp.sum(args[0] * args[1], axis=-1, keepdims=True)
        if m == "cos":
            a = args[0] / jnp.linalg.norm(args[0], axis=-1, keepdims=True)
            b = args[1] / jnp.linalg.norm(args[1], axis=-1, keepdims=True)
            return jnp.sum(a * b, axis=-1, keepdims=True)
        raise ValueError(f"unknown merge mode {m!r}")


def merge_op(mode: str, concat_axis: int = -1) -> Merge:
    return Merge(mode=mode, concat_axis=concat_axis)


def merge(inputs: List[Node], mode: str = "sum", concat_axis: int = -1) -> Node:
    """Functional merge (ref pyzoo keras merge())."""
    return Merge(mode=mode, concat_axis=concat_axis)(inputs)


class TimeDistributed(KerasLayer):
    """Apply a layer to every time step (ref keras TimeDistributed)."""

    def __init__(self, layer: KerasLayer, name=None):
        super().__init__(name)
        self.layer = layer

    def make_module(self):
        # a user-chosen inner name is kept (save/load keys on it); only an
        # auto-generated one is replaced to keep the tree deterministic
        if getattr(self.layer, "_auto_named", False):
            self.layer.name = f"{self.name}_inner"
        return self.layer.make_module()

    def apply(self, module, args, train):
        x = args[0]
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        out = self.layer.apply(module, [flat], train)
        return out.reshape((b, t) + out.shape[1:])


class GetShape(KerasLayer):
    def apply(self, module, args, train):
        return jnp.asarray(args[0].shape)


# ---------------- transformer / BERT ----------------

class TransformerLayer(KerasLayer):
    """GPT-style causal transformer over token ids
    (ref zoo/.../keras/layers/TransformerLayer.scala:56). Input: [b, L]
    token ids; output: [b, L, hidden_size]."""

    def __init__(self, vocab: int, hidden_size: int = 768, n_block: int = 12,
                 n_head: int = 12, seq_len: int = 512,
                 hidden_drop: float = 0.1, input_shape=None, name=None):
        super().__init__(name, input_shape)
        self.vocab, self.hidden_size = vocab, hidden_size
        self.n_block, self.n_head = n_block, n_head
        self.seq_len, self.hidden_drop = seq_len, hidden_drop

    def _infer_shape(self, in_shapes):
        s = in_shapes[0]
        return (None if s is None else s[0], self.hidden_size) \
            if s and len(s) == 1 else (s + (self.hidden_size,) if s else None)

    def make_module(self):
        from analytics_zoo_tpu.text.bert import TransformerModule
        return TransformerModule(
            vocab=self.vocab, hidden_size=self.hidden_size,
            n_block=self.n_block, n_head=self.n_head,
            hidden_drop=self.hidden_drop, max_position_len=self.seq_len,
            name=self.name)

    def apply(self, module, args, train):
        return module(args[0], train=train)


class BERT(KerasLayer):
    """BERT encoder layer (ref zoo/.../keras/layers/BERT.scala:66).

    Call on ``[ids]`` or ``[ids, token_types, mask]`` nodes. ``output``:
    ``"pooled"`` (default, [b, hidden]) or ``"sequence"`` ([b, L, hidden]).
    """

    def __init__(self, vocab: int = 30522, hidden_size: int = 768,
                 n_block: int = 12, n_head: int = 12,
                 intermediate_size: int = 3072, max_position_len: int = 512,
                 hidden_drop: float = 0.1, attn_drop: float = 0.1,
                 output: str = "pooled", input_shape=None, name=None):
        super().__init__(name, input_shape)
        from analytics_zoo_tpu.text.bert import BertConfig
        if output not in ("pooled", "sequence"):
            raise ValueError("output must be 'pooled' or 'sequence'")
        self.config = BertConfig(
            vocab=vocab, hidden_size=hidden_size, n_block=n_block,
            n_head=n_head, intermediate_size=intermediate_size,
            max_position_len=max_position_len, hidden_drop=hidden_drop,
            attn_drop=attn_drop)
        self.output = output

    def _infer_shape(self, in_shapes):
        s = in_shapes[0]
        if self.output == "pooled":
            return (self.config.hidden_size,)
        return (None if s is None else s[0], self.config.hidden_size)

    def make_module(self):
        from analytics_zoo_tpu.text.bert import BertModule
        return BertModule(self.config, name=self.name)

    def apply(self, module, args, train):
        ids = args[0]
        seg = args[1] if len(args) > 1 else None
        mask = args[2] if len(args) > 2 else None
        seq, pooled = module(ids, seg, mask, train=train)
        return pooled if self.output == "pooled" else seq
